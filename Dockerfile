# Cordum-TPU control plane image.
#
# One image, six entrypoints: the service is selected with CORDUM_SERVICE
# (statebus | safety-kernel | scheduler | workflow-engine | gateway | worker),
# mirroring the reference's single-binary-per-container layout
# (reference Dockerfile + docker-compose.yml) without six separate builds.
#
# The worker container is the only one that needs a TPU: on GKE it is
# scheduled onto TPU node pools via the manifests in deploy/k8s/ (node
# selectors + google.com/tpu resources); every other service is pure CPU.
FROM python:3.12-slim

# gcc for the native strategy-scan hot loop (built from source at first use;
# binaries are never shipped in the image or the repo)
RUN apt-get update && apt-get install -y --no-install-recommends gcc libc6-dev \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY cordum_tpu/ cordum_tpu/
COPY config/ config/
COPY examples/ examples/

# control-plane deps (jax is only required by the worker image variant; the
# control plane runs without it)
RUN pip install --no-cache-dir aiohttp msgpack pyyaml jsonschema cryptography

# worker variant: docker build --build-arg WITH_TPU=1 ... installs jax for
# the in-tree TPU worker (the TPU runtime/libtpu comes from the node image)
ARG WITH_TPU=0
RUN if [ "$WITH_TPU" = "1" ]; then \
      pip install --no-cache-dir "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html || \
      pip install --no-cache-dir jax; \
    fi

ENV PYTHONUNBUFFERED=1 \
    CORDUM_SERVICE=gateway \
    CORDUM_STATEBUS_URL=statebus://statebus:7420

CMD ["sh", "-c", "python -m cordum_tpu.cmd.$(echo $CORDUM_SERVICE | tr - _)"]
