PY ?= python

.PHONY: test test-fast smoke bench up init dryrun lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x --ignore=tests/test_models.py --ignore=tests/test_moe_pipeline.py --ignore=tests/test_training.py

smoke:
	$(PY) tools/platform_smoke.py

bench:
	$(PY) bench.py

up:
	$(PY) -m cordum_tpu.cli up

init:
	$(PY) -m cordum_tpu.cli init

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); import __graft_entry__ as g; g.dryrun_multichip(8)"
