PY ?= python

.PHONY: test test-fast smoke bench up init dryrun lint

# Static analysis gate: cordumlint always (stdlib-only), ruff + mypy-strict
# when installed (the CI lint job installs both; minimal TPU images may not).
lint:
	$(PY) -m tools.cordumlint cordum_tpu bench.py
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check cordum_tpu tools bench.py; \
	else echo "lint: ruff not installed; skipped (CI enforces it)"; fi
	@if command -v mypy >/dev/null 2>&1; then \
		mypy --strict cordum_tpu/protocol cordum_tpu/infra; \
	else echo "lint: mypy not installed; skipped (CI enforces it)"; fi

lint-baseline:
	$(PY) -m tools.cordumlint cordum_tpu bench.py --write-baseline \
		--justification "$(JUSTIFICATION)"

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q -x --ignore=tests/test_models.py --ignore=tests/test_moe_pipeline.py --ignore=tests/test_training.py

smoke:
	$(PY) tools/platform_smoke.py

bench:
	$(PY) bench.py

up:
	$(PY) -m cordum_tpu.cli up

init:
	$(PY) -m cordum_tpu.cli init

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); import __graft_entry__ as g; g.dryrun_multichip(8)"
