"""Headline benchmark: scheduled jobs/sec end-to-end through the control
plane (BASELINE.json north star: ≥1,000 scheduled TPU jobs/sec on v5p-8).

Four benches, one JSON line:

* ``scheduled_jobs_per_sec`` — burst submit through the real pipeline
  (gateway-role submit → scheduler engine w/ safety check, strategy, state
  machine → worker → result handling) over the in-process bus + KV store.
* ``p50_e2e_ms``/``p99_e2e_ms`` — PACED open-loop submission at a fixed
  offered rate with exact per-job submit→result timing (a burst benchmark
  is queueing-dominated and says nothing about latency).
* ``selections_per_sec`` — worker-selection throughput at 1000 workers
  (reference analogue: 18,234/s, BENCHMARKS.md:131).
* TPU compute: ``embeds_per_sec`` (context-engine embedder) and
  ``model_tokens_per_sec``+``mfu`` (Llama forward).  These run in a
  SUBPROCESS with a hard watchdog: a wedged TPU grant or a crashed PJRT
  client must never take down the control-plane numbers, and any failure
  is reported in ``embed_error``/``model_error`` — never swallowed.  A host
  with no TPU skips cleanly (``{"skipped": "no tpu"}``) and the cpu
  fallback carries the run without fabricating errors.
* Micro-batching: ``batched_embeds_per_sec`` vs ``single_job_embeds_per_sec``
  through the REAL worker path (bus → context fetch → batch queue →
  bucketed XLA flush → result publish); the acceptance bar is ≥3× the
  single-job rate on the same host.

``--smoke`` runs a fast CI-sized pass (small job counts, cpu-only child).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""
from __future__ import annotations

import asyncio
import json
import math
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

N_JOBS = int(os.environ.get("BENCH_JOBS", "3000"))
PACED_JOBS = int(os.environ.get("BENCH_PACED_JOBS", "1500"))
PACED_RATE = float(os.environ.get("BENCH_PACED_RATE", "1000"))  # jobs/s offered
STATEBUS_JOBS = int(os.environ.get("BENCH_STATEBUS_JOBS", "600"))
TELEMETRY_JOBS = int(os.environ.get("BENCH_TELEMETRY_JOBS", "2000"))
SHARDED_JOBS = int(os.environ.get("BENCH_SHARDED_JOBS", "2000"))
SHARDS = int(os.environ.get("BENCH_SHARDS", "4"))
SB_PARTITIONS = int(os.environ.get("BENCH_STATEBUS_PARTITIONS", "2"))
JAX_TIMEOUT_S = float(os.environ.get("BENCH_JAX_TIMEOUT_S", "420"))
# TPU backend discovery gets its own short watchdog: a hung PJRT grant on a
# TPU-less host must become a clean {"skipped": ...} exit 0, not a
# faulthandler rc=1 crash polluting the JSON (BENCH_r04/r05)
TPU_PROBE_TIMEOUT_S = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT_S", "45"))
BASELINE_JOBS_PER_SEC = 1000.0  # BASELINE.json north-star target

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets)
PEAK_FLOPS = {"v5e": 197e12, "v5p": 459e12, "v4": 275e12, "v6e": 918e12}


def _make_stack():
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol.types import Heartbeat

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    kernel = SafetyKernel(
        policy_doc={
            "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
            "rules": [
                {"id": "tpu", "match": {"topics": ["job.tpu.>"]}, "decision": "allow"},
            ],
        }
    )
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}})
    eng = Engine(
        bus=bus, job_store=js, safety=SafetyClient(kernel.check),
        strategy=LeastLoadedStrategy(reg, pc), registry=reg,
    )
    reg.update(Heartbeat(worker_id="bench-w", pool="bench", max_parallel_jobs=1 << 30))
    return kv, bus, js, eng


async def bench_scheduler(telemetry: bool = False,
                          n_jobs: Optional[int] = None,
                          profiling: bool = False) -> dict:
    """Burst throughput: N_JOBS submitted as fast as possible.

    ``telemetry=True`` attaches the full fleet telemetry plane (ISSUE 9) to
    the same loopback stack — a TelemetryExporter on the scheduler registry
    at an aggressive 0.25 s cadence plus the gateway-role FleetAggregator +
    SLOTracker — so interleaved plain/instrumented pairs measure the export
    overhead, and the post-run fleet snapshot is checked for correctness
    (merged counter == the engine registry, SLO burn rate present).

    ``profiling=True`` additionally turns on the ISSUE 10 capacity
    observatory instrumentation: histogram exemplar capture plus a
    per-job CapacityProfiler observation on the worker leg whose block
    rides the telemetry beacon — the instrumented half of the
    ``profiling_overhead_pct`` pairs.  ``profiling=False`` disables
    exemplar capture globally so the plain half really is plain."""
    from cordum_tpu.infra import metrics as metrics_mod
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest, JobResult

    metrics_mod.set_exemplars_enabled(profiling)
    kv, bus, js, eng = _make_stack()
    await eng.start()

    agg = tracker = exporter = capacity = None
    if profiling:
        from cordum_tpu.obs.capacity import CapacityProfiler

        capacity = CapacityProfiler("cpu")
    if telemetry:
        from cordum_tpu.infra.metrics import Metrics
        from cordum_tpu.obs import FleetAggregator, SLOTracker, TelemetryExporter

        agg = FleetAggregator(bus, metrics=Metrics(), fine_step_s=0.5)
        await agg.start()
        tracker = SLOTracker.from_config(
            {"batch": {"job_class": "BATCH", "latency_ms": 1000,
                       "latency_target": 0.95}})

        def health() -> dict:
            doc = {"role": "scheduler",
                   "jobs_scheduled": eng.metrics.jobs_dispatched.total()}
            if capacity is not None:
                doc["capacity"] = capacity.snapshot()
            return doc

        exporter = TelemetryExporter(
            "scheduler", bus, eng.metrics, instance_id="bench-sched-0",
            interval_s=0.25, health_fn=health,
        )
        await exporter.start()

    async def worker_handler(subject, pkt):
        req = pkt.job_request
        if capacity is not None:
            t_h = time.perf_counter()
        await bus.publish(
            subj.RESULT,
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="bench-w"),
                trace_id=pkt.trace_id, sender_id="bench-w", span_id=pkt.span_id,
            ),
        )
        if capacity is not None:
            capacity.observe("bench", device_s=time.perf_counter() - t_h,
                             bucket="-", items=1)

    await bus.subscribe(subj.direct_subject("bench-w"), worker_handler, queue="w")

    jobs_target = N_JOBS if n_jobs is None else n_jobs
    t0 = time.perf_counter()
    for i in range(jobs_target):
        req = JobRequest(job_id=f"bench-{i}", topic="job.bench", tenant_id="default")
        await bus.publish(subj.SUBMIT, BusPacket.wrap(req, sender_id="bench"))
    await bus.drain()
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        await bus.drain()
        if eng.metrics.jobs_completed.value(status="SUCCEEDED") >= jobs_target:
            break
        await asyncio.sleep(0.01)
    dt = time.perf_counter() - t0
    n = eng.metrics.jobs_completed.value(status="SUCCEEDED")
    # per-job KV chatter on the full submit→result loop (the engine binds
    # cordum_kv_roundtrips_total to its store; ISSUE 4 acceptance metric)
    roundtrips = eng.metrics.kv_roundtrips.total()
    out = {
        "jobs": int(n), "seconds": dt,
        "jobs_per_sec": n / dt if dt > 0 else 0.0,
        "kv_roundtrips_per_job": roundtrips / n if n else 0.0,
    }
    if telemetry:
        # flush one final snapshot, then verify the fleet view end to end
        await exporter.publish_once()
        await bus.drain()
        agg.sample()
        doc = agg.fleet_doc(tracker)
        merged = doc["fleet"]["jobs_dispatched_total"]
        engine_total = eng.metrics.jobs_dispatched.total()
        slo = (doc.get("slo") or [{}])[0]
        w5 = (slo.get("windows") or {}).get("5m") or {}
        out["fleet_snapshot_ok"] = float(
            doc["healthy_services"] >= 1
            and merged == engine_total
            and engine_total > 0
            and isinstance(w5.get("burn_rate"), (int, float))
            and w5.get("total", 0) > 0
        )
        out["fleet_services"] = doc["healthy_services"]
        out["slo_burn_rate_5m"] = w5.get("burn_rate", -1.0)
        out["slo_state"] = slo.get("state", "")
        if capacity is not None:
            # capacity observatory correctness: the beacon-shipped profile
            # must come back out of the aggregator as a fresh non-zero
            # throughput-matrix row for the bench op
            cap = agg.capacity_doc()
            rows = [r for r in cap["matrix"]
                    if r["op"] == "bench" and not r["stale"]]
            out["capacity_matrix_ok"] = float(
                bool(rows)
                and rows[0]["items_per_s"] > 0
                and rows[0]["n"] >= jobs_target
                and cap["ops"].get("bench", 0.0) > 0
            )
            out["capacity_ops"] = len(cap["ops"])
        await exporter.stop()
        await agg.stop()
    await eng.stop()
    await bus.close()
    metrics_mod.set_exemplars_enabled(True)  # process-global: don't leak
    return out


async def bench_latency() -> dict:
    """Open-loop paced submission at PACED_RATE jobs/s offered load, exact
    submit→result latency per job (raw list, not a capped histogram), plus a
    per-stage breakdown derived from the flight-recorder spans the pipeline
    publishes on ``sys.trace.span``."""
    from cordum_tpu.obs.tracer import Tracer
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest, JobResult

    kv, bus, js, eng = _make_stack()
    await eng.start()

    done: dict[str, float] = {}
    submitted: dict[str, float] = {}
    all_done = asyncio.Event()
    wtracer = Tracer("worker", bus)

    async def worker_handler(subject, pkt):
        req = pkt.job_request
        async with wtracer.span(
            "execute", trace_id=pkt.trace_id, parent_span_id=pkt.span_id
        ) as sp:
            pass  # zero-work execute: the span bounds result-publish timing
        await bus.publish(
            subj.RESULT,
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="bench-w"),
                trace_id=pkt.trace_id, sender_id="bench-w", span_id=sp.span_id,
            ),
        )

    async def result_tap(subject, pkt):
        res = pkt.job_result
        if res and res.job_id in submitted and res.job_id not in done:
            done[res.job_id] = time.perf_counter() - submitted[res.job_id]
            if len(done) >= PACED_JOBS:
                all_done.set()

    # stage breakdown straight from the span stream (exact durations, no
    # bucketing) — the same data the collector would persist
    stage_samples: dict[str, list[float]] = {}

    async def span_tap(subject, pkt):
        sp = pkt.span
        if sp is not None:
            stage_samples.setdefault(sp.name, []).append(sp.duration_us / 1000.0)

    await bus.subscribe(subj.direct_subject("bench-w"), worker_handler, queue="w")
    await bus.subscribe(subj.RESULT, result_tap)
    await bus.subscribe(subj.TRACE_SPAN, span_tap)

    # pace in 10ms ticks to keep sleep() syscalls off the per-job path
    tick = 0.010
    per_tick = max(1, int(PACED_RATE * tick))
    i = 0
    start = time.perf_counter()
    while i < PACED_JOBS:
        tick_t0 = time.perf_counter()
        for _ in range(min(per_tick, PACED_JOBS - i)):
            jid = f"lat-{i}"
            submitted[jid] = time.perf_counter()
            await bus.publish(
                subj.SUBMIT,
                BusPacket.wrap(JobRequest(job_id=jid, topic="job.bench"), sender_id="bench"),
            )
            i += 1
        # open loop: sleep the REMAINDER of the tick regardless of completions
        rem = tick - (time.perf_counter() - tick_t0)
        if rem > 0:
            await asyncio.sleep(rem)
    try:
        await asyncio.wait_for(all_done.wait(), timeout=60)
    except asyncio.TimeoutError:
        pass
    offered_dt = time.perf_counter() - start
    await eng.stop()
    await bus.close()
    lat = sorted(done.values())
    if not lat:
        return {"paced_completed": 0}

    def q(p: float) -> float:
        return lat[min(len(lat) - 1, int(p * len(lat)))] * 1000

    # per-stage p50s from the span stream (ISSUE stage names → bench keys)
    def stage_p50(name: str) -> float:
        vals = sorted(stage_samples.get(name, []))
        return vals[len(vals) // 2] if vals else 0.0

    stages = {
        "policy": stage_p50("policy-check"),
        "schedule": stage_p50("schedule"),
        "dispatch": stage_p50("dispatch"),
        "execute": stage_p50("execute"),
        "result_publish": stage_p50("result"),
    }
    return {
        "paced_completed": len(lat),
        "paced_offered_rate": PACED_JOBS / offered_dt,
        "p50_e2e_ms": q(0.50),
        "p90_e2e_ms": q(0.90),
        "p99_e2e_ms": q(0.99),
        "stage_p50_ms": {k: round(v, 3) for k, v in stages.items()},
    }


class _PerOpPipelineKV:
    """Bench-only degraded KV: delegates every op to the wrapped StateBusKV
    but downgrades ``pipe_execute`` (the jobstore hot path calls it
    directly) to one wire call PER buffered op, plus a version read per
    watch — the pre-pipelining wire behavior, so the statebus bench can
    report before/after on the same run."""

    def __init__(self, kv):
        self._kv = kv

    def __getattr__(self, name):
        return getattr(self._kv, name)

    async def pipe_execute(self, watches, ops):
        kv = self._kv
        for key, ver in watches.items():
            if await kv.version(key) != ver:
                return False, {}
        for op in ops:
            name, *args = op
            await getattr(kv, name)(*args)
        return True, {k: await kv.version(k) for k in watches}


async def bench_statebus(pipelined: bool, n_jobs: int, *,
                         replicated: bool = False) -> dict:
    """The schedule loop against a REAL TCP StateBusServer (the deployment
    the pipelining work targets): scheduler and worker hold separate
    connections, every KV op is a genuine wire round trip.

    ``replicated`` attaches a replica SUBPROCESS (async ack mode) tailing
    the primary's committed-record stream, so the reported throughput
    carries the full replication cost — frame fan-out on the primary plus
    a competing apply/ack process (ISSUE 8; ceiling in bench_floor.json).
    """
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.infra.statebus import StateBusServer, connect
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, Heartbeat, JobRequest, JobResult

    srv = StateBusServer(port=0)
    await srv.start()
    url = f"statebus://127.0.0.1:{srv.port}"
    replica_child = None
    if replicated:
        rport = _free_ports(1)[0]
        me = os.path.abspath(__file__)
        replica_child = subprocess.Popen(
            [sys.executable, me, "--statebus-child", str(rport), url],
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        deadline = time.monotonic() + 60
        while not srv.repl.sessions:
            if time.monotonic() > deadline:
                replica_child.kill()
                raise TimeoutError("bench replica never attached")
            await asyncio.sleep(0.05)
    skv, sbus, sconn = await connect(url)  # scheduler "process"
    wkv, wbus, wconn = await connect(url)  # worker "process"
    try:
        kv = skv if pipelined else _PerOpPipelineKV(skv)
        js = JobStore(kv)
        kernel = SafetyKernel(
            policy_doc={"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}}
        )
        reg = WorkerRegistry()
        pc = parse_pool_config(
            {"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}}
        )
        eng = Engine(
            bus=sbus, job_store=js, safety=SafetyClient(kernel.check),
            strategy=LeastLoadedStrategy(reg, pc), registry=reg,
        )
        reg.update(Heartbeat(worker_id="bench-w", pool="bench", max_parallel_jobs=1 << 30))
        await eng.start()

        async def worker_handler(subject, pkt):
            req = pkt.job_request
            await wbus.publish(
                subj.RESULT,
                BusPacket.wrap(
                    JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="bench-w"),
                    sender_id="bench-w",
                ),
            )

        await wbus.subscribe(subj.direct_subject("bench-w"), worker_handler, queue="w")

        t0 = time.perf_counter()
        for i in range(n_jobs):
            await sbus.publish(
                subj.SUBMIT,
                BusPacket.wrap(
                    JobRequest(job_id=f"sb-{i}", topic="job.bench", tenant_id="default"),
                    sender_id="bench",
                ),
            )
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:
            if eng.metrics.jobs_completed.value(status="SUCCEEDED") >= n_jobs:
                break
            await asyncio.sleep(0.01)
        dt = time.perf_counter() - t0
        n = eng.metrics.jobs_completed.value(status="SUCCEEDED")
        roundtrips = eng.metrics.kv_roundtrips.total()
        await eng.stop()
        out = {
            "jobs": int(n),
            "jobs_per_sec": n / dt if dt > 0 else 0.0,
            "kv_roundtrips_per_job": roundtrips / n if n else 0.0,
        }
        if replicated:
            # end-of-run lag: how far the replica trails when the burst ends
            # (async mode's loss window if the primary died right now)
            out["repl_lag_ops_end"] = max(
                (srv.repl.offset - s.acked_offset
                 for s in srv.repl.sessions.values()), default=-1)
        return out
    finally:
        await sconn.close()
        await wconn.close()
        await srv.stop()
        if replica_child is not None:
            replica_child.terminate()
            try:
                replica_child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                replica_child.kill()


def bench_telemetry(pairs: int = 5) -> dict:
    """Fleet telemetry export cost + snapshot correctness (ISSUE 9).

    Interleaved (plain, instrumented) scheduler-burst pairs at the FULL
    telemetry job count (smoke-sized runs finish in ~0.1 s, putting startup
    noise in the same decade as the effect — the replication-overhead
    lesson), after one discarded warmup pair; the instrumented runs carry an
    exporter at 4 Hz plus the live aggregator/SLO tracker on the same loop.
    Reports the MEDIAN same-run overhead pct (ceiling-gated ≤5% in
    bench_floor.json) and the ``fleet_snapshot_ok`` flag: the post-run
    merged fleet counter must equal the engine registry and the SLO tracker
    must report a burn rate for the configured class.
    """
    import statistics

    n = TELEMETRY_JOBS
    asyncio.run(bench_scheduler(n_jobs=n))  # warmup: imports + allocator heat
    overheads = []
    last = {}
    for _ in range(pairs):
        plain = asyncio.run(bench_scheduler(n_jobs=n))
        instr = asyncio.run(bench_scheduler(telemetry=True, n_jobs=n))
        last = instr
        if plain["jobs_per_sec"]:
            overheads.append(
                100.0 * (1.0 - instr["jobs_per_sec"] / plain["jobs_per_sec"]))
    return {
        "telemetry_overhead_pct": round(
            statistics.median(overheads), 1) if overheads else 100.0,
        "telemetry_overhead_runs": [round(o, 1) for o in overheads],
        "fleet_snapshot_ok": last.get("fleet_snapshot_ok", 0.0),
        "fleet_services": last.get("fleet_services", 0),
        "slo_burn_rate_5m": last.get("slo_burn_rate_5m", -1.0),
        "slo_state": last.get("slo_state", ""),
    }


def bench_profiling(pairs: int = 5) -> dict:
    """Capacity-observatory instrumentation cost + matrix correctness
    (ISSUE 10), same harness as ``bench_telemetry``.

    Interleaved (telemetry, telemetry+profiling) scheduler-burst pairs at
    the full telemetry job count — both halves carry the exporter/
    aggregator, so the ratio isolates the PROFILER itself (per-job
    CapacityProfiler observation + histogram exemplar capture + the
    capacity block riding each beacon) from the already-gated export cost.
    Reports the MEDIAN overhead pct (ceiling-gated ≤5% in bench_floor.json)
    and ``capacity_matrix_ok``: the instrumented run's aggregator must
    reproduce the bench op as a fresh non-zero throughput-matrix row.
    """
    import statistics

    n = TELEMETRY_JOBS
    asyncio.run(bench_scheduler(telemetry=True, n_jobs=n, profiling=True))  # warmup
    overheads = []
    last = {}
    for _ in range(pairs):
        base = asyncio.run(bench_scheduler(telemetry=True, n_jobs=n))
        instr = asyncio.run(
            bench_scheduler(telemetry=True, n_jobs=n, profiling=True))
        last = instr
        if base["jobs_per_sec"]:
            overheads.append(
                100.0 * (1.0 - instr["jobs_per_sec"] / base["jobs_per_sec"]))
    return {
        "profiling_overhead_pct": round(
            statistics.median(overheads), 1) if overheads else 100.0,
        "profiling_overhead_runs": [round(o, 1) for o in overheads],
        "capacity_matrix_ok": last.get("capacity_matrix_ok", 0.0),
        "capacity_ops": last.get("capacity_ops", 0),
    }


def bench_replication_overhead(pairs: int = 5) -> dict:
    """Async-replication cost on the statebus schedule loop (ISSUE 8).

    Runs ``pairs`` interleaved (plain, replicated) pipelined runs at the
    FULL statebus job count — short smoke-sized runs put startup noise in
    the same decade as the effect — and reports the MEDIAN same-run
    overhead ratio, so one scheduler hiccup on a shared 1-2 core CI runner
    can't fake (or mask) a regression.  The replica is a real subprocess
    tailing the primary's committed-record stream with async acks.
    """
    import statistics

    overheads, plain_rates, repl_rates, lag_end = [], [], [], 0
    for _ in range(pairs):
        plain = asyncio.run(bench_statebus(True, STATEBUS_JOBS))
        repl = asyncio.run(bench_statebus(True, STATEBUS_JOBS, replicated=True))
        plain_rates.append(plain["jobs_per_sec"])
        repl_rates.append(repl["jobs_per_sec"])
        lag_end = max(lag_end, repl.get("repl_lag_ops_end", -1))
        if plain["jobs_per_sec"]:
            overheads.append(
                100.0 * (1.0 - repl["jobs_per_sec"] / plain["jobs_per_sec"]))
    return {
        "statebus_replicated_jobs_per_sec": round(
            statistics.median(repl_rates), 1) if repl_rates else 0.0,
        "statebus_replication_overhead_pct": round(
            statistics.median(overheads), 1) if overheads else 100.0,
        "statebus_replication_overhead_runs": [round(o, 1) for o in overheads],
        "statebus_replication_lag_ops_end": lag_end,
    }


# ---------------------------------------------------------------------------
# sharded mode (ISSUE 5): S scheduler-shard PROCESSES over P statebus
# partition PROCESSES — the real multi-process control plane, keyspace-
# partitioned end to end (gateway-role submit stamps sys.job.submit.<p>,
# each shard owns its jobs' full lifecycle, workers echo the partition on
# results).  Child modes: `--statebus-child <port>` / `--shard-child i n urls`.
# ---------------------------------------------------------------------------


def _free_ports(n: int) -> list[int]:
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


async def _wait_for_stop() -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()


def _statebus_child(port: int, replica_of: str = "") -> None:
    """One statebus partition server process (optionally a replica tailing
    ``replica_of`` — the --replicated bench topology)."""
    async def run() -> None:
        from cordum_tpu.infra.statebus import StateBusServer

        srv = StateBusServer(port=port, replica_of=replica_of,
                             auto_promote=False)
        await srv.start()
        await _wait_for_stop()
        await srv.stop()

    asyncio.run(run())


def _shard_child(index: int, count: int, urls: str) -> None:
    """One scheduler shard process: engine shard `index` of `count` over the
    partitioned statebus; reports completion counts through the KV so the
    parent can observe end-to-end progress without sharing a process."""
    async def run() -> None:
        from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
        from cordum_tpu.controlplane.scheduler.engine import Engine
        from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
        from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
        from cordum_tpu.infra.config import parse_pool_config
        from cordum_tpu.infra.jobstore import JobStore
        from cordum_tpu.infra.registry import WorkerRegistry
        from cordum_tpu.infra.statebus import connect_partitioned
        from cordum_tpu.protocol.types import Heartbeat

        kv, bus, grp = await connect_partitioned(urls)
        kernel = SafetyKernel(
            policy_doc={"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}}
        )
        reg = WorkerRegistry()
        pc = parse_pool_config(
            {"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}}
        )
        eng = Engine(
            bus=bus, job_store=JobStore(kv), safety=SafetyClient(kernel.check),
            strategy=LeastLoadedStrategy(reg, pc), registry=reg,
            instance_id=f"bench-shard-{index}", shard_index=index, shard_count=count,
        )
        # seed the local load view so the first dispatch cannot race the
        # parent's first heartbeat (heartbeats keep refreshing it after)
        reg.update(Heartbeat(worker_id="bench-w", pool="bench", max_parallel_jobs=1 << 30))
        await eng.start()
        await kv.set(f"bench:shard_ready:{index}", b"1")

        done = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, done.set)

        async def report() -> None:
            while not done.is_set():
                n = int(eng.metrics.jobs_completed.value(status="SUCCEEDED"))
                await kv.set(f"bench:done:{index}", str(n).encode())
                await asyncio.sleep(0.1)

        rep = asyncio.ensure_future(report())
        await done.wait()
        rep.cancel()
        try:  # best-effort final flush — the servers may already be gone
            n = int(eng.metrics.jobs_completed.value(status="SUCCEEDED"))
            await asyncio.wait_for(kv.set(f"bench:done:{index}", str(n).encode()), 2.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass  # parent already read the periodic reports; flush is advisory
        await eng.stop()
        await grp.close()

    asyncio.run(run())


async def bench_sharded(shards: int, partitions: int, n_jobs: int) -> dict:
    """Keyspace-sharded schedule loop: `shards` engine processes ×
    `partitions` statebus server processes, submits stamped to
    ``sys.job.submit.<p>``, one worker role in the parent."""
    from cordum_tpu.infra.statebus import connect_partitioned
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, Heartbeat, JobRequest, JobResult, LABEL_PARTITION,
    )

    me = os.path.abspath(__file__)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ports = _free_ports(partitions)
    urls = ",".join(f"statebus://127.0.0.1:{p}" for p in ports)
    procs = [
        subprocess.Popen([sys.executable, me, "--statebus-child", str(p)],
                         env=env, cwd=os.path.dirname(me))
        for p in ports
    ]
    kv = bus = grp = None
    hb_task = None
    shard_procs: list[subprocess.Popen] = []
    try:
        deadline = time.monotonic() + 30
        while True:  # servers up? (connect_partitioned dials every endpoint)
            try:
                kv, bus, grp = await connect_partitioned(urls)
                break
            except (OSError, ConnectionError):
                if time.monotonic() > deadline:
                    raise
                await asyncio.sleep(0.1)
        shard_procs = [
            subprocess.Popen(
                [sys.executable, me, "--shard-child", str(i), str(shards), urls],
                env=env, cwd=os.path.dirname(me))
            for i in range(shards)
        ]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:  # every shard subscribed?
            flags = await asyncio.gather(
                *(kv.get(f"bench:shard_ready:{i}") for i in range(shards))
            )
            if all(flags):
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("scheduler shards never became ready")

        hb = Heartbeat(worker_id="bench-w", pool="bench", max_parallel_jobs=1 << 30)

        async def heartbeats() -> None:
            while True:
                await bus.publish(subj.HEARTBEAT, BusPacket.wrap(hb, sender_id="bench-w"))
                await asyncio.sleep(1.0)

        hb_task = asyncio.ensure_future(heartbeats())

        submitted: dict[str, float] = {}
        done: dict[str, float] = {}
        all_done = asyncio.Event()

        async def worker_handler(subject, pkt):
            req = pkt.job_request
            # echo the owning shard's partition stamp → result routes
            # straight to sys.job.result.<p>, no forwarding hop
            await bus.publish(
                subj.stamped_result_subject((req.labels or {}).get(LABEL_PARTITION, "")),
                BusPacket.wrap(
                    JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="bench-w"),
                    sender_id="bench-w",
                ),
            )

        async def result_tap(subject, pkt):
            res = pkt.job_result
            if res and res.job_id in submitted and res.job_id not in done:
                done[res.job_id] = time.perf_counter() - submitted[res.job_id]
                if len(done) >= n_jobs:
                    all_done.set()

        await bus.subscribe(subj.direct_subject("bench-w"), worker_handler, queue="w")
        await bus.subscribe(subj.RESULT, result_tap)
        await bus.subscribe(f"{subj.RESULT}.>", result_tap)

        t0 = time.perf_counter()
        for i in range(n_jobs):
            jid = f"sh-{i}"
            submitted[jid] = time.perf_counter()
            await bus.publish(
                subj.submit_subject_for(jid, shards),
                BusPacket.wrap(
                    JobRequest(job_id=jid, topic="job.bench", tenant_id="default"),
                    sender_id="bench",
                ),
            )
        try:
            await asyncio.wait_for(all_done.wait(), timeout=120)
        except asyncio.TimeoutError:
            pass
        dt = time.perf_counter() - t0

        # the shards' own terminal commits (reported through the KV): proves
        # every shard drove its partition's jobs to a terminal state
        terminal = 0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            vals = await asyncio.gather(
                *(kv.get(f"bench:done:{i}") for i in range(shards))
            )
            terminal = sum(int(v or b"0") for v in vals)
            if terminal >= n_jobs:
                break
            await asyncio.sleep(0.1)
        lat = sorted(done.values())
        return {
            "shards": shards,
            "statebus_partitions": partitions,
            "jobs": len(done),
            "jobs_per_sec": len(done) / dt if dt > 0 else 0.0,
            "p50_e2e_ms": (lat[len(lat) // 2] * 1000) if lat else 0.0,
            "terminal_total": terminal,
        }
    finally:
        if hb_task:
            hb_task.cancel()
        if grp is not None:
            await grp.close()  # before SIGTERM: no reconnect-warn churn
        # shards first (their shutdown flushes through the servers), then
        # the statebus partitions
        for batch in (shard_procs, procs):
            for p in batch:
                p.send_signal(signal.SIGTERM)
            for p in batch:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def bench_profile() -> dict:
    """Per-layer timing breakdown (``--profile``; also emitted by --smoke):
    microbenchmarks of the four layers the 1×1 hot path decomposes into —
    routing, codec, selection, commit — so a future throughput regression
    is attributable to a layer straight from the JSON artifact (ISSUE 6).
    All numbers are microseconds per operation."""
    import random

    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.codec import pack_record, unpack_record
    from cordum_tpu.infra.jobstore import JobStore, MetaSnapshot
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.infra.statebus import PartitionedKV
    from cordum_tpu.protocol.partition import partition_of
    from cordum_tpu.protocol.types import (
        BusPacket, Heartbeat, JobRequest, JobState,
    )

    def us_per(fn, n: int) -> float:
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e6

    out: dict = {}

    # routing: keyspace hash + the 1×1 identity collapse
    out["routing_partition_of_us"] = round(
        us_per(lambda: partition_of("job-abcdef-123456", 8), 20000), 3)
    out["routing_unsharded_collapsed"] = PartitionedKV([MemoryKV()]).__class__ is MemoryKV

    # codec: envelope encode/decode, lazy payload, cached re-encode, records
    req = JobRequest(job_id="prof-1", topic="job.bench", tenant_id="default",
                     labels={"k": "v"}, env={"A": "B"})
    out["codec_encode_us"] = round(
        us_per(lambda: BusPacket.wrap(req, sender_id="prof").to_wire(), 5000), 3)
    wire = BusPacket.wrap(req, sender_id="prof").to_wire()
    out["codec_decode_envelope_us"] = round(
        us_per(lambda: BusPacket.from_wire(wire), 5000), 3)
    out["codec_decode_payload_us"] = round(
        us_per(lambda: BusPacket.from_wire(wire).job_request, 5000), 3)
    out["codec_reencode_cached_us"] = round(
        us_per(lambda: BusPacket.from_wire(wire).to_wire(), 5000), 3)
    rec = {"ts_us": 1, "state": JobState.RUNNING.value,
           "prev": JobState.DISPATCHED.value, "event": "running"}
    packed = pack_record(rec)
    out["codec_record_pack_us"] = round(us_per(lambda: pack_record(rec), 20000), 3)
    out["codec_record_unpack_us"] = round(
        us_per(lambda: unpack_record(packed), 20000), 3)

    # selection: the strategy pick (native scan when available)
    rng = random.Random(9)
    reg = WorkerRegistry()
    for i in range(100):
        reg.update(Heartbeat(worker_id=f"w{i:03d}", pool="bench",
                             active_jobs=rng.randint(0, 4), max_parallel_jobs=16))
    pc = parse_pool_config(
        {"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}})
    strat = LeastLoadedStrategy(reg, pc)
    sreq = JobRequest(job_id="prof", topic="job.bench")
    out["selection_pick_us"] = round(us_per(lambda: strat.pick_subject(sreq), 10000), 3)

    # commit: a grouped pipelined transition chain on MemoryKV
    kv = MemoryKV()
    js = JobStore(kv)
    ops, _, _ = js.build_chain_ops(
        "prof-job", MetaSnapshot(), [(JobState.PENDING, {"topic": "job.bench"}, "submit")]
    )

    async def commit_loop(n: int) -> float:
        await kv.pipe_execute({}, ops)  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            await kv.pipe_execute({}, ops)
        return (time.perf_counter() - t0) / n * 1e6

    out["commit_pipe_us"] = round(asyncio.run(commit_loop(5000)), 3)
    return out


def bench_selection() -> dict:
    """Worker-selection throughput at 1000 workers (reference analogue:
    18,234 selections/s, BENCHMARKS.md:131)."""
    import random

    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol.types import Heartbeat, JobRequest

    rng = random.Random(9)
    reg = WorkerRegistry()
    for i in range(1000):
        reg.update(Heartbeat(
            worker_id=f"w{i:05d}", pool="tpu", capabilities=["tpu"],
            chip_count=rng.choice([1, 4, 8]), active_jobs=rng.randint(0, 12),
            max_parallel_jobs=16, cpu_load=rng.uniform(0, 100),
            tpu_duty_cycle=rng.uniform(0, 100),
        ))
    pc = parse_pool_config({"topics": {"job.tpu.work": "tpu"}, "pools": {"tpu": {"requires": ["tpu"]}}})
    strat = LeastLoadedStrategy(reg, pc)
    req = JobRequest(job_id="j", topic="job.tpu.work")
    strat.pick_subject(req)
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        strat.pick_subject(req)
    dt = time.perf_counter() - t0
    return {"selections_per_sec": n / dt, "native": strat._packed is not None}


# ---------------------------------------------------------------------------
# Gang scheduling bench (ISSUE 15, docs/GANG.md) — run via
# `python bench.py --gang-child [smoke]` in a subprocess that forces an
# 8-device CPU host platform BEFORE jax initializes (the MULTICHIP mesh).
# The child drives an in-process fleet through the REAL
# submit → reserve → rendezvous → step → result pipeline:
#   * a burst of barrier-only gangs measures the control-plane gang rate
#     (gang_jobs_per_sec);
#   * the three MULTICHIP dryrun flows (dense dp×tp×sp, moe dp×tp×ep,
#     MPMD pipeline dp×pp with one stage per worker) run as scheduled
#     gang jobs (gang_flows_ok + per-flow losses);
#   * gang spans (reserve/rendezvous/step/release) must land in the trace
#     stream (gang_spans_ok) and cordum_gang_* metrics in the fleet
#     exposition (gang_metrics_ok);
#   * gang_partial_reservations re-reads the ledger invariant counter
#     (ceiling 0 in bench_floor.json).
# ---------------------------------------------------------------------------


def _gang_child(smoke: bool) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import faulthandler

    faulthandler.dump_traceback_later(max(60.0, JAX_TIMEOUT_S), exit=True)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # cordumlint: disable=CL002 -- older jax without the config key; env var governs
        pass
    print(json.dumps(asyncio.run(_bench_gang(smoke))))


async def _bench_gang(smoke: bool) -> dict:
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.gang import GangScheduler
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.obs import FleetAggregator, TelemetryExporter
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, JobRequest, LABEL_GANG_WORKERS,
    )
    from cordum_tpu.worker.gang import GangRunner
    from cordum_tpu.worker.runtime import Worker
    from cordum_tpu.worker.training import TrainRunner

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}})
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.gang": "gangpool"},
                            "pools": {"gangpool": {}}})
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc), registry=reg)
    gangs = GangScheduler(eng, pc, rendezvous_timeout_s=10.0,
                          watch_interval_s=0.05)
    await eng.start()
    await gangs.start()
    spans: list = []

    async def collect_span(subject, pkt):
        spans.append(pkt.payload)

    await bus.subscribe(subj.TRACE_SPAN, collect_span)
    agg = FleetAggregator(bus, metrics=Metrics(), fine_step_s=0.5)
    await agg.start()
    exporter = TelemetryExporter(
        "scheduler", bus, eng.metrics, instance_id="gang-sched",
        interval_s=0.5,
        health_fn=lambda: {"role": "scheduler", "gangs": gangs.doc(),
                           "gang_queue_depth": len(gangs._fifo)},
    )
    store = MemoryStore(kv)
    workers = []
    for i in range(4):
        w = Worker(bus=bus, store=store, worker_id=f"gw{i}", pool="gangpool",
                   heartbeat_interval_s=0.5)
        w.attach_gang(GangRunner(
            w, trainer=TrainRunner(), rendezvous_timeout_s=10.0,
            peer_timeout_s=60.0, beacon_interval_s=0.05,
        ), metrics=eng.metrics)
        await w.start()
        workers.append(w)
    await asyncio.sleep(0.1)

    out: dict = {}

    async def submit(job_id: str, payload: dict, n_workers: int) -> None:
        ptr = await store.put_context(job_id, payload)
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=job_id, topic="job.gang", tenant_id="default",
                       context_ptr=ptr,
                       labels={LABEL_GANG_WORKERS: str(n_workers)}),
            sender_id="bench"))

    async def wait_done(job_ids, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        states = {}
        while time.monotonic() < deadline:
            states = {j: await js.get_state(j) for j in job_ids}
            if all(s in ("SUCCEEDED", "FAILED", "DENIED", "CANCELLED")
                   for s in states.values()):
                break
            await asyncio.sleep(0.05)
        return states

    try:
        # -- 1. control-plane gang rate: barrier-only gangs of 2 over 4
        # workers (two gangs run concurrently; the rest queue FIFO)
        n_echo = 8 if smoke else 20
        t0 = time.perf_counter()
        for i in range(n_echo):
            await submit(f"ge-{i}", {"op": "gang_echo"}, 2)
        states = await wait_done([f"ge-{i}" for i in range(n_echo)], 120.0)
        elapsed = time.perf_counter() - t0
        ok = sum(1 for s in states.values() if s == "SUCCEEDED")
        out["gang_echo_gangs"] = ok
        out["gang_jobs_per_sec"] = round(ok / elapsed, 2) if elapsed else 0.0
        if ok < n_echo:
            out["gang_error"] = f"echo gangs: {states}"

        # -- 2. the three MULTICHIP dryrun flows as scheduled gang jobs
        flows = {
            "dense": {"op": "train", "model": "llama-tiny", "steps": 1,
                      "batch": 4, "seq": 16, "mesh": {"tp": 2, "sp": 2},
                      "gang": {"workers": 2}},
            "moe": {"op": "train", "model": "moe", "steps": 1,
                    "batch": 4, "seq": 16, "mesh": {"tp": 2, "ep": 2},
                    "gang": {"workers": 2}},
            "pipeline": {"op": "train", "model": "pipeline", "steps": 1,
                         "batch": 4, "seq": 16, "microbatches": 2,
                         "mesh": {"dp": -1, "pp": 2},
                         "gang": {"workers": 2}},
        }
        flows_ok = 1.0
        for name, payload in flows.items():
            await submit(f"gf-{name}", payload, 2)
            states = await wait_done([f"gf-{name}"], 600.0)
            if states.get(f"gf-{name}") != "SUCCEEDED":
                flows_ok = 0.0
                out["gang_error"] = (
                    out.get("gang_error", "")
                    + f" flow {name}: {states.get(f'gf-{name}')}"
                ).strip()
                continue
            res = await store.get_result(f"gf-{name}")
            loss = res.get("loss")
            out[f"gang_{name}_loss"] = loss
            out[f"gang_{name}_mode"] = res.get("mode")
            if loss is None or not math.isfinite(float(loss)):
                flows_ok = 0.0
                out["gang_error"] = (
                    out.get("gang_error", "") + f" flow {name}: loss={loss}"
                ).strip()
        out["gang_flows_ok"] = flows_ok

        # -- 3. gang spans in the trace stream (the waterfall's source)
        for _ in range(20):
            await bus.drain()
            await asyncio.sleep(0.01)
        names = {sp.name for sp in spans}
        want = {"gang-reserve", "gang-dispatch", "gang-rendezvous",
                "gang-step", "gang-release"}
        out["gang_spans_ok"] = 1.0 if want <= names else 0.0
        if want - names:
            out["gang_error"] = (
                out.get("gang_error", "")
                + f" missing spans: {sorted(want - names)}"
            ).strip()

        # -- 4. cordum_gang_* metrics in the fleet exposition
        await exporter.publish_once()
        await bus.drain()
        text = agg.render()
        out["gang_metrics_ok"] = 1.0 if (
            "cordum_gang_admissions_total" in text
            and "cordum_gang_rendezvous_seconds" in text
        ) else 0.0
        gdoc = agg.gangs_doc()
        out["gang_table_rows"] = len(gdoc.get("gangs") or [])

        # -- 5. the all-or-nothing invariant counter (ceiling 0)
        gangs.ledger.verify()
        out["gang_partial_reservations"] = (
            eng.metrics.gang_partial_reservations.total())
        out.setdefault("gang_error", "")
    finally:
        await exporter.stop()
        await agg.stop()
        await gangs.stop()
        await eng.stop()
        for w in workers:
            await w.stop()
        await bus.close()
    return out


_GANG_KEYS = (
    "gang_jobs_per_sec", "gang_echo_gangs", "gang_flows_ok",
    "gang_dense_loss", "gang_dense_mode", "gang_moe_loss", "gang_moe_mode",
    "gang_pipeline_loss", "gang_pipeline_mode", "gang_spans_ok",
    "gang_metrics_ok", "gang_table_rows", "gang_partial_reservations",
    "gang_error",
)


def bench_gang(*, smoke: bool = False) -> dict:
    """Run the gang bench in a child process (it must force the 8-device
    CPU host platform before jax initializes; the parent may already hold
    an initialized single-device backend)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--gang-child"]
            + (["smoke"] if smoke else []),
            capture_output=True, text=True, timeout=max(600.0, JAX_TIMEOUT_S),
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        line = (proc.stdout.strip().splitlines() or [""])[-1]
        child = json.loads(line) if line.startswith("{") else {}
        if not child:
            tail = (proc.stderr or proc.stdout or "")[-600:]
            return {"gang_error": f"gang child rc={proc.returncode}: {tail}",
                    "gang_jobs_per_sec": 0.0, "gang_flows_ok": 0.0,
                    "gang_partial_reservations": 0.0}
        return {k: child[k] for k in _GANG_KEYS if k in child}
    except subprocess.TimeoutExpired:
        return {"gang_error": "gang child timed out",
                "gang_jobs_per_sec": 0.0, "gang_flows_ok": 0.0,
                "gang_partial_reservations": 0.0}
    except Exception as ex:  # noqa: BLE001 - bench must report, not crash
        return {"gang_error": f"{type(ex).__name__}: {ex}"[:300],
                "gang_jobs_per_sec": 0.0, "gang_flows_ok": 0.0,
                "gang_partial_reservations": 0.0}


# ---------------------------------------------------------------------------
# sharded serving gangs (bench.py --tp): the SAME session set served by a
# TP=2 in-process serving gang (docs/SERVING.md §Sharded serving) vs a
# single-rank worker, same model, same process tree.  The contract metrics
# are exact: tp_token_identity (TP is a placement change, not a math
# change — the gang's streams must equal the single-rank fp32 run token
# for token), tp_compile_per_rank (every rank compiles exactly ONE ragged
# program), and tp_speedup as the same-run wall ratio.  On a 1-2 core CI
# host both gang ranks time-share the only core, so the observed ratio
# sits near 0.5 — the bench_floor.json floor is a COLLAPSE guard (a gang
# that serializes rank steps or recompiles per rank lands far below it);
# the real >=1.5x bar needs one chip per rank (see ROADMAP).
# ---------------------------------------------------------------------------

_TP_KEYS = (
    "tp_tokens_per_sec", "tp_single_tokens_per_sec", "tp_speedup",
    "tp_token_identity", "tp_compile_per_rank", "tp_single_compile_count",
    "tp_ranks", "tp_sessions", "tp_new_tokens", "tp_error",
)


def _tp_child(smoke: bool) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import faulthandler

    faulthandler.dump_traceback_later(max(60.0, JAX_TIMEOUT_S), exit=True)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # cordumlint: disable=CL002 -- older jax without the config key; env var governs
        pass
    print(json.dumps(asyncio.run(_bench_tp(smoke))))


async def _bench_tp(smoke: bool) -> dict:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend
    from cordum_tpu.serving.engine import GenRequest, ServingEngine
    from cordum_tpu.serving.shard import ServingGangGroup

    async def run_blocking(fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    cfg = dataclasses.replace(llama.LlamaConfig.tiny(), dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    sessions = 4 if smoke else 8
    n_new = 8 if smoke else 24
    prompts = [
        [(7 * i + 3 * j + 1) % cfg.vocab_size for j in range(9 + i % 4)]
        for i in range(sessions)
    ]

    async def serve(backend) -> tuple[list[list[int]], float]:
        # prefix cache off: the oracle run must prefill every prompt in
        # full, same as the gang's replayed entry stream
        eng = ServingEngine(backend, run_blocking=run_blocking,
                            max_new_tokens_cap=max(64, n_new),
                            prefix_cache=False)
        t0 = time.perf_counter()
        outs = await asyncio.gather(*[
            eng.submit(GenRequest(prompt=p, max_new_tokens=n_new,
                                  stream=False), job_id=f"tp-{i}")
            for i, p in enumerate(prompts)
        ])
        dt = time.perf_counter() - t0
        await eng.stop()
        return [o["tokens"] for o in outs], (sessions * n_new) / max(dt, 1e-9)

    single = LlamaServingBackend(cfg, num_pages=96, page_size=8,
                                 params_provider=lambda: params)
    gang = ServingGangGroup(cfg, tp=2, num_pages=96, page_size=8,
                            params_provider=lambda: params)
    toks_single, rate_single = await serve(single)
    toks_gang, rate_gang = await serve(gang)
    return {
        "tp_ranks": 2,
        "tp_sessions": sessions,
        "tp_new_tokens": sessions * n_new,
        "tp_tokens_per_sec": round(rate_gang, 1),
        "tp_single_tokens_per_sec": round(rate_single, 1),
        "tp_speedup": round(rate_gang / rate_single, 3) if rate_single else 0.0,
        "tp_token_identity": 1 if toks_gang == toks_single else 0,
        "tp_compile_per_rank": max(gang.compiled_per_rank()),
        "tp_single_compile_count": single.compiled_programs(),
        "tp_error": "",
    }


def bench_tp(*, smoke: bool = False) -> dict:
    """Run the TP serving bench in a child process (it must force the
    8-device CPU host platform before jax initializes; the parent may
    already hold an initialized single-device backend)."""
    fail = {"tp_tokens_per_sec": 0.0, "tp_speedup": 0.0,
            "tp_token_identity": 0.0, "tp_compile_per_rank": 99.0}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--tp-child"]
            + (["smoke"] if smoke else []),
            capture_output=True, text=True, timeout=max(600.0, JAX_TIMEOUT_S),
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        line = (proc.stdout.strip().splitlines() or [""])[-1]
        child = json.loads(line) if line.startswith("{") else {}
        if not child:
            tail = (proc.stderr or proc.stdout or "")[-600:]
            return {**fail, "tp_error": f"tp child rc={proc.returncode}: {tail}"}
        return {k: child[k] for k in _TP_KEYS if k in child}
    except subprocess.TimeoutExpired:
        return {**fail, "tp_error": "tp child timed out"}
    except Exception as ex:  # noqa: BLE001 - bench must report, not crash
        return {**fail, "tp_error": f"{type(ex).__name__}: {ex}"[:300]}


# ---------------------------------------------------------------------------
# TPU compute benches — run via `python bench.py --jax-child [tpu|cpu]` in a
# subprocess so a wedged TPU grant / crashed PJRT client can't hang the
# control-plane benches. The child prints ONE json line.
# ---------------------------------------------------------------------------


def _jax_child(device: str) -> None:
    import faulthandler
    import threading

    # watchdog: if the PJRT client wedges (e.g. TPU grant never arrives),
    # die with a traceback instead of hanging the driver
    faulthandler.dump_traceback_later(max(30.0, JAX_TIMEOUT_S - 30.0), exit=True)
    if device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    out: dict = {}

    # Backend-discovery watchdog (the BENCH_r04/r05 `child rc=1` fix): on
    # hosts where libtpu is installed but no TPU is grantable, jax.devices()
    # HANGS instead of raising — and it hangs inside C init WITHOUT releasing
    # the GIL, so an in-process watchdog thread (the original PR-5 fix) never
    # gets to run.  The tpu probe therefore runs in a THROWAWAY GRANDCHILD
    # process this child can kill from outside the GIL: a probe that doesn't
    # finish inside TPU_PROBE_TIMEOUT_S, crashes, or reports a non-tpu
    # backend is a clean skip (exit 0, {"skipped": ...}).  Only a probe that
    # confirms a real TPU lets this process touch jax at all.
    if device == "tpu":
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, json; print(json.dumps(jax.devices()[0].platform))"],
                capture_output=True, text=True, timeout=TPU_PROBE_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            print(json.dumps({"skipped": "no tpu",
                              "detail": "backend probe timed out after "
                                        f"{TPU_PROBE_TIMEOUT_S:.0f}s (TPU grant unavailable?)"}),
                  flush=True)
            return
        platform = ""
        if probe.returncode == 0:
            lines = [ln for ln in probe.stdout.strip().splitlines() if ln]
            try:
                platform = json.loads(lines[-1]) if lines else ""
            except ValueError:
                platform = ""
        if platform != "tpu":
            detail = (f"jax backend is {platform!r}" if probe.returncode == 0
                      else f"probe rc={probe.returncode}: {(probe.stderr or '')[-200:]}")
            print(json.dumps({"skipped": "no tpu", "detail": detail}), flush=True)
            return

    # second line of defense: a probe-confirmed backend that still wedges in
    # THIS process trips the event-based watchdog (kept for the case where
    # the grant vanishes between probe and init — here the hang does release
    # the GIL once real compilation work is underway)
    probe_done = threading.Event()

    def _probe_watchdog() -> None:
        if probe_done.wait(TPU_PROBE_TIMEOUT_S):
            return
        if device == "tpu":
            print(json.dumps({"skipped": "no tpu",
                              "detail": "backend init timed out after "
                                        f"{TPU_PROBE_TIMEOUT_S:.0f}s (TPU grant unavailable?)"}),
                  flush=True)
            os._exit(0)
        faulthandler.dump_traceback()
        os._exit(1)

    threading.Thread(target=_probe_watchdog, daemon=True).start()
    try:
        import jax

        if device == "cpu":
            jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
    except Exception as ex:  # noqa: BLE001 - "no TPU" is an expected outcome
        probe_done.set()
        if device == "tpu":
            # no TPU on this host is not a failure: exit cleanly so the
            # driver falls back to the cpu child without an embed_error
            print(json.dumps({"skipped": "no tpu",
                              "detail": f"{type(ex).__name__}: {ex}"[:300]}),
                  flush=True)
            return
        raise
    probe_done.set()
    dev = devs[0]
    if device == "tpu" and dev.platform != "tpu":
        print(json.dumps({"skipped": "no tpu",
                          "detail": f"jax backend is {dev.platform!r}"}), flush=True)
        return
    out["device"] = dev.device_kind
    peak = 0.0
    for gen, flops in PEAK_FLOPS.items():
        if gen in dev.device_kind.lower().replace(" ", ""):
            peak = flops

    # --- embedder (context-engine path; headline embeds/sec) ---
    try:
        from cordum_tpu.models.embedder import Embedder, EmbedderConfig

        if device == "cpu":  # CPU smoke shape (single-core CI boxes)
            cfg = EmbedderConfig(n_layers=2, d_model=128, max_len=64)
            batch, iters = 32, 2
        else:
            cfg = EmbedderConfig()
            batch, iters = 256, 8
        e = Embedder(cfg, seed=0)
        texts = [f"document {i}: control plane scheduling latency report" for i in range(batch)]
        e.embed(texts)  # warm compile
        t0 = time.perf_counter()
        for _ in range(iters):
            e.embed(texts)
        dt = time.perf_counter() - t0
        out["embeds_per_sec"] = iters * batch / dt
    except Exception as ex:  # noqa: BLE001
        out["embed_error"] = f"{type(ex).__name__}: {ex}"[:300]

    # --- llama forward (tokens/s + MFU) ---
    try:
        from cordum_tpu.models import llama

        if device == "cpu":
            cfg = llama.LlamaConfig(vocab_size=2048, d_model=128, n_layers=2,
                                    n_heads=4, n_kv_heads=2, d_ff=384)
            b, s, iters = 2, 128, 2
        else:
            # matmul-dominated shape that fits a single chip's HBM comfortably
            cfg = llama.LlamaConfig(vocab_size=32000, d_model=2048, n_layers=16,
                                    n_heads=16, n_kv_heads=8, d_ff=7168)
            b, s, iters = 8, 1024, 6
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0, cfg.vocab_size)
        fwd = jax.jit(lambda p, t: llama.forward(p, t, cfg))
        jax.block_until_ready(fwd(params, tokens))  # warm compile
        t0 = time.perf_counter()
        for _ in range(iters):
            y = fwd(params, tokens)
        jax.block_until_ready(y)
        dt = time.perf_counter() - t0
        toks = b * s * iters
        # analytic FLOPs: 2 flops/param/token over every dense matmul weight
        # (embed lookup excluded, lm_head included) + attention score/value
        # matmuls 2*2*S*h*hd per token per layer (causal → /2)
        dense_params = sum(
            x.size for x in jax.tree.leaves(params)
            if hasattr(x, "ndim") and x.ndim == 2
        ) - cfg.vocab_size * cfg.d_model  # embed table
        attn = cfg.n_layers * 2 * 2 * s * cfg.n_heads * cfg.head_dim / 2
        flops_per_tok = 2 * dense_params + attn
        out["model_tokens_per_sec"] = toks / dt
        out["model_params_m"] = round(
            sum(x.size for x in jax.tree.leaves(params) if hasattr(x, "size")) / 1e6, 1)
        out["model_achieved_tflops"] = toks * flops_per_tok / dt / 1e12
        if peak:
            out["mfu"] = round(toks * flops_per_tok / dt / peak, 4)
    except Exception as ex:  # noqa: BLE001
        out["model_error"] = f"{type(ex).__name__}: {ex}"[:300]

    # --- micro-batching: the REAL worker path, single-job vs batched ---
    # (ISSUE 3 acceptance: batched_embeds_per_sec >= 3x the single-job path)
    try:
        out.update(asyncio.run(_bench_worker_embeds(device)))
    except Exception as ex:  # noqa: BLE001
        out["batched_error"] = f"{type(ex).__name__}: {ex}"[:300]

    # --- serving: continuous-batching decode vs sequential per-session ---
    # (ISSUE 7 acceptance: decode_tokens_per_sec >= 2x sequential)
    try:
        out.update(asyncio.run(_bench_worker_serving(device)))
    except Exception as ex:  # noqa: BLE001
        out["serving_error"] = f"{type(ex).__name__}: {ex}"[:300]

    # --- disaggregated prefill/decode serving (ISSUE 14): co-located vs
    # post-prefill hand-off over a 2-worker heterogeneous fleet ---
    try:
        out.update(asyncio.run(_bench_disagg(device)))
    except Exception as ex:  # noqa: BLE001
        out["disagg_error"] = f"{type(ex).__name__}: {ex}"[:300]

    # --- multi-turn chat: prefix-cache TTFT + session tiering (ISSUE 18) ---
    try:
        out.update(asyncio.run(_bench_chat(device)))
    except Exception as ex:  # noqa: BLE001
        out["chat_error"] = f"{type(ex).__name__}: {ex}"[:300]

    # --- self-speculative decoding inside the ragged step (ISSUE 19) ---
    try:
        out.update(asyncio.run(_bench_spec(device)))
    except Exception as ex:  # noqa: BLE001
        out["spec_error"] = f"{type(ex).__name__}: {ex}"[:300]

    print(json.dumps(out), flush=True)


async def _bench_worker_embeds(device: str) -> dict:
    """Drive 1-text embed jobs through a real Worker twice — micro-batcher
    off (one XLA dispatch per job) then on (bucketed coalesced calls) — and
    report both rates.  This is the end-to-end worker path: bus delivery,
    context-pointer fetch, batch queueing, executor dispatch, result publish.
    """
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.models.embedder import EmbedderConfig
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest
    from cordum_tpu.worker.handlers import (
        TPUCompute, make_micro_batcher, make_tpu_handlers,
    )
    from cordum_tpu.worker.runtime import Worker

    if device == "cpu":
        cfg = EmbedderConfig(n_layers=2, d_model=128, max_len=64)
        n_jobs = 96
    else:
        cfg = EmbedderConfig()
        n_jobs = 512
    text = "control plane scheduling latency report for document"

    async def run_pass(batched: bool) -> dict:
        kv = MemoryKV()
        bus = LoopbackBus()
        ms = MemoryStore(kv)
        worker = Worker(bus=bus, store=ms, worker_id="bench-w",
                        pool="bench", heartbeat_interval_s=999)
        compute = TPUCompute(tp=1, embedder_cfg=cfg)
        worker.register_default(make_tpu_handlers(compute))
        if batched:
            worker.attach_batcher(make_micro_batcher(
                compute, worker, max_batch_rows=32, max_wait_ms=5.0))
        await worker.start()
        # warm the XLA programs both paths will hit so the timed loop
        # measures dispatch, not compilation
        compute.embedder.embed([text])
        compute.embed_batch([text] * 32, seq_len=16)
        compute.embed_batch([text], seq_len=16)

        done = asyncio.Event()
        seen = set()

        async def tap(subject, pkt):
            res = pkt.job_result
            if res is not None and res.status == "SUCCEEDED":
                seen.add(res.job_id)
                if len(seen) >= n_jobs:
                    done.set()

        sub = await bus.subscribe(subj.RESULT, tap)
        prefix = "b" if batched else "s"
        ptrs = []
        for i in range(n_jobs):
            jid = f"{prefix}{i}"
            ptrs.append((jid, await ms.put_context(jid, {"op": "embed", "texts": [text]})))
        t0 = time.perf_counter()
        for jid, ptr in ptrs:
            await bus.publish(
                subj.direct_subject("bench-w"),
                BusPacket.wrap(JobRequest(job_id=jid, topic="job.tpu.embed",
                                          context_ptr=ptr)),
            )
        await asyncio.wait_for(done.wait(), timeout=JAX_TIMEOUT_S / 2)
        dt = time.perf_counter() - t0
        stats = worker.batcher.stats if worker.batcher else None
        sub.unsubscribe()
        await worker.stop()
        await bus.close()
        return {
            "embeds_per_sec": n_jobs / dt if dt > 0 else 0.0,
            "flushes": stats.flushes if stats else 0,
            "max_batch": stats.max_batch_rows_seen if stats else 0,
        }

    single = await run_pass(False)
    batched = await run_pass(True)
    return {
        "single_job_embeds_per_sec": round(single["embeds_per_sec"], 1),
        "batched_embeds_per_sec": round(batched["embeds_per_sec"], 1),
        "batched_speedup": round(
            batched["embeds_per_sec"] / single["embeds_per_sec"], 2
        ) if single["embeds_per_sec"] else 0.0,
        "batch_flushes": batched["flushes"],
        "max_batch_rows": batched["max_batch"],
    }


async def _bench_worker_serving(device: str) -> dict:
    """Multi-session ``llm.generate`` decode through a real Worker twice —
    sequential (one session at a time: the no-continuous-batching baseline)
    then open-loop (every session submitted at once, ragged continuous
    batching) — reporting decode token rates, p50/p99 inter-token latency,
    mean step occupancy, and the TOTAL XLA program count of the run
    (ISSUE 11: the ragged mixed prefill+decode entry point compiles exactly
    once — the bucketed backend paid one program per prompt-length bucket
    plus one per pow2 decode-batch bucket for the same session mix)."""
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.models import llama
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest
    from cordum_tpu.worker.handlers import (
        TPUCompute, make_serving_engine, make_tpu_handlers,
    )
    from cordum_tpu.worker.runtime import Worker

    if device == "cpu":
        lcfg = llama.LlamaConfig.tiny()
        n_sessions, max_new = 12, 40
    else:
        lcfg = llama.LlamaConfig(vocab_size=32000, d_model=1024, n_layers=8,
                                 n_heads=8, n_kv_heads=4, d_ff=3584,
                                 max_seq_len=512)
        n_sessions, max_new = 32, 64
    prompt_len, page_size = 8, 16
    pages_per = -(-(prompt_len + max_new) // page_size)
    cache_pages = n_sessions * pages_per + 8  # +null page +slack

    async def run_pass(concurrent: bool) -> dict:
        bus = LoopbackBus()
        ms = MemoryStore(MemoryKV())
        worker = Worker(bus=bus, store=ms, worker_id="bench-s",
                        pool="bench", heartbeat_interval_s=999)
        compute = TPUCompute(tp=1, llama_cfg=lcfg)
        worker.register_default(make_tpu_handlers(compute))
        worker.attach_serving(make_serving_engine(
            compute, worker, cache_pages=cache_pages, page_size=page_size,
            # the baseline pass admits ONE session at a time: the decode
            # loop degenerates to per-session autoregression (what the
            # fleet does without continuous batching)
            max_sessions=n_sessions if concurrent else 1,
            max_new_tokens=max_new,
        ))
        await worker.start()
        be = worker.serving.backend
        # warm the XLA program: ONE call — the single ragged entry point is
        # every program there is (any prefill-chunk/decode mix reuses it),
        # so the timed window measures steady-state steps.  The bucketed
        # backend needed the whole prefill-bucket + pow2-batch ladder here.
        warm = [1, 2, 3]
        be.prefill(list(range(2, prompt_len + 2)), warm)
        waiters = {f"{'c' if concurrent else 'q'}{i}": asyncio.Event()
                   for i in range(n_sessions)}

        async def tap(subject, pkt):
            res = pkt.job_result
            if res is not None and res.job_id in waiters:
                assert res.status == "SUCCEEDED", (res.job_id, res.status, res.error_message)
                waiters[res.job_id].set()

        sub = await bus.subscribe(subj.RESULT, tap)
        reqs = []
        for i, jid in enumerate(waiters):
            ptr = await ms.put_context(jid, {
                "op": "llm.generate",
                "tokens": [(i * 7 + j) % lcfg.vocab_size for j in range(prompt_len)],
                "max_new_tokens": max_new, "session_id": f"conv-{i}",
                "stream": False,
            })
            reqs.append((jid, ptr))
        # both passes are open-loop (all sessions offered upfront); the
        # baseline's max_sessions=1 admission is what serializes it, so the
        # comparison isolates continuous batching itself
        t0 = time.perf_counter()
        for jid, ptr in reqs:
            await bus.publish(
                subj.direct_subject("bench-s"),
                BusPacket.wrap(JobRequest(job_id=jid, topic="job.tpu.generate",
                                          context_ptr=ptr)),
            )
        await asyncio.wait_for(
            asyncio.gather(*(w.wait() for w in waiters.values())),
            timeout=JAX_TIMEOUT_S / 2,
        )
        dt = time.perf_counter() - t0
        st = worker.serving.stats
        steps = sorted(st.step_seconds)
        ttfts = sorted(st.ttft_seconds)
        sub.unsubscribe()
        await worker.stop()
        await bus.close()
        return {
            "tokens_per_sec": st.decoded_tokens / dt if dt > 0 else 0.0,
            # prompt-ingestion rate, reported separately from decode so
            # disaggregation gains are attributable (ISSUE 14)
            "prefill_tokens_per_sec": st.prefill_tokens / dt if dt > 0 else 0.0,
            "p50_step_ms": (steps[len(steps) // 2] * 1000.0) if steps else 0.0,
            "p99_step_ms": (
                steps[min(len(steps) - 1, int(len(steps) * 0.99))] * 1000.0
            ) if steps else 0.0,
            "p50_ttft_ms": (ttfts[len(ttfts) // 2] * 1000.0) if ttfts else 0.0,
            "mean_occupancy": st.mean_occupancy,
            "steps": st.steps,
            # total XLA programs this pass compiled (warmup included): the
            # ragged entry point makes this exactly 1 — the gated number
            # behind the "no bucket-recompile cliff" claim
            "compiles": be.compiled_programs(),
        }

    seq = await run_pass(False)
    cont = await run_pass(True)
    out = {
        "decode_tokens_per_sec": round(cont["tokens_per_sec"], 1),
        "prefill_tokens_per_sec": round(cont["prefill_tokens_per_sec"], 1),
        "serving_ttft_p50_ms": round(cont["p50_ttft_ms"], 2),
        "sequential_decode_tokens_per_sec": round(seq["tokens_per_sec"], 1),
        "serving_speedup": round(
            cont["tokens_per_sec"] / seq["tokens_per_sec"], 2
        ) if seq["tokens_per_sec"] else 0.0,
        "p50_inter_token_ms": round(cont["p50_step_ms"], 2),
        "inter_token_p99_ms": round(cont["p99_step_ms"], 2),
        "serving_mean_occupancy": round(cont["mean_occupancy"], 2),
        "serving_steps": cont["steps"],
        "serving_sessions": n_sessions,
        "serving_compile_count": cont["compiles"],
    }
    out.update(await _bench_session_migration())
    return out


async def _bench_session_migration() -> dict:
    """Live KV-page migration pause (ISSUE 12): ping-pong ONE decoding
    session between two warmed paged backends over the real TCP migration
    listener and report the p50 decode pause (freeze → target commit) —
    the only window where the session's tokens stop.  The bulk page phase
    streams while decode continues, so the pause should stay in the
    single-digit-to-tens-of-ms range on any host; bench_floor.json gates a
    collapse of that property."""
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend
    from cordum_tpu.serving.engine import (
        GenRequest, ServingEngine, SessionMigrated,
    )
    from cordum_tpu.serving.migration import MigrationServer, migrate_session

    async def run_blocking(fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    metrics = Metrics()
    lcfg = llama.LlamaConfig.tiny()
    engines, servers = [], []
    done = asyncio.Event()
    final: dict = {}
    for _ in range(2):
        be = LlamaServingBackend(lcfg, num_pages=32, page_size=16)
        be.prefill([1, 2, 3, 4], [1])  # warm: the freeze never waits a compile
        eng = ServingEngine(be, run_blocking=run_blocking,
                            max_new_tokens_cap=1024, metrics=metrics)
        engines.append(eng)

        async def install(meta, state, records, eng=eng):
            req = GenRequest(prompt=meta["prompt"],
                             max_new_tokens=meta["max_new_tokens"],
                             stream=False,
                             resume_tokens=meta["resume_tokens"])
            fut = await eng.install_session(
                req, job_id=meta["job_id"], state=state, records=records)

            def _done(f: "asyncio.Future") -> None:
                if f.cancelled() or isinstance(f.exception(), SessionMigrated):
                    return  # bounced onward; the next owner reports
                if f.exception() is None:
                    final["tokens"] = f.result()
                done.set()

            fut.add_done_callback(_done)

        srv = MigrationServer(install)
        await srv.start()
        servers.append(srv)

    jid = "mig-bench"
    waiter = asyncio.ensure_future(engines[0].submit(
        GenRequest(prompt=[5, 9, 2, 7], max_new_tokens=100, stream=False),
        job_id=jid))
    migrations, src = 0, 0
    while migrations < 6 and not done.is_set():
        eng = engines[src]
        for _ in range(200):
            if eng.describe_session(jid) is not None or done.is_set():
                break
            await asyncio.sleep(0.005)
        if done.is_set() or eng.describe_session(jid) is None:
            break
        await asyncio.sleep(0.03)  # let some pages fill between hops
        tgt = 1 - src
        if await migrate_session(eng, jid, servers[tgt].host,
                                 servers[tgt].port, metrics=metrics):
            migrations += 1
            src = tgt
        else:
            break
    try:
        await asyncio.wait_for(waiter, timeout=60)
    except SessionMigrated:
        await asyncio.wait_for(done.wait(), timeout=60)
    for eng in engines:
        await eng.stop()
    for srv in servers:
        await srv.stop()
    if migrations < 2:
        raise RuntimeError(f"only {migrations} migrations completed")
    p50_s = metrics.serving_migration_pause.quantile(0.5) or 0.0
    return {
        "migration_pause_p50_ms": round(p50_s * 1000.0, 2),
        "migrations_done": migrations,
    }


async def _bench_chat(device: str) -> dict:
    """Prefix-cache + session-tiering chat serving (ISSUE 18), three legs
    on the real paged backend:

      * **prefix TTFT**: N chat sessions sharing a 48-token system prompt,
        run cold (``prefix_cache=False``) then against a primed cache in a
        fresh engine — the hit pass prefills only the post-divergence
        tokens, so its TTFT p50 must beat the cold pass (the
        ``chat_prefix_ttft_speedup`` floor) while staying token-identical
        (sharing is a placement change, not a math change).
      * **residency**: M conversations with page-sized unique histories on
        a small device arena, hibernated to the host-RAM cold arena by the
        idle sweep between waves — the resident-conversation count must
        exceed what the device arena could hold warm
        (``chat_resident_over_capacity`` floor).
      * **restore**: second turns for a sample of hibernated conversations
        re-warm their cold pages; ``chat_restore_pause_p50_ms`` is the p50
        alloc+scatter pause (ceiling in bench_floor.json)."""
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend
    from cordum_tpu.serving.engine import GenRequest, ServingEngine

    async def run_blocking(fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    if device == "cpu":
        lcfg = llama.LlamaConfig.tiny()
        n_chat, n_resident, n_restore = 6, 24, 4
    else:
        lcfg = llama.LlamaConfig(vocab_size=32000, d_model=1024, n_layers=8,
                                 n_heads=8, n_kv_heads=4, d_ff=3584,
                                 max_seq_len=512)
        n_chat, n_resident, n_restore = 16, 48, 8
    page_size, max_new = 16, 8
    vocab = lcfg.vocab_size
    metrics = Metrics()

    def make_engine(num_pages: int, prefix: bool,
                    hibernate: float = 0.0) -> ServingEngine:
        be = LlamaServingBackend(lcfg, num_pages=num_pages,
                                 page_size=page_size)
        be.prefill([1, 2, 3], [1])  # warm: TTFT never includes the compile
        return ServingEngine(be, run_blocking=run_blocking,
                             max_new_tokens_cap=max_new, prefix_cache=prefix,
                             hibernate_after_s=hibernate, metrics=metrics)

    # --- leg 1: prefix-hit TTFT vs cold, token-identical ---
    system = [((i * 31) % (vocab - 2)) + 1 for i in range(48)]  # 3 full pages
    prompts = [system + [((i * 7 + j) % 97) + 5 for j in range(4)]
               for i in range(n_chat)]
    arena = 8 + n_chat * (-(-(len(prompts[0]) + max_new) // page_size))

    async def run_turns(eng, tag, plist, keyed=True):
        outs = []
        for i, p in enumerate(plist):
            r = await asyncio.wait_for(eng.submit(
                GenRequest(prompt=p, max_new_tokens=max_new, stream=False,
                           session_key=f"{tag}-{i}" if keyed else ""),
                job_id=f"{tag}{i}"), timeout=JAX_TIMEOUT_S / 4)
            outs.append(r["tokens"])
        return outs

    cold_eng = make_engine(arena, prefix=False)
    cold_outs = await run_turns(cold_eng, "cold", prompts)
    cold_ttfts = sorted(cold_eng.stats.ttft_seconds)
    await cold_eng.stop()

    hit_eng = make_engine(arena, prefix=True)
    await run_turns(hit_eng, "prime", [system])  # populate the radix cache
    hit_outs = await run_turns(hit_eng, "hit", prompts)
    hit_ttfts = sorted(list(hit_eng.stats.ttft_seconds)[1:])  # drop the prime
    st = hit_eng.stats
    looked = st.prefix_hits + st.prefix_misses
    hit_rate = st.prefix_hits / looked if looked else 0.0
    identical = int(hit_outs == cold_outs)
    await hit_eng.stop()

    # --- legs 2+3: residency above the device arena + restore pause ---
    # per-conversation history = 2 unique full pages; a 32-page arena holds
    # at most capacity//2 conversations warm, so residency beyond that is
    # hibernation working, not slack
    eng = make_engine(32, prefix=True, hibernate=3600.0)
    capacity_sessions = (32 - 1) // 2
    convo: dict[int, list[int]] = {}
    for i in range(n_resident):
        p = [((i * 131 + j * 17) % (vocab - 2)) + 1 for j in range(36)]
        r = await asyncio.wait_for(eng.submit(
            GenRequest(prompt=p, max_new_tokens=max_new, stream=False,
                       session_key=f"conv-{i}"),
            job_id=f"res{i}"), timeout=JAX_TIMEOUT_S / 4)
        convo[i] = p + r["tokens"]
        if (i + 1) % 6 == 0:  # idle sweep: demote everything to cold
            await eng.tiering.sweep(now=time.monotonic() + 7200.0)
    await eng.tiering.sweep(now=time.monotonic() + 7200.0)
    warm, cold = eng.tiering.tier_counts()
    resident = warm + cold
    for i in range(n_restore):  # turn 2: cold pages re-warm on admission
        p2 = convo[i] + [7]
        await asyncio.wait_for(eng.submit(
            GenRequest(prompt=p2, max_new_tokens=4, stream=False,
                       session_key=f"conv-{i}"),
            job_id=f"res2-{i}"), timeout=JAX_TIMEOUT_S / 4)
    pf = eng.prefix.stats
    restore_p50_s = metrics.serving_hibernate_pause.quantile(0.5) or 0.0
    await eng.stop()

    def p50_ms(vals) -> float:
        return vals[len(vals) // 2] * 1000.0 if vals else 0.0

    cold_p50, hit_p50 = p50_ms(cold_ttfts), p50_ms(hit_ttfts)
    return {
        "chat_ttft_cold_p50_ms": round(cold_p50, 2),
        "chat_ttft_hit_p50_ms": round(hit_p50, 2),
        "chat_prefix_ttft_speedup": round(cold_p50 / hit_p50, 2) if hit_p50 else 0.0,
        "chat_prefix_hit_rate": round(hit_rate, 3),
        "chat_token_identical": identical,
        "chat_sessions": n_chat,
        "chat_resident_sessions": resident,
        "chat_device_session_capacity": capacity_sessions,
        "chat_resident_over_capacity": round(resident / capacity_sessions, 2),
        "chat_hibernated_pages": pf.hibernated_pages,
        "chat_restored_pages": pf.restored_pages,
        "chat_restore_pause_p50_ms": round(restore_p50_s * 1000.0, 2),
    }


async def _bench_spec(device: str) -> dict:
    """Self-speculative decoding inside the ragged step (ISSUE 19): the
    zero-extra-weights n-gram drafter on a templated agent-style workload —
    repeated instruction motifs, the pattern tool-call loops and
    form-filling chains produce — run twice on the real paged backend:
    once speculation-off (the sequential one-token-per-step baseline), once
    speculation-on (draft rows verified as k+1-token prefill-shaped rows).

      * ``spec_decode_speedup``: baseline wall / speculative wall for the
        identical prompt set (floor in bench_floor.json) — static shapes
        make a k+1-token row cost roughly one step, so the speedup tracks
        the mean accepted burst length.
      * ``spec_token_identity``: greedy accept-longest-prefix is a
        schedule change, not a math change — outputs must match the
        baseline token-for-token (floor 1.0, i.e. always).
      * ``spec_compile_count``: draft rows reuse the ONE ragged program
        (prefill-shaped rows already exist); any second program is a
        recompile-cliff regression."""
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend
    from cordum_tpu.serving.engine import GenRequest, ServingEngine

    async def run_blocking(fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    if device == "cpu":
        lcfg = llama.LlamaConfig.tiny()
        n_sessions = 4
    else:
        lcfg = llama.LlamaConfig(vocab_size=32000, d_model=1024, n_layers=8,
                                 n_heads=8, n_kv_heads=4, d_ff=3584,
                                 max_seq_len=512)
        n_sessions = 8
    page_size, max_new, draft_k = 8, 80, 4
    # templated prompts: an 8-token instruction motif repeated 4× plus a
    # per-session suffix — greedy continuations of this seed settle into
    # cycles the n-gram drafter predicts near-perfectly, the same shape as
    # templated agent loops (PAPER.md §workloads)
    motif = [5, 9, 14, 23, 7, 11, 3, 19]
    prompts = [motif * 4 + [i + 1] for i in range(n_sessions)]

    async def run_pass(speculative: bool) -> dict:
        metrics = Metrics()
        be = LlamaServingBackend(lcfg, num_pages=192, page_size=page_size,
                                 max_batch_tokens=64, seed=2, metrics=metrics)
        eng = ServingEngine(be, run_blocking=run_blocking,
                            max_new_tokens_cap=max_new,
                            speculative=speculative, draft_k=draft_k,
                            metrics=metrics)
        # warm the ragged program so neither pass pays compile in its wall
        await asyncio.wait_for(eng.submit(
            GenRequest(prompt=[1, 2, 3], max_new_tokens=2, stream=False),
            job_id="spec-warm"), timeout=JAX_TIMEOUT_S / 4)
        steps0, decoded0 = eng.stats.steps, eng.stats.decoded_tokens
        t0 = time.perf_counter()
        results = await asyncio.gather(*[
            asyncio.wait_for(eng.submit(
                GenRequest(prompt=p, max_new_tokens=max_new, stream=False),
                job_id=f"spec-{int(speculative)}-{i}"),
                timeout=JAX_TIMEOUT_S / 2)
            for i, p in enumerate(prompts)
        ])
        wall = time.perf_counter() - t0
        st = eng.stats
        out = {
            "outs": [r["tokens"] for r in results],
            "wall": wall,
            "steps": st.steps - steps0,
            "decoded": st.decoded_tokens - decoded0,
            "drafted": st.drafted_tokens,
            "accepted": st.accepted_tokens,
            "rolled_back": st.rolled_back_tokens,
            "compiles": be.compiled_programs(),
        }
        await eng.stop()
        return out

    base = await run_pass(False)
    spec = await run_pass(True)
    speedup = base["wall"] / spec["wall"] if spec["wall"] else 0.0
    accept = (spec["accepted"] / spec["drafted"]) if spec["drafted"] else 0.0
    return {
        "spec_decode_speedup": round(speedup, 2),
        "spec_token_identity": int(spec["outs"] == base["outs"]),
        "spec_accept_rate": round(accept, 3),
        "spec_decode_tokens_per_s": round(spec["decoded"] / spec["wall"], 1)
        if spec["wall"] else 0.0,
        "spec_base_tokens_per_s": round(base["decoded"] / base["wall"], 1)
        if base["wall"] else 0.0,
        "spec_steps": spec["steps"],
        "spec_base_steps": base["steps"],
        "spec_drafted_tokens": spec["drafted"],
        "spec_accepted_tokens": spec["accepted"],
        "spec_rolled_back_tokens": spec["rolled_back"],
        "spec_compile_count": spec["compiles"],
        "spec_sessions": n_sessions,
    }


async def _bench_disagg(device: str) -> dict:
    """Disaggregated prefill/decode serving (ISSUE 14): a 2-worker
    in-process fleet — one prefill-biased (large ``serving_prefill_budget``,
    4 concurrent prefill chunks), one decode-biased (budget 4) — under
    mixed long-prompt + streaming load, run twice in the same process:

      * **co-located**: jobs round-robin across both workers, no hand-off
        (every session prefills AND decodes wherever it lands — long
        prompt chunks share ragged steps with streaming decode rows);
      * **disaggregated**: every job routes to the prefill worker (the
        ServingPlacer policy), which live-migrates each session to the
        decode worker once its prompt finishes prefilling.

    Same two workers, same workload — the delta is the deployment policy.
    The measured class is the STREAMING sessions; the long prompts are the
    non-streaming BATCH disturbance.  The ragged entry point's shapes are
    static (ISSUE 11), so a mixed worker pays its prefill budget's flat-
    buffer slots on EVERY decode step forever — the co-location tax is
    structural — while disaggregation's costs (the hand-off blip, the
    ingestion burst) are transient.  The headline is therefore the
    STEADY-STATE stream inter-token p99: gaps from the second half of each
    stream, after the hand-offs and the long-prompt waves have passed —
    the co-located fleet is still paying the mixed-program tax there, the
    decode worker is running the right-sized program.  Also reported:
    stream TTFT p50, long-job completion p50, the full co/disagg ratios,
    and the hand-off migration count (floor-gated: a disaggregated pass
    that never migrates is not disaggregated)."""
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.models import llama
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, JobRequest, STATUS_HINT_STREAM,
    )
    from cordum_tpu.worker.handlers import (
        TPUCompute, make_serving_engine, make_tpu_handlers,
    )
    from cordum_tpu.worker.runtime import Worker

    if device == "cpu":
        # tiny-plus: big enough that flat-buffer slots dominate step cost
        # (T=16 ≈ 21ms vs T=28 ≈ 31ms vs T=60 ≈ 71ms per step measured on
        # the 1-core host — the program-size tax being measured), small
        # enough that two warmed backends fit a CI runner
        lcfg = llama.LlamaConfig(vocab_size=256, d_model=128, n_layers=4,
                                 n_heads=4, n_kv_heads=2, d_ff=256,
                                 max_seq_len=512)
        n_long, n_stream = 4, 6
        long_prompt, long_new = 96, 4
        stream_prompt, stream_new = 8, 192
    else:
        lcfg = llama.LlamaConfig(vocab_size=32000, d_model=1024, n_layers=8,
                                 n_heads=8, n_kv_heads=4, d_ff=3584,
                                 max_seq_len=512)
        n_long, n_stream = 8, 8
        long_prompt, long_new = 256, 8
        stream_prompt, stream_new = 8, 192
    n_jobs = n_long + n_stream
    page_size = 16
    pages_per = -(-(long_prompt + max(long_new, stream_new)) // page_size)
    cache_pages = n_jobs * pages_per + 8  # every session fits either worker

    async def run_pass(disagg: bool) -> dict:
        bus = LoopbackBus()
        ms = MemoryStore(MemoryKV())
        workers = []
        # co-located = the uniform mixed fleet (default prefill budget on
        # both workers, no hand-off); disaggregated = the SAME two workers
        # redeployed as one prefill-biased ingester (budget 48, 4
        # concurrent chunks — affordable precisely because it stops
        # decoding) + one decode-biased generator (budget 4), with every
        # session migrating to the decoder post-prefill
        specs = (
            (("w-pre", "prefill", 48, 4), ("w-dec", "decode", 4, 1))
            if disagg else
            (("w-pre", "mixed", 16, 2), ("w-dec", "mixed", 16, 2))
        )
        for wid, role, budget, prefills in specs:
            w = Worker(bus=bus, store=ms, worker_id=wid, pool="bench",
                       heartbeat_interval_s=999, serving_role=role)
            compute = TPUCompute(tp=1, llama_cfg=lcfg)
            w.register_default(make_tpu_handlers(compute))
            w.attach_serving(make_serving_engine(
                compute, w, cache_pages=cache_pages, page_size=page_size,
                max_sessions=n_jobs,
                max_new_tokens=max(long_new, stream_new),
                max_concurrent_prefills=prefills, prefill_budget=budget))
            await w.start()
            workers.append(w)
        for w in workers:
            # warm the single ragged program so the timed window measures
            # the policy, not XLA compilation
            w.serving.backend.prefill(list(range(2, 10)), [1])
        for w in workers:
            # peers learn each other's migration listener + role + headroom
            await w.send_heartbeat()
        await asyncio.sleep(0)

        submit_at: dict = {}
        ttft: dict = {}
        seen: dict = {}
        last_arrival: dict = {}
        gaps: list = []
        long_done_ms: list = []
        done = asyncio.Event()
        finished = set()

        async def tap_progress(subject, pkt):
            pr = pkt.job_progress
            if pr is None or pr.status_hint != STATUS_HINT_STREAM:
                return
            if pr.job_id not in submit_at or not pr.tokens:
                return
            now = time.perf_counter()
            if pr.offset < seen.get(pr.job_id, 0):
                return  # handover replay of already-streamed tokens
            tok_idx = pr.offset + len(pr.tokens)
            seen[pr.job_id] = tok_idx
            if pr.job_id not in ttft:
                ttft[pr.job_id] = now - submit_at[pr.job_id]
            elif pr.job_id in last_arrival:
                # (token index, gap): the steady-state p99 keeps only the
                # second half of each stream — past the hand-off blip and
                # the long-prompt ingestion window
                gaps.append((tok_idx, now - last_arrival[pr.job_id]))
            last_arrival[pr.job_id] = now

        async def tap_result(subject, pkt):
            res = pkt.job_result
            if res is not None and res.job_id in submit_at:
                assert res.status == "SUCCEEDED", (
                    res.job_id, res.status, res.error_message)
                if res.job_id.endswith("L"):
                    long_done_ms.append(
                        (time.perf_counter() - submit_at[res.job_id]) * 1000.0)
                finished.add(res.job_id)
                if len(finished) >= n_jobs:
                    done.set()

        subs = [await bus.subscribe(subj.PROGRESS, tap_progress),
                await bus.subscribe(subj.RESULT, tap_result)]
        tag = "d" if disagg else "c"

        async def submit(i: int, is_long: bool) -> None:
            jid = f"{tag}{i}{'L' if is_long else 'S'}"
            plen = long_prompt if is_long else stream_prompt
            ptr = await ms.put_context(jid, {
                "op": "llm.generate",
                "tokens": [(i * 13 + j) % lcfg.vocab_size
                           for j in range(plen)],
                "max_new_tokens": long_new if is_long else stream_new,
                "session_id": f"{tag}conv-{i}",
                # streams are the measured latency class; the long-prompt
                # BATCH jobs are the disturbance (no token stream — their
                # cost is step-budget theft, measured via completion time)
                "stream": not is_long,
            })
            # disaggregated: everything routes to the prefill worker (the
            # ServingPlacer policy); co-located: round-robin spread over
            # the uniform fleet
            target = "w-pre" if disagg else ("w-pre", "w-dec")[i % 2]
            submit_at[jid] = time.perf_counter()
            await bus.publish(
                subj.direct_subject(target),
                BusPacket.wrap(JobRequest(
                    job_id=jid, topic="job.tpu.generate", context_ptr=ptr,
                    priority="BATCH" if is_long else "INTERACTIVE",
                )),
            )

        t0 = time.perf_counter()
        for i in range(n_stream):
            await submit(i, False)
        # long-prompt waves land on top of the running streams early: the
        # disturbance (and the hand-offs it triggers) plays out inside the
        # streams' first half, leaving the second half steady-state
        for wave in range(2):
            await asyncio.sleep(0.15)
            for k in range(n_long // 2):
                await submit(n_stream + wave * (n_long // 2) + k, True)
        await asyncio.wait_for(done.wait(), timeout=JAX_TIMEOUT_S / 2)
        dt = time.perf_counter() - t0
        migrations = sum(w.serving.stats.migrated_in for w in workers)
        decoded = sum(w.serving.stats.decoded_tokens for w in workers)
        for s in subs:
            s.unsubscribe()
        for w in workers:
            await w.stop()
        await bus.close()
        ttfts = sorted(ttft.values())
        steady = sorted(g for idx, g in gaps if idx > stream_new // 2)
        longs_sorted = sorted(long_done_ms)
        return {
            "ttft_p50_ms": (ttfts[len(ttfts) // 2] * 1000.0) if ttfts else 0.0,
            "inter_token_p99_ms": (
                steady[min(len(steady) - 1,
                           int(len(steady) * 0.99))] * 1000.0
            ) if steady else 0.0,
            "long_job_p50_ms": (
                longs_sorted[len(longs_sorted) // 2] if longs_sorted else 0.0
            ),
            "migrations": migrations,
            "tokens_per_sec": decoded / dt if dt > 0 else 0.0,
        }

    co = await run_pass(False)
    dis = await run_pass(True)
    return {
        "disagg_ttft_p50_ms": round(dis["ttft_p50_ms"], 2),
        "colocated_ttft_p50_ms": round(co["ttft_p50_ms"], 2),
        "disagg_ttft_gain": round(
            co["ttft_p50_ms"] / dis["ttft_p50_ms"], 2
        ) if dis["ttft_p50_ms"] > 0 else 0.0,
        "disagg_inter_token_p99_ms": round(dis["inter_token_p99_ms"], 2),
        "colocated_inter_token_p99_ms": round(co["inter_token_p99_ms"], 2),
        "disagg_inter_token_gain": round(
            co["inter_token_p99_ms"] / dis["inter_token_p99_ms"], 2
        ) if dis["inter_token_p99_ms"] > 0 else 0.0,
        "disagg_long_job_p50_ms": round(dis["long_job_p50_ms"], 2),
        "colocated_long_job_p50_ms": round(co["long_job_p50_ms"], 2),
        "disagg_migrations_done": dis["migrations"],
        "disagg_decode_tokens_per_sec": round(dis["tokens_per_sec"], 1),
        "colocated_decode_tokens_per_sec": round(co["tokens_per_sec"], 1),
    }


def bench_session_affinity(n_sessions: int = 32, turns: int = 20,
                           workers: int = 4) -> dict:
    """Scheduler-side session-affinity hit rate: interleaved decode turns of
    ``n_sessions`` conversations over a ``workers``-worker pool.  Steady
    state (every turn after a session's first routing) must ride to the
    worker holding the session's KV pages — the ISSUE 7 bar is ≥95%.
    Pure control-plane: no jax, runs in the parent process."""
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol.types import Heartbeat, JobRequest, LABEL_SESSION_KEY

    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.generate": "tpu"},
                            "pools": {"tpu": {}}})
    strat = LeastLoadedStrategy(reg, pc)
    for w in range(workers):
        reg.update(Heartbeat(worker_id=f"w{w}", pool="tpu",
                             max_parallel_jobs=256))
    routed: dict[str, set] = {}
    for turn in range(turns):
        for s in range(n_sessions):
            subject = strat.pick_subject(JobRequest(
                job_id=f"s{s}t{turn}", topic="job.tpu.generate",
                labels={LABEL_SESSION_KEY: f"conv-{s}"},
            ))
            routed.setdefault(f"conv-{s}", set()).add(subject)
    steady = strat.session_affinity_hits + strat.session_affinity_misses
    return {
        "serving_affinity_hit_rate": round(
            strat.session_affinity_hits / steady, 4) if steady else 0.0,
        "serving_affinity_sessions_smeared": sum(
            1 for subs in routed.values() if len(subs) > 1),
    }


async def _storm_pass(*, admission: bool, duration_s: float,
                      settle_s: float = 5.0) -> dict:
    """One storm run: an open-loop multi-tenant generator overdrives a
    two-worker heterogeneous fleet at ~2× its measured capacity through the
    REAL admission→engine→worker pipeline (AdmissionController fed by a
    live FleetAggregator + SLOTracker, ThroughputAwareStrategy over a live
    CapacityView).  ``admission=False`` is the control run: same storm, no
    shedding — proving the controller, not slack, holds interactive p99.

    Latency accounting is censorship-honest: jobs still queued when the
    settle window closes contribute their AGE as a lower-bound latency, so
    a collapsed control run cannot fake a good p99 by never finishing."""
    from cordum_tpu.controlplane.gateway.admission import AdmissionController
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import ThroughputAwareStrategy
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.loadgen import LoadGen, TenantSpec
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.obs import FleetAggregator, SLOTracker, TelemetryExporter
    from cordum_tpu.obs.capacity import CapacityProfiler, CapacityView
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, Heartbeat, JobRequest, JobResult, LABEL_OP,
    )

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
    })
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.storm": "storm"},
                            "pools": {"storm": {"requires": []}}})
    cap_view = CapacityView(stale_after_s=30.0)
    await cap_view.start(bus)
    strategy = ThroughputAwareStrategy(reg, pc, capacity=cap_view)
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=strategy, registry=reg)
    await eng.start()

    # -- two heterogeneous simulated workers (fast 2× the slow one): each
    # runs serial (parallel=1) so the profiler's device-time items/s IS the
    # worker's true service rate and the measured matrix equals capacity
    service_ms = {"w-fast": {"chat": 8.0, "embed": 16.0},
                  "w-slow": {"chat": 16.0, "embed": 32.0}}
    submit_t: dict[str, tuple[float, str]] = {}  # job_id → (t0, class)
    latencies: dict[str, list[float]] = {"INTERACTIVE": [], "BATCH": []}
    completed: dict[str, int] = {"INTERACTIVE": 0, "BATCH": 0}
    exporters = []
    profs: dict[str, CapacityProfiler] = {}
    for wid, services in service_ms.items():
        prof = profs[wid] = CapacityProfiler("cpu", full_every=2)
        sem = asyncio.Semaphore(1)
        reg.update(Heartbeat(worker_id=wid, pool="storm",
                             max_parallel_jobs=1 << 30))

        def make_handler(prof=prof, sem=sem, services=services, wid=wid):
            async def handler(subject, pkt):
                req = pkt.job_request
                if req is None:
                    return
                op = (req.labels or {}).get(LABEL_OP, "chat")
                service_s = services.get(op, 0.01) / 1000.0
                async with sem:
                    await asyncio.sleep(service_s)
                prof.observe(op, device_s=service_s, items=1)
                t0, klass = submit_t.pop(req.job_id, (None, "BATCH"))
                if t0 is not None:
                    latencies[klass].append(time.perf_counter() - t0)
                    completed[klass] += 1
                await bus.publish(subj.RESULT, BusPacket.wrap(
                    JobResult(job_id=req.job_id, status="SUCCEEDED",
                              worker_id=wid),
                    trace_id=pkt.trace_id, sender_id=wid))
            return handler

        await bus.subscribe(subj.direct_subject(wid), make_handler(), queue=wid)
        exporters.append(TelemetryExporter(
            "worker", bus, Metrics(), instance_id=wid, interval_s=0.5,
            health_fn=(lambda prof=prof: {"role": "worker",
                                          "capacity": prof.snapshot()}),
        ))
    # scheduler beacon: the aggregator needs the engine registry (SLO burn
    # sources) and the queue-depth fallback signal
    exporters.append(TelemetryExporter(
        "scheduler", bus, eng.metrics, instance_id="storm-sched",
        interval_s=0.5,
        health_fn=lambda: {"role": "scheduler", "queue_depth": eng._inflight},
    ))
    agg = FleetAggregator(bus, metrics=Metrics(), fine_step_s=0.5)
    await agg.start()
    for ex in exporters:
        await ex.start()
    tracker = SLOTracker.from_config({
        "interactive": {"job_class": "INTERACTIVE", "latency_ms": 500,
                        "latency_target": 0.9},
        "batch": {"job_class": "BATCH", "latency_ms": 5000,
                  "latency_target": 0.5},
    })
    controller = AdmissionController(
        fleet=agg, slo_tracker=tracker,
        config={
            "enabled": admission, "safety_factor": 0.7,
            "queue_depth_limit": 200,
            "tenants": {"default": {"rate_rps": 0, "burst": 0}},
        },
        metrics=Metrics(), bus=bus, instance_id="storm-gw",
    )

    # -- warm the matrix: feed each worker's true per-op service time into
    # its profiler (what a short calibration pass would measure), beacon,
    # fold — so admission starts analytic and routing starts skew-aware
    for wid, services in service_ms.items():
        for op, ms in services.items():
            for _ in range(20):
                profs[wid].observe(op, device_s=ms / 1000.0, items=1)
    for ex in exporters:
        await ex.publish_once()
    await bus.drain()
    controller.refresh()
    capacity_chat = controller._capacity.get("chat", 0.0) / max(
        0.01, controller.safety_factor)  # un-scaled measured items/s

    seq = 0

    async def submit_job(op: str, klass: str) -> str:
        nonlocal seq
        seq += 1
        jid = f"storm-{'a' if admission else 'c'}-{seq}"
        submit_t[jid] = (time.perf_counter(), klass)
        req = JobRequest(job_id=jid, topic="job.storm", priority=klass,
                         tenant_id="default", labels={LABEL_OP: op})
        await bus.publish(subj.SUBMIT, BusPacket.wrap(req, sender_id="storm"))
        return jid

    # -- controller refresh loop (the gateway's _admission_loop equivalent)
    tier_max = 0

    async def refresh_loop() -> None:
        nonlocal tier_max
        while True:
            await asyncio.sleep(0.5)
            controller.refresh()
            tier_max = max(tier_max, controller.tier)
            await controller.publish_pressure()

    refresh_task = asyncio.ensure_future(refresh_loop())

    # -- the storm: offered ≈ 2× measured chat capacity, mixed classes
    offered_rate = 2.0 * max(50.0, capacity_chat)
    shed: dict[str, int] = {"INTERACTIVE": 0, "BATCH": 0}
    offered: dict[str, int] = {"INTERACTIVE": 0, "BATCH": 0}

    async def storm_submit(spec, session_id, turn) -> None:
        klass = spec.job_class
        offered[klass] = offered.get(klass, 0) + 1
        verdict = controller.admit(op=spec.op, job_class=klass,
                                   tenant="default")
        if not verdict.allowed:
            shed[klass] = shed.get(klass, 0) + 1
            return
        await submit_job(spec.op, klass)

    tenants = [
        TenantSpec(name="chat-users", job_class="INTERACTIVE", op="chat",
                   rate_rps=0.08 * offered_rate, session_turns=3,
                   think_time_s=0.2, diurnal_period_s=4.0, diurnal_amp=0.25),
        TenantSpec(name="batch-flood", job_class="BATCH", op="chat",
                   rate_rps=0.72 * offered_rate, burst_factor=2.0,
                   burst_every_s=3.0, burst_len_s=0.5),
        TenantSpec(name="embed-feed", job_class="BATCH", op="embed",
                   rate_rps=0.04 * offered_rate),
    ]
    gen = LoadGen(storm_submit, tenants, duration_s=duration_s)
    t_start = time.perf_counter()
    await gen.run()
    storm_wall = time.perf_counter() - t_start

    # settle: bounded drain, then censor still-queued jobs at their age
    deadline = time.perf_counter() + settle_s
    while time.perf_counter() < deadline and submit_t:
        await bus.drain()
        await asyncio.sleep(0.05)
    now = time.perf_counter()
    for jid, (t0, klass) in submit_t.items():
        latencies[klass].append(now - t0)

    refresh_task.cancel()
    try:
        await refresh_task
    except asyncio.CancelledError:
        pass
    for ex in exporters:
        await ex.stop()
    await agg.stop()
    await eng.stop()
    await cap_view.stop()
    await bus.close()

    def p(q: float, vals: list[float]) -> float:
        if not vals:
            return 0.0
        s = sorted(vals)
        return s[min(len(s) - 1, int(q * (len(s) - 1)))] * 1000.0

    total_shed = shed["INTERACTIVE"] + shed["BATCH"]
    return {
        "interactive_p50_ms": round(p(0.50, latencies["INTERACTIVE"]), 2),
        "interactive_p99_ms": round(p(0.99, latencies["INTERACTIVE"]), 2),
        "batch_p99_ms": round(p(0.99, latencies["BATCH"]), 2),
        "interactive_offered": offered["INTERACTIVE"],
        "interactive_shed": shed["INTERACTIVE"],
        "interactive_shed_rate": round(
            shed["INTERACTIVE"] / offered["INTERACTIVE"], 4
        ) if offered["INTERACTIVE"] else 0.0,
        "batch_offered": offered["BATCH"],
        "batch_shed": shed["BATCH"],
        "batch_shed_share": round(shed["BATCH"] / total_shed, 4)
        if total_shed else 1.0,
        "batch_goodput": round(completed["BATCH"] / storm_wall, 1),
        "interactive_completed": completed["INTERACTIVE"],
        "batch_completed": completed["BATCH"],
        "capacity_measured": round(capacity_chat, 1),
        "offered_rate": round(offered_rate, 1),
        "brownout_tier_max": tier_max,
        "preempt_requested": int(
            eng.metrics.preemptions.value(reason="requested")),
        "unfinished": len(submit_t),
    }


async def bench_storm(smoke: bool = True) -> dict:
    """Multi-tenant storm harness (docs/ADMISSION.md §Storm harness): the
    ISSUE 13 judgment call — at ~2× measured fleet capacity with mixed
    classes, interactive p99 holds and interactive shed ≈ 0 while BATCH
    absorbs the shedding; the admission-disabled control run degrades,
    proving the controller (not slack) holds the line.  Floor keys:
    ``storm_interactive_p99_ms`` (ceiling), ``storm_interactive_shed_rate``
    (ceiling ≈ 0), ``storm_batch_goodput`` (floor),
    ``storm_control_vs_admitted_p99`` (floor > 1)."""
    duration = 6.0 if smoke else 12.0
    admitted = await _storm_pass(admission=True, duration_s=duration)
    control = await _storm_pass(admission=False, duration_s=duration)
    ratio = (
        control["interactive_p99_ms"] / admitted["interactive_p99_ms"]
        if admitted["interactive_p99_ms"] > 0 else 0.0
    )
    return {
        "storm_interactive_p50_ms": admitted["interactive_p50_ms"],
        "storm_interactive_p99_ms": admitted["interactive_p99_ms"],
        "storm_interactive_shed_rate": admitted["interactive_shed_rate"],
        "storm_interactive_offered": admitted["interactive_offered"],
        "storm_interactive_completed": admitted["interactive_completed"],
        "storm_batch_shed_share": admitted["batch_shed_share"],
        "storm_batch_goodput": admitted["batch_goodput"],
        "storm_batch_p99_ms": admitted["batch_p99_ms"],
        "storm_capacity_measured": admitted["capacity_measured"],
        "storm_offered_rate": admitted["offered_rate"],
        "storm_brownout_tier_max": admitted["brownout_tier_max"],
        "storm_preempt_requested": admitted["preempt_requested"],
        "storm_control_interactive_p99_ms": control["interactive_p99_ms"],
        "storm_control_unfinished": control["unfinished"],
        "storm_control_vs_admitted_p99": round(ratio, 2),
    }


async def bench_agents(smoke: bool = True) -> dict:
    """Agent-loop storm (ISSUE 17, docs/WORKFLOWS.md §Storm harness):
    loadgen-driven multi-step agent workflows — llm.generate → context.update
    → context.window (RAG) → llm.generate — through the REAL pipeline:
    gateway-style admission at run start, workflow engine dispatch, scheduler
    session/batch-affinity routing, simulated serving workers that track
    per-session prefill state, context embeds as pool jobs (BusEmbedder),
    and workflow resume via the queue-group result consumer + reconciler.

    The agent-serving invariants under load:
      * ``agents_affinity_hit_rate`` — steady-state generate turns route to
        the worker already holding the session's KV pages;
      * ``agents_reprefills`` — sessions that cold-prefilled on a second
        worker (the no-re-prefill acceptance bar: 0);
      * ``agents_workflow_steps_per_sec`` / ``agents_step_p99_ms`` — the
        control plane's step engine keeps up (floors in bench_floor.json);
      * ``agents_context_embeds_per_sec`` — context embeds ride the real
        worker path as micro-batchable pool jobs."""
    from cordum_tpu.context.service import BusEmbedder, ContextService
    from cordum_tpu.controlplane.gateway.admission import AdmissionController
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine as SchedEngine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.controlplane.workflowengine.service import (
        WorkflowEngineService,
    )
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.loadgen import LoadGen, TenantSpec
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.obs import FleetAggregator
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, Heartbeat, JobResult, LABEL_OP, LABEL_SESSION_KEY,
        LABEL_SLO_CLASS,
    )
    from cordum_tpu.workflow import models as WM
    from cordum_tpu.workflow.engine import Engine as WfEngine
    from cordum_tpu.workflow.models import Workflow
    from cordum_tpu.workflow.store import WorkflowStore

    kv = MemoryKV()
    bus = LoopbackBus()
    mem = MemoryStore(kv)
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc={
        "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
    })
    reg = WorkerRegistry()
    pc = parse_pool_config({
        "topics": {"job.tpu.generate": "tpu", "job.tpu.embed": "tpu"},
        "pools": {"tpu": {}},
    })
    strategy = LeastLoadedStrategy(reg, pc)
    sched = SchedEngine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                        strategy=strategy, registry=reg)
    await sched.start()

    # -- simulated serving workers: per-session prefill state makes cold
    # starts observable — a session's first generate on a worker pays a
    # prefill; any LATER prefill of the same session is a re-prefill (the
    # affinity miss the tentpole forbids)
    n_workers = 3
    session_workers: dict[str, set] = {}
    prefills = [0]
    embedded = [0]
    decode_ms, prefill_ms, embed_ms = 2.0, 8.0, 2.0
    for w in range(n_workers):
        wid = f"agent-w{w}"
        reg.update(Heartbeat(worker_id=wid, pool="tpu",
                             max_parallel_jobs=1 << 30))

        def make_handler(wid=wid):
            async def handler(subject, pkt):
                req = pkt.job_request
                if req is None:
                    return
                t0 = time.perf_counter()
                op = (req.labels or {}).get(LABEL_OP, "")
                if op == "llm.generate":
                    skey = (req.labels or {}).get(LABEL_SESSION_KEY, "")
                    if skey:
                        owners = session_workers.setdefault(skey, set())
                        if wid not in owners:
                            owners.add(wid)
                            prefills[0] += 1
                            await asyncio.sleep(prefill_ms / 1000.0)
                    await asyncio.sleep(decode_ms / 1000.0)
                    out = {"text": f"gen:{req.job_id}", "tokens": 8}
                elif op == "embed":
                    payload = await mem.get_context(req.context_ptr) or {}
                    texts = payload.get("texts") or []
                    await asyncio.sleep(embed_ms / 1000.0)
                    out = {"embeddings": [[0.3] * 8 for _ in texts], "dim": 8}
                    embedded[0] += len(texts)
                else:
                    await asyncio.sleep(0.001)
                    out = {"ok": True}
                ptr = await mem.put_result(req.job_id, out)
                await bus.publish(subj.RESULT, BusPacket.wrap(
                    JobResult(
                        job_id=req.job_id, status="SUCCEEDED",
                        result_ptr=ptr, worker_id=wid,
                        execution_ms=int((time.perf_counter() - t0) * 1000),
                    ),
                    trace_id=pkt.trace_id, sender_id=wid))
            return handler

        await bus.subscribe(subj.direct_subject(wid), make_handler(), queue=wid)

    # -- workflow plane: engine + queue-group result consumer + reconciler,
    # context steps in-engine with embeds dispatched back to the pool
    embedder = BusEmbedder(bus, mem, timeout_s=30.0)
    ctx_svc = ContextService(kv, embedder=embedder)
    wf_store = WorkflowStore(kv)
    wf_metrics = Metrics()
    wf_engine = WfEngine(store=wf_store, bus=bus, mem=mem, metrics=wf_metrics,
                         instance_id="agents-wf", context_svc=ctx_svc)
    wf_svc = WorkflowEngineService(engine=wf_engine, bus=bus, job_store=js,
                                   instance_id="agents-wf",
                                   reconcile_interval_s=0.5)
    await wf_svc.start()

    # gateway-equivalent admission at run start (tier 0 without fleet
    # pressure — the run still pays the controller's book-keeping path)
    controller = AdmissionController(
        fleet=FleetAggregator(bus, metrics=Metrics()),
        config={"enabled": True, "queue_depth_limit": 10_000,
                "tenants": {"default": {"rate_rps": 0, "burst": 0}}},
        metrics=Metrics(), instance_id="agents-gw",
    )

    # the 4-step agent loop: generate → remember (context.update, embeds its
    # note chunk) → window (context.window RAG, embeds the query) → generate
    # with the window output in scope
    await wf_store.put_workflow(Workflow.from_dict({
        "id": "agent-loop",
        "slo_class": "INTERACTIVE",
        "steps": {
            "plan": {"topic": "job.tpu.generate",
                     "input": {"op": "llm.generate",
                               "prompt": "${input.goal}"}},
            "remember": {"topic": "job.tpu.context",
                         "depends_on": ["plan"],
                         "input": {"op": "context.update",
                                   "user_payload": "${input.goal}",
                                   "model_response": "${steps.plan.text}",
                                   "chunks": [{"file_path": "notes",
                                               "content": "${steps.plan.text}"}]}},
            "window": {"topic": "job.tpu.context",
                       "depends_on": ["remember"],
                       "input": {"op": "context.window", "mode": "RAG",
                                 "query": "${input.goal}"}},
            "act": {"topic": "job.tpu.generate",
                    "depends_on": ["window"],
                    "input": {"op": "llm.generate",
                              "prompt": "ctx ${steps.window.message_count}: "
                                        "${steps.plan.text}"}},
        },
    }))

    run_ids: list[str] = []
    shed = [0]

    async def start_agent_turn(spec, session_id, turn) -> None:
        verdict = controller.admit(op="workflow.run",
                                   job_class="INTERACTIVE", tenant="default")
        if not verdict.allowed:
            shed[0] += 1
            return
        run = await wf_engine.start_run(
            "agent-loop", {"goal": f"goal {session_id} t{turn}"},
            org_id="default",
            # every turn of one agent shares the session key (and thus the
            # memory + the serving worker): turn N resumes where N-1 left off
            labels={LABEL_SESSION_KEY: f"agent-{session_id}"},
        )
        run_ids.append(run.run_id)

    duration_s = 3.5 if smoke else 8.0
    rate = 6.0 if smoke else 25.0
    tenants = [TenantSpec(name="agents", job_class="INTERACTIVE",
                          op="llm.generate", rate_rps=rate,
                          session_turns=2, think_time_s=0.3)]
    gen = LoadGen(start_agent_turn, tenants, duration_s=duration_s)
    t_start = time.perf_counter()
    await gen.run()

    # settle: drive the pipeline until every started run is terminal
    deadline = time.perf_counter() + (10.0 if smoke else 20.0)
    terminal = set(WM.RUN_TERMINAL)
    runs = []
    while time.perf_counter() < deadline:
        await bus.drain()
        await wf_engine.drain_context_steps()
        runs = await wf_store.get_runs(run_ids)
        if runs and all(r is not None and r.status in terminal for r in runs):
            break
        await asyncio.sleep(0.05)
    wall = time.perf_counter() - t_start

    await wf_svc.stop()
    await embedder.stop()
    await sched.stop()
    await bus.close()

    step_ms: list[float] = []
    steps_done = 0
    runs_ok = runs_failed = 0
    for r in runs:
        if r is None:
            continue
        if r.status == WM.SUCCEEDED:
            runs_ok += 1
        elif r.status in terminal:
            runs_failed += 1
        for sr in r.steps.values():
            if sr.status == WM.SUCCEEDED:
                steps_done += 1
                if sr.finished_at_us and sr.started_at_us:
                    step_ms.append((sr.finished_at_us - sr.started_at_us) / 1e3)

    def p(q: float, vals: list) -> float:
        if not vals:
            return 0.0
        s = sorted(vals)
        return s[min(len(s) - 1, int(q * (len(s) - 1)))]

    # strategy counters: the first route of a session is "new" (neither hit
    # nor miss), so hits/(hits+misses) IS the steady-state affinity rate
    hits, misses = strategy.session_affinity_hits, strategy.session_affinity_misses
    sessions = len(session_workers)
    steady = hits + misses
    reprefills = sum(len(ws) - 1 for ws in session_workers.values() if len(ws) > 1)
    return {
        "agents_workflow_steps_per_sec": round(steps_done / wall, 1) if wall else 0.0,
        "agents_step_p50_ms": round(p(0.50, step_ms), 2),
        "agents_step_p99_ms": round(p(0.99, step_ms), 2),
        "agents_steps_completed": steps_done,
        "agents_runs_started": len(run_ids),
        "agents_runs_completed": runs_ok,
        "agents_runs_failed": runs_failed,
        "agents_runs_shed": shed[0],
        "agents_sessions": sessions,
        "agents_affinity_hit_rate": round(hits / steady, 4) if steady else 1.0,
        "agents_affinity_hits": hits,
        "agents_affinity_misses": misses,
        "agents_reprefills": reprefills,
        "agents_prefills": prefills[0],
        "agents_context_embeds": embedded[0],
        "agents_context_embeds_per_sec": round(embedded[0] / wall, 1) if wall else 0.0,
        "agents_context_embed_jobs": embedder.jobs_total,
    }


_CHILD_METRIC_KEYS = (
    "embeds_per_sec", "model_tokens_per_sec", "model_achieved_tflops",
    "model_params_m", "single_job_embeds_per_sec", "batched_embeds_per_sec",
    "batched_speedup", "batch_flushes", "max_batch_rows",
    "decode_tokens_per_sec", "sequential_decode_tokens_per_sec",
    "prefill_tokens_per_sec", "serving_ttft_p50_ms",
    "serving_speedup", "p50_inter_token_ms", "inter_token_p99_ms",
    "serving_mean_occupancy", "serving_steps", "serving_sessions",
    "serving_compile_count", "migration_pause_p50_ms", "migrations_done",
    "disagg_ttft_p50_ms", "colocated_ttft_p50_ms", "disagg_ttft_gain",
    "disagg_inter_token_p99_ms", "colocated_inter_token_p99_ms",
    "disagg_inter_token_gain", "disagg_long_job_p50_ms",
    "colocated_long_job_p50_ms", "disagg_migrations_done",
    "disagg_decode_tokens_per_sec", "colocated_decode_tokens_per_sec",
    "chat_ttft_cold_p50_ms", "chat_ttft_hit_p50_ms",
    "chat_prefix_ttft_speedup", "chat_prefix_hit_rate",
    "chat_token_identical", "chat_sessions", "chat_resident_sessions",
    "chat_device_session_capacity", "chat_resident_over_capacity",
    "chat_hibernated_pages", "chat_restored_pages",
    "chat_restore_pause_p50_ms",
    "spec_decode_speedup", "spec_token_identity", "spec_accept_rate",
    "spec_decode_tokens_per_s", "spec_base_tokens_per_s", "spec_steps",
    "spec_base_steps", "spec_drafted_tokens", "spec_accepted_tokens",
    "spec_rolled_back_tokens", "spec_compile_count", "spec_sessions",
)


def bench_jax(*, smoke: bool = False) -> dict:
    """Run the TPU bench child; fall back to a CPU child so the compute path
    is still exercised when the TPU is unavailable (clearly labeled).

    A host without a TPU is NOT a failure: the tpu child exits cleanly with
    ``{"skipped": "no tpu"}`` and the cpu fallback's success clears any
    tpu-pass error (it survives as ``tpu_*_error`` context).  Real child
    failures are never silently degraded into a partial metric: the full
    child traceback rides along in ``child_traceback`` and main() flags the
    run ``degraded`` with a loud stderr warning (CL002 applied to the bench
    harness)."""
    results: dict = {}
    devices = ("cpu",) if smoke else ("tpu", "cpu")
    for device in devices:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--jax-child", device],
                capture_output=True, text=True, timeout=JAX_TIMEOUT_S,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            line = (proc.stdout.strip().splitlines() or [""])[-1]
            child = json.loads(line) if line.startswith("{") else {}
            if not child:
                tail = (proc.stderr or proc.stdout or "")[-300:]
                child = {"embed_error": f"child rc={proc.returncode}: {tail}",
                         "model_error": f"child rc={proc.returncode}"}
            if ("embed_error" in child or "model_error" in child) and proc.stderr:
                # full crash context, not just the one-line summary
                child["child_traceback"] = proc.stderr[-8000:]
        except subprocess.TimeoutExpired as te:
            child = {"embed_error": f"{device} bench timed out after {JAX_TIMEOUT_S}s "
                                    "(TPU grant unavailable?)",
                     "model_error": "timeout"}
            partial = te.stderr.decode(errors="replace") if isinstance(te.stderr, bytes) else (te.stderr or "")
            if partial:
                child["child_traceback"] = partial[-8000:]
        except Exception as ex:  # noqa: BLE001
            child = {"embed_error": f"{type(ex).__name__}: {ex}"[:300]}
        if device == "tpu":
            if child.get("skipped"):
                # no TPU on this host: clean skip, cpu pass carries the run
                results["tpu_skipped"] = str(child.get("detail") or child["skipped"])
                continue
            results = dict(child)
            if all(k in child for k in
                   ("embeds_per_sec", "model_tokens_per_sec",
                    "batched_embeds_per_sec", "decode_tokens_per_sec")):
                return results
            # remember why the TPU pass failed, then try CPU for coverage;
            # only backfill embed_error if the embed bench itself is missing
            # (a model-only failure must not be misattributed)
            if "embeds_per_sec" not in results and "embed_error" not in results:
                results["embed_error"] = results.get("model_error", "unknown")
        else:
            # merge CPU numbers for whichever metric the TPU pass missed
            for k in _CHILD_METRIC_KEYS:
                if k not in results and k in child:
                    results[k] = child[k]
                    results["fallback_device"] = child.get("device", "cpu")
            for k in ("embed_error", "model_error", "batched_error",
                      "serving_error", "disagg_error", "chat_error",
                      "spec_error", "child_traceback"):
                if k not in results and k in child:
                    results[k] = child[k]
            if "device" not in results and "device" in child:
                results["device"] = child["device"]
    # the cpu fallback succeeded for a metric → the tpu-pass error is
    # context, not a failure (the noisy BENCH_r05 embed_error fix)
    for metric, err in (("embeds_per_sec", "embed_error"),
                        ("model_tokens_per_sec", "model_error"),
                        ("batched_embeds_per_sec", "batched_error"),
                        ("decode_tokens_per_sec", "serving_error"),
                        ("disagg_ttft_p50_ms", "disagg_error"),
                        ("chat_prefix_ttft_speedup", "chat_error"),
                        ("spec_decode_speedup", "spec_error")):
        if metric in results and err in results and results.get("fallback_device"):
            results[f"tpu_{err}"] = results.pop(err)
    return results


def main() -> None:
    global N_JOBS, PACED_JOBS, PACED_RATE, JAX_TIMEOUT_S
    # hermetic placement: the bench itself saturates the host, and real
    # loadavg-derived cpu_load would flip its in-process workers to
    # overloaded (breaking the affinity-hit floors it gates on)
    os.environ.setdefault("CORDUM_HOST_LOAD", "0")
    if len(sys.argv) >= 2 and sys.argv[1] == "--jax-child":
        _jax_child(sys.argv[2] if len(sys.argv) > 2 else "tpu")
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--statebus-child":
        _statebus_child(int(sys.argv[2]),
                        sys.argv[3] if len(sys.argv) > 3 else "")
        return
    if "--replicated" in sys.argv:
        # statebus replication overhead mode (ISSUE 8): one JSON line, keys
        # match the full bench's statebus section so bench_floor.json gates
        # both surfaces identically.
        out = {"metric": "statebus_replication_overhead_pct", "unit": "%"}
        out.update(bench_replication_overhead())
        out["value"] = out["statebus_replication_overhead_pct"]
        print(json.dumps(out))
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--shard-child":
        _shard_child(int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--gang-child":
        _gang_child("smoke" in sys.argv[2:])
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--tp-child":
        _tp_child("smoke" in sys.argv[2:])
        return
    if "--tp" in sys.argv:
        # sharded serving gang mode (ISSUE 20): the same session set on a
        # TP=2 in-process gang vs a single-rank worker — token identity,
        # one compiled ragged program per rank, same-run wall ratio.  One
        # JSON line, same tp_* keys as the full bench so bench_floor.json
        # gates both surfaces.
        out = {"metric": "tp_tokens_per_sec", "unit": "tokens/s"}
        out.update(bench_tp(smoke="--smoke" in sys.argv))
        out["value"] = out.get("tp_tokens_per_sec", 0.0)
        print(json.dumps(out))
        return
    if "--gang" in sys.argv:
        # gang-scheduling mode (ISSUE 15): barrier-only gang throughput +
        # the three MULTICHIP dryrun flows (dense/moe/MPMD-pipeline) as
        # scheduled gang jobs through the real submit → reserve →
        # rendezvous → result pipeline.  One JSON line, same gang_* keys
        # as the full bench so bench_floor.json gates both surfaces.
        out = {"metric": "gang_jobs_per_sec", "unit": "gangs/s"}
        out.update(bench_gang(smoke="--smoke" in sys.argv))
        out["value"] = out.get("gang_jobs_per_sec", 0.0)
        print(json.dumps(out))
        return
    if "--storm" in sys.argv:
        # storm-only mode (ISSUE 13): the multi-tenant overload harness —
        # admission on vs the control run.  One JSON line, same storm_*
        # keys as the full bench so bench_floor.json gates both surfaces.
        out = {"metric": "storm_interactive_p99_ms", "unit": "ms"}
        out.update(asyncio.run(bench_storm(smoke="--smoke" in sys.argv)))
        out["value"] = out["storm_interactive_p99_ms"]
        print(json.dumps(out))
        return
    if "--agents" in sys.argv:
        # agent-workflow mode (ISSUE 17): the agent-loop storm — concurrent
        # multi-step workflows with think time through admission → affinity-
        # routed serving → context embeds on the pool → workflow resume.
        # One JSON line, same agents_* keys as the full bench so
        # bench_floor.json gates both surfaces.
        out = {"metric": "agents_workflow_steps_per_sec", "unit": "steps/s"}
        out.update(asyncio.run(bench_agents(smoke="--smoke" in sys.argv)))
        out["value"] = out["agents_workflow_steps_per_sec"]
        print(json.dumps(out))
        return
    if "--serving" in sys.argv:
        # serving-only mode (ISSUE 7): the continuous-batching worker bench
        # (in-process; set JAX_PLATFORMS=cpu off-TPU) + the scheduler
        # session-affinity hit rate.  One JSON line, same keys as the full
        # bench's serving section.
        out = {"metric": "decode_tokens_per_sec"}
        out.update(asyncio.run(_bench_worker_serving(
            "cpu" if os.environ.get("JAX_PLATFORMS", "") == "cpu" else "tpu")))
        out.update(bench_session_affinity())
        out["value"] = out["decode_tokens_per_sec"]
        out["unit"] = "tokens/s"
        print(json.dumps(out))
        return
    if "--chat" in sys.argv:
        # chat mode (ISSUE 18): prefix-cache TTFT speedup + session-tiering
        # residency/restore on the real paged backend.  One JSON line, same
        # chat_* keys as the full bench so bench_floor.json gates both
        # surfaces.
        out = {"metric": "chat_prefix_ttft_speedup", "unit": "x"}
        out.update(asyncio.run(_bench_chat(
            "cpu" if os.environ.get("JAX_PLATFORMS", "") == "cpu" else "tpu")))
        out["value"] = out.get("chat_prefix_ttft_speedup", 0.0)
        print(json.dumps(out))
        return
    if "--spec" in sys.argv:
        # speculative-decoding mode (ISSUE 19): the self-drafted
        # multi-token verification bench — speculation-off vs -on on the
        # identical templated workload, token-identity gated.  One JSON
        # line, same spec_* keys as the full bench so bench_floor.json
        # gates both surfaces.
        out = {"metric": "spec_decode_speedup", "unit": "x"}
        out.update(asyncio.run(_bench_spec(
            "cpu" if os.environ.get("JAX_PLATFORMS", "") == "cpu" else "tpu")))
        out["value"] = out.get("spec_decode_speedup", 0.0)
        print(json.dumps(out))
        return
    if "--disagg" in sys.argv:
        # disaggregation-only mode (ISSUE 14): co-located vs disaggregated
        # prefill/decode over a 2-worker in-process fleet, same run.  One
        # JSON line, same disagg_* keys as the full bench so
        # bench_floor.json gates both surfaces.
        out = {"metric": "disagg_ttft_p50_ms", "unit": "ms"}
        out.update(asyncio.run(_bench_disagg(
            "cpu" if os.environ.get("JAX_PLATFORMS", "") == "cpu" else "tpu")))
        out["value"] = out["disagg_ttft_p50_ms"]
        print(json.dumps(out))
        return
    smoke = "--smoke" in sys.argv
    profile = "--profile" in sys.argv or smoke  # smoke ships the breakdown in CI
    if smoke:
        # CI sanity mode: small sizes, cpu-only compute child, same JSON shape
        N_JOBS = min(N_JOBS, 400)
        PACED_JOBS = min(PACED_JOBS, 200)
        PACED_RATE = min(PACED_RATE, 500.0)
        JAX_TIMEOUT_S = min(JAX_TIMEOUT_S, 240.0)
    sb_jobs = min(STATEBUS_JOBS, 150) if smoke else STATEBUS_JOBS
    # smoke: 2 shards × 2 statebus partitions (the CI topology); full mode
    # defaults to 4 × 2 (the ISSUE 5 acceptance topology)
    shards = min(SHARDS, 2) if smoke else SHARDS
    sh_jobs = min(SHARDED_JOBS, 300) if smoke else SHARDED_JOBS
    sched = asyncio.run(bench_scheduler())
    lat = asyncio.run(bench_latency())
    sb_pipe = asyncio.run(bench_statebus(True, sb_jobs))
    sb_perop = asyncio.run(bench_statebus(False, sb_jobs))
    sb_repl = bench_replication_overhead()
    tele = bench_telemetry()
    capprof = bench_profiling()
    sharded = asyncio.run(bench_sharded(shards, SB_PARTITIONS, sh_jobs))
    sharded_single = asyncio.run(bench_sharded(1, 1, sh_jobs))
    sel = bench_selection()
    prof = bench_profile() if profile else None
    affinity = bench_session_affinity()
    storm = asyncio.run(bench_storm(smoke=smoke))
    agents = asyncio.run(bench_agents(smoke=smoke))
    gang = bench_gang(smoke=smoke)
    tp = bench_tp(smoke=smoke)
    jx = bench_jax(smoke=smoke)
    out = {
        "metric": "scheduled_jobs_per_sec",
        "value": round(sched["jobs_per_sec"], 1),
        "unit": "jobs/s",
        "vs_baseline": round(sched["jobs_per_sec"] / BASELINE_JOBS_PER_SEC, 3),
        "jobs": sched["jobs"],
        # KV round-trip budget (ISSUE 4): submit→result chatter per job
        "kv_roundtrips_per_job": round(sched["kv_roundtrips_per_job"], 1),
        # statebus mode: the same schedule loop over a real TCP statebus,
        # pipelined vs. downgraded-to-per-op-calls on the same run
        "statebus_jobs_per_sec": round(sb_pipe["jobs_per_sec"], 1),
        "statebus_unpipelined_jobs_per_sec": round(sb_perop["jobs_per_sec"], 1),
        "statebus_pipeline_speedup": round(
            sb_pipe["jobs_per_sec"] / sb_perop["jobs_per_sec"], 2
        ) if sb_perop["jobs_per_sec"] else 0.0,
        "statebus_kv_roundtrips_per_job": round(sb_pipe["kv_roundtrips_per_job"], 1),
        "statebus_unpipelined_kv_roundtrips_per_job": round(
            sb_perop["kv_roundtrips_per_job"], 1
        ),
        # replication overhead (ISSUE 8): median over interleaved
        # plain/replicated pairs with a live replica subprocess tailing the
        # primary (async acks); same-run ratios so host speed cancels
        # (ceiling in bench_floor.json)
        **sb_repl,
        # fleet telemetry plane (ISSUE 9): export overhead over interleaved
        # plain/instrumented pairs + post-run fleet-snapshot correctness
        # (merged counter == engine registry, SLO burn rate present);
        # overhead ceiling + fleet_snapshot_ok floor live in bench_floor.json
        **tele,
        # capacity observatory (ISSUE 10): profiler cost over interleaved
        # telemetry/telemetry+profiling pairs + the post-run throughput-
        # matrix correctness flag (profiling_overhead_pct ceiling +
        # capacity_matrix_ok floor live in bench_floor.json)
        **capprof,
        # keyspace-sharded control plane (ISSUE 5): S scheduler-shard
        # processes over P statebus partition processes, vs the same
        # multi-process harness at 1×1
        "sharded_jobs_per_sec": round(sharded["jobs_per_sec"], 1),
        "sharded_p50_e2e_ms": round(sharded["p50_e2e_ms"], 2),
        "sharded_shards": sharded["shards"],
        "sharded_statebus_partitions": sharded["statebus_partitions"],
        "sharded_jobs": sharded["jobs"],
        "sharded_jobs_terminal": sharded["terminal_total"],
        "sharded_single_jobs_per_sec": round(sharded_single["jobs_per_sec"], 1),
        "sharded_single_p50_e2e_ms": round(sharded_single["p50_e2e_ms"], 2),
        "sharded_speedup": round(
            sharded["jobs_per_sec"] / sharded_single["jobs_per_sec"], 2
        ) if sharded_single["jobs_per_sec"] else 0.0,
        "p50_e2e_ms": round(lat.get("p50_e2e_ms", 0.0), 2),
        "p99_e2e_ms": round(lat.get("p99_e2e_ms", 0.0), 2),
        "stage_p50_ms": lat.get("stage_p50_ms", {}),
        "paced_rate_offered": round(lat.get("paced_offered_rate", 0.0), 1),
        "paced_completed": lat.get("paced_completed", 0),
        "selections_per_sec": round(sel["selections_per_sec"], 1),
        "native_scan": sel["native"],
        # TPU compute: always present, errors never swallowed
        "embeds_per_sec": round(jx.get("embeds_per_sec", 0.0), 1),
        "embed_error": jx.get("embed_error", ""),
        "model_tokens_per_sec": round(jx.get("model_tokens_per_sec", 0.0), 1),
        "model_error": jx.get("model_error", ""),
        "mfu": jx.get("mfu", None),
        "model_achieved_tflops": round(jx.get("model_achieved_tflops", 0.0), 2),
        "embed_device": jx.get("device", ""),
        # micro-batching: the real worker path, per-job vs coalesced
        "single_job_embeds_per_sec": jx.get("single_job_embeds_per_sec", 0.0),
        "batched_embeds_per_sec": jx.get("batched_embeds_per_sec", 0.0),
        "batched_speedup": jx.get("batched_speedup", 0.0),
        "batch_flushes": jx.get("batch_flushes", 0),
        "batched_error": jx.get("batched_error", ""),
        # serving (ISSUE 7): continuous-batching decode through the real
        # worker path, vs sequential per-session decode of the same workload
        "decode_tokens_per_sec": jx.get("decode_tokens_per_sec", 0.0),
        "prefill_tokens_per_sec": jx.get("prefill_tokens_per_sec", 0.0),
        "serving_ttft_p50_ms": jx.get("serving_ttft_p50_ms", 0.0),
        "sequential_decode_tokens_per_sec": jx.get(
            "sequential_decode_tokens_per_sec", 0.0),
        "serving_speedup": jx.get("serving_speedup", 0.0),
        "p50_inter_token_ms": jx.get("p50_inter_token_ms", 0.0),
        "inter_token_p99_ms": jx.get("inter_token_p99_ms", 0.0),
        "serving_mean_occupancy": jx.get("serving_mean_occupancy", 0.0),
        "serving_sessions": jx.get("serving_sessions", 0),
        "serving_compile_count": jx.get("serving_compile_count", 0),
        # live KV-page migration (ISSUE 12): decode pause per session hop
        "migration_pause_p50_ms": jx.get("migration_pause_p50_ms", 0.0),
        "migrations_done": jx.get("migrations_done", 0),
        "serving_error": jx.get("serving_error", ""),
        # disaggregated prefill/decode serving (ISSUE 14): co-located vs
        # post-prefill hand-off over a 2-worker heterogeneous fleet, same
        # run — TTFT p50 and inter-token p99 on both sides + the hand-off
        # migration count (collapse guards in bench_floor.json)
        "disagg_ttft_p50_ms": jx.get("disagg_ttft_p50_ms", 0.0),
        "colocated_ttft_p50_ms": jx.get("colocated_ttft_p50_ms", 0.0),
        "disagg_ttft_gain": jx.get("disagg_ttft_gain", 0.0),
        "disagg_inter_token_p99_ms": jx.get("disagg_inter_token_p99_ms", 0.0),
        "colocated_inter_token_p99_ms": jx.get(
            "colocated_inter_token_p99_ms", 0.0),
        "disagg_inter_token_gain": jx.get("disagg_inter_token_gain", 0.0),
        "disagg_long_job_p50_ms": jx.get("disagg_long_job_p50_ms", 0.0),
        "colocated_long_job_p50_ms": jx.get("colocated_long_job_p50_ms", 0.0),
        "disagg_migrations_done": jx.get("disagg_migrations_done", 0),
        "disagg_decode_tokens_per_sec": jx.get(
            "disagg_decode_tokens_per_sec", 0.0),
        "colocated_decode_tokens_per_sec": jx.get(
            "colocated_decode_tokens_per_sec", 0.0),
        "disagg_error": jx.get("disagg_error", ""),
        # prefix cache + session tiering (ISSUE 18): multi-turn chat over a
        # shared system prompt — prefix-hit TTFT vs cold (same-run ratio,
        # token-identical), resident conversations held above the device
        # arena via hibernation, and the cold→warm restore pause (speedup/
        # residency floors + restore-pause ceiling in bench_floor.json)
        "chat_ttft_cold_p50_ms": jx.get("chat_ttft_cold_p50_ms", 0.0),
        "chat_ttft_hit_p50_ms": jx.get("chat_ttft_hit_p50_ms", 0.0),
        "chat_prefix_ttft_speedup": jx.get("chat_prefix_ttft_speedup", 0.0),
        "chat_prefix_hit_rate": jx.get("chat_prefix_hit_rate", 0.0),
        "chat_token_identical": jx.get("chat_token_identical", 0),
        "chat_sessions": jx.get("chat_sessions", 0),
        "chat_resident_sessions": jx.get("chat_resident_sessions", 0),
        "chat_device_session_capacity": jx.get(
            "chat_device_session_capacity", 0),
        "chat_resident_over_capacity": jx.get(
            "chat_resident_over_capacity", 0.0),
        "chat_hibernated_pages": jx.get("chat_hibernated_pages", 0),
        "chat_restored_pages": jx.get("chat_restored_pages", 0),
        "chat_restore_pause_p50_ms": jx.get("chat_restore_pause_p50_ms", 0.0),
        "chat_error": jx.get("chat_error", ""),
        # self-speculative decoding (ISSUE 19): n-gram drafts verified as
        # k+1-token rows inside the ONE ragged program — wall speedup on
        # the templated workload vs the same prompts speculation-off,
        # token-identity gated (speedup + identity floors and the
        # compile-count ceiling live in bench_floor.json)
        "spec_decode_speedup": jx.get("spec_decode_speedup", 0.0),
        "spec_token_identity": jx.get("spec_token_identity", 0),
        "spec_accept_rate": jx.get("spec_accept_rate", 0.0),
        "spec_decode_tokens_per_s": jx.get("spec_decode_tokens_per_s", 0.0),
        "spec_base_tokens_per_s": jx.get("spec_base_tokens_per_s", 0.0),
        "spec_steps": jx.get("spec_steps", 0),
        "spec_base_steps": jx.get("spec_base_steps", 0),
        "spec_drafted_tokens": jx.get("spec_drafted_tokens", 0),
        "spec_accepted_tokens": jx.get("spec_accepted_tokens", 0),
        "spec_rolled_back_tokens": jx.get("spec_rolled_back_tokens", 0),
        "spec_compile_count": jx.get("spec_compile_count", 0),
        "spec_sessions": jx.get("spec_sessions", 0),
        "spec_error": jx.get("spec_error", ""),
        **affinity,
        # overload resilience (ISSUE 13): the multi-tenant storm at ~2×
        # measured capacity — interactive p99 holds, interactive shed ≈ 0,
        # batch absorbs the shedding, and the admission-disabled control
        # run degrades (floors/ceilings in bench_floor.json)
        **storm,
        # agentic workflow serving (ISSUE 17): the agent-loop storm —
        # session-carrying DAG steps through admission, session-affinity
        # serving, pool-executed context embeds, and workflow resume
        # (steps/s + hit-rate floors, step-p99 + re-prefill ceilings in
        # bench_floor.json)
        **agents,
        # gang scheduling (ISSUE 15): barrier-only gang rate + the three
        # MULTICHIP flows as scheduled gang jobs (gang_jobs_per_sec /
        # gang_flows_ok floors + the gang_partial_reservations == 0
        # all-or-nothing invariant ceiling live in bench_floor.json)
        **gang,
        # sharded serving gangs (ISSUE 20): the TP=2 gang vs single-rank
        # same-run comparison — token identity + one-program-per-rank are
        # exact contracts, tp_speedup is a 1-core-host collapse guard
        # (floors/ceiling in bench_floor.json)
        **tp,
    }
    if smoke:
        out["smoke"] = True
    if prof is not None:
        # per-layer µs/op breakdown: routing / codec / selection / commit
        out["profile"] = prof
    for k in ("fallback_device", "tpu_skipped", "tpu_embed_error",
              "tpu_model_error", "tpu_batched_error", "tpu_serving_error",
              "tpu_disagg_error", "tpu_chat_error", "tpu_spec_error"):
        if k in jx:
            out[k] = jx[k]
    degraded = bool(out["embed_error"] or out["model_error"]
                    or out["batched_error"] or out["serving_error"]
                    or out["disagg_error"] or out["chat_error"]
                    or out["spec_error"] or out.get("gang_error")
                    or out.get("tp_error"))
    out["degraded"] = degraded
    if degraded:
        out["child_traceback"] = jx.get("child_traceback", "")
        sys.stderr.write(
            "\n*** BENCH DEGRADED: the JAX compute child failed — the control-"
            "plane numbers above are healthy but embed/model metrics are "
            "partial or missing. Child errors:\n"
            f"    embed_error: {out['embed_error'] or '-'}\n"
            f"    model_error: {out['model_error'] or '-'}\n"
            f"    batched_error: {out['batched_error'] or '-'}\n"
            f"    serving_error: {out['serving_error'] or '-'}\n"
        )
        if out["child_traceback"]:
            sys.stderr.write("--- child traceback (tail) ---\n")
            sys.stderr.write(out["child_traceback"][-2000:] + "\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
