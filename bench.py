"""Headline benchmark: scheduled jobs/sec end-to-end through the control
plane (BASELINE.json north star: ≥1,000 scheduled TPU jobs/sec on v5p-8).

Drives the real pipeline — gateway-role submit → scheduler engine (safety
check, strategy, state machine) → worker → result handling — over the
in-process bus with the KV store, i.e. every control-plane code path a
production deployment runs per job, minus network hops.  Also measures
context-engine embeds/sec on the accelerator when one is available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""
from __future__ import annotations

import asyncio
import json
import os
import sys
import time

N_JOBS = int(os.environ.get("BENCH_JOBS", "3000"))
BASELINE_JOBS_PER_SEC = 1000.0  # BASELINE.json north-star target


async def bench_scheduler() -> dict:
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.bus import LoopbackBus
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.kv import MemoryKV
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, Heartbeat, JobRequest, JobResult

    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    ms = MemoryStore(kv)
    kernel = SafetyKernel(
        policy_doc={
            "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
            "rules": [
                {"id": "tpu", "match": {"topics": ["job.tpu.>"]}, "decision": "allow"},
            ],
        }
    )
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}})
    eng = Engine(
        bus=bus, job_store=js, safety=SafetyClient(kernel.check),
        strategy=LeastLoadedStrategy(reg, pc), registry=reg,
    )
    await eng.start()

    done = asyncio.Event()
    completed = 0

    # minimal worker: replies immediately (we are measuring the control plane)
    async def worker_handler(subject, pkt):
        nonlocal completed
        req = pkt.job_request
        await bus.publish(
            subj.RESULT,
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="bench-w"),
                sender_id="bench-w",
            ),
        )

    await bus.subscribe("worker.bench-w.jobs", worker_handler, queue="w")
    for i in range(4):
        reg.update(Heartbeat(worker_id="bench-w", pool="bench", max_parallel_jobs=1 << 30))

    # count terminal results via the engine's completion metric
    t0 = time.perf_counter()
    for i in range(N_JOBS):
        req = JobRequest(job_id=f"bench-{i}", topic="job.bench", tenant_id="default")
        await bus.publish(subj.SUBMIT, BusPacket.wrap(req, sender_id="bench"))
    await bus.drain()
    # wait for all results to land
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        await bus.drain()
        n = eng.metrics.jobs_completed.value(status="SUCCEEDED")
        if n >= N_JOBS:
            break
        await asyncio.sleep(0.01)
    dt = time.perf_counter() - t0
    n = eng.metrics.jobs_completed.value(status="SUCCEEDED")
    p50 = eng.metrics.e2e_latency.quantile(0.5)
    await eng.stop()
    await bus.close()
    return {
        "jobs": int(n),
        "seconds": dt,
        "jobs_per_sec": n / dt if dt > 0 else 0.0,
        "p50_e2e_ms": (p50 or 0.0) * 1000,
    }


def bench_embeds() -> dict:
    """Context-engine embedding throughput on the available accelerator."""
    try:
        import jax

        from cordum_tpu.models.embedder import Embedder, EmbedderConfig

        on_accelerator = jax.devices()[0].platform not in ("cpu",)
        if on_accelerator:
            cfg = EmbedderConfig()
            batch, iters = 256, 4
        else:  # CPU smoke shape (single-core CI boxes)
            cfg = EmbedderConfig(n_layers=2, d_model=128, max_len=64)
            batch, iters = 32, 2
        e = Embedder(cfg, seed=0)
        texts = [f"document {i}: control plane scheduling latency report" for i in range(batch)]
        e.embed(texts)  # warm compile
        t0 = time.perf_counter()
        for _ in range(iters):
            e.embed(texts)
        dt = time.perf_counter() - t0
        return {
            "embeds_per_sec": iters * len(texts) / dt,
            "embed_device": jax.devices()[0].device_kind,
        }
    except Exception as ex:  # accelerator unavailable → report scheduling only
        return {"embeds_per_sec": 0.0, "embed_error": str(ex)[:120]}


def bench_selection() -> dict:
    """Worker-selection throughput at 1000 workers (reference analogue:
    18,234 selections/s, BENCHMARKS.md:131)."""
    import random

    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.protocol.types import Heartbeat, JobRequest

    rng = random.Random(9)
    reg = WorkerRegistry()
    for i in range(1000):
        reg.update(Heartbeat(
            worker_id=f"w{i:05d}", pool="tpu", capabilities=["tpu"],
            chip_count=rng.choice([1, 4, 8]), active_jobs=rng.randint(0, 12),
            max_parallel_jobs=16, cpu_load=rng.uniform(0, 100),
            tpu_duty_cycle=rng.uniform(0, 100),
        ))
    pc = parse_pool_config({"topics": {"job.tpu.work": "tpu"}, "pools": {"tpu": {"requires": ["tpu"]}}})
    strat = LeastLoadedStrategy(reg, pc)
    req = JobRequest(job_id="j", topic="job.tpu.work")
    strat.pick_subject(req)
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        strat.pick_subject(req)
    dt = time.perf_counter() - t0
    return {"selections_per_sec": n / dt, "native": strat._packed is not None}


def main() -> None:
    sched = asyncio.run(bench_scheduler())
    sel = bench_selection()
    emb = bench_embeds()
    out = {
        "metric": "scheduled_jobs_per_sec",
        "value": round(sched["jobs_per_sec"], 1),
        "unit": "jobs/s",
        "vs_baseline": round(sched["jobs_per_sec"] / BASELINE_JOBS_PER_SEC, 3),
        "p50_e2e_ms": round(sched["p50_e2e_ms"], 2),
        "jobs": sched["jobs"],
        "selections_per_sec": round(sel["selections_per_sec"], 1),
        "native_scan": sel["native"],
        "embeds_per_sec": round(emb.get("embeds_per_sec", 0.0), 1),
    }
    if "embed_device" in emb:
        out["embed_device"] = emb["embed_device"]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
