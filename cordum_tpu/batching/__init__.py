"""Micro-batching engine: coalesce batchable TPU jobs into bucketed XLA calls.

One control-plane job per XLA dispatch leaves the chip idle between tiny
programs — the device round-trip dominates for single-text ``embed`` and
short ``infer`` requests.  This package sits between the worker's job intake
and the XLA handlers: batchable jobs land in per-(op, length-bucket) queues,
an adaptive window flushes each queue into ONE padded bf16 XLA call, and the
per-job results scatter back so downstream consumers see ordinary
``JobResult`` packets (see ``docs/BATCHING.md``).
"""
from .buckets import bucket_for, pow2_buckets
from .engine import BatchCancelled, BatchItem, BatchParts, MicroBatcher

__all__ = [
    "BatchCancelled",
    "BatchItem",
    "BatchParts",
    "MicroBatcher",
    "bucket_for",
    "pow2_buckets",
]
