"""Pad-to-bucket sizing.

XLA compiles one program per input shape, so the batcher quantizes both the
sequence dimension (queue assignment) and the batch dimension (flush-time
padding) onto a small ladder of buckets: every flush reuses one of a handful
of compiled programs instead of compiling per ragged shape (the Ragged Paged
Attention / FlexNPU serving trick applied to the control plane's job ops).
"""
from __future__ import annotations

from typing import Sequence


def pow2_buckets(lo: int, hi: int) -> tuple[int, ...]:
    """Powers of two from ``lo`` up to and including a final ``hi`` cap."""
    out: list[int] = []
    b = max(1, lo)
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ ``length``; the largest bucket when none fits
    (callers cap lengths at the model's max, so overflow means clamp)."""
    for b in buckets:
        if length <= b:
            return b
    return buckets[-1]
