"""The micro-batcher: per-(op, length-bucket) queues + adaptive flush window.

Flow (see ``docs/BATCHING.md``):

  * :meth:`MicroBatcher.submit` parks a job's rows in the queue keyed by
    ``(op, length_bucket)`` and returns an awaitable per-job result;
  * a queue flushes when its accumulated rows reach ``max_batch_rows`` OR
    when the adaptive window expires — the window is sized from the observed
    arrival rate (EWMA of inter-arrival gaps): fast arrivals wait long
    enough to fill the batch, slow arrivals flush almost immediately so a
    lone job never sits out the full ``max_wait_ms``;
  * one flush = one call of ``flush_fn(op, bucket, items)`` (the padded
    bf16 XLA program, executed off-loop by the caller's executor);
  * a whole-batch failure falls back to per-item execution so one poison
    job cannot fail its batch-mates;
  * :meth:`cancel` removes a still-queued job and resolves its waiter with
    :class:`BatchCancelled` — the job never rides in the flush.

Each flush emits a ``batch-flush`` flight-recorder span (trace of the
oldest member; parent = that member's execute span) carrying ``batch_size``
/ ``queue_wait_ms`` attributes, and feeds the ``cordum_batch_size`` /
``cordum_batch_queue_depth`` metrics.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional, Sequence

from ..infra import logging as logx
from ..infra.metrics import Metrics
from ..obs.tracer import Tracer
from .buckets import bucket_for, pow2_buckets

# flush_fn(op, seq_bucket, items) -> one result per item, same order
FlushFn = Callable[[str, int, "list[BatchItem]"], Awaitable[Sequence[Any]]]


@dataclass(frozen=True)
class BatchParts:
    """A payload decomposed for batching: op + its rows + queue length key."""

    op: str
    rows: Any
    n_rows: int
    length: int


# parts_fn(payload) -> BatchParts when the payload is batchable, else None.
# Injected by the handler layer (it knows model configs); keeps this engine
# free of op-specific knowledge.
PartsFn = Callable[[Any], Optional[BatchParts]]

DEFAULT_MAX_BATCH_ROWS = 32
DEFAULT_MAX_WAIT_MS = 25.0
MIN_WAIT_MS = 0.5
ARRIVAL_EWMA_ALPHA = 0.3


class BatchCancelled(Exception):
    """Job was cancelled while waiting in a batch queue."""


@dataclass
class BatchItem:
    """One queued job's contribution to a batch."""

    job_id: str
    rows: Any  # op-specific row payload (texts / token rows)
    n_rows: int
    enqueued_at: float
    future: asyncio.Future
    trace_id: str = ""
    parent_span_id: str = ""  # the job's execute span (flush span parent)
    # written at flush time (batch_size / queue_wait_ms); the worker folds
    # these into the job's execute-span attrs
    attr_sink: dict = field(default_factory=dict)


@dataclass
class _Queue:
    items: list[BatchItem] = field(default_factory=list)
    n_rows: int = 0
    timer: Optional[asyncio.TimerHandle] = None


@dataclass
class BatcherStats:
    flushes: int = 0
    flushed_jobs: int = 0
    flushed_rows: int = 0
    max_batch_rows_seen: int = 0
    item_fallbacks: int = 0
    cancelled_in_queue: int = 0


class MicroBatcher:
    def __init__(
        self,
        flush_fn: FlushFn,
        *,
        parts_fn: Optional[PartsFn] = None,
        max_batch_rows: int = DEFAULT_MAX_BATCH_ROWS,
        max_wait_ms: float = DEFAULT_MAX_WAIT_MS,
        len_buckets: Sequence[int] = (),
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        self.flush_fn = flush_fn
        self.parts_fn = parts_fn
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.len_buckets = tuple(len_buckets) or pow2_buckets(16, 128)
        self.metrics = metrics
        self.tracer = tracer
        self.stats = BatcherStats()
        self._queues: dict[tuple[str, int], _Queue] = {}
        # EWMA inter-arrival gap per queue key (seconds); the adaptive window
        self._arrival_ewma: dict[tuple[str, int], float] = {}
        self._last_arrival: dict[tuple[str, int], float] = {}
        self._flush_tasks: set[asyncio.Task] = set()
        self._closed = False

    # ------------------------------------------------------------------
    def parts(self, payload: Any) -> Optional[BatchParts]:
        """Decompose a job payload for batching; None = not batchable (the
        worker falls back to its ordinary per-job handler path)."""
        if self.parts_fn is None:
            return None
        return self.parts_fn(payload)

    # ------------------------------------------------------------------
    def queue_depth(self, op: str = "") -> int:
        """Queued rows (for ``op``, or all ops when empty) — observability."""
        return sum(
            q.n_rows for (qop, _), q in self._queues.items() if not op or qop == op
        )

    def window_s(self, key: tuple[str, int], queued_rows: int) -> float:
        """Adaptive wait for a queue: the EWMA-predicted time for the batch
        to fill, clamped to [MIN_WAIT_MS, max_wait_ms].  No arrival history
        yet → the full window (first jobs pay the exploratory wait once)."""
        gap = self._arrival_ewma.get(key)
        if gap is None:
            return self.max_wait_s
        expected_fill = gap * max(1, self.max_batch_rows - queued_rows)
        return min(self.max_wait_s, max(MIN_WAIT_MS / 1000.0, expected_fill))

    # ------------------------------------------------------------------
    async def submit(
        self,
        op: str,
        rows: Any,
        *,
        job_id: str,
        length: int,
        n_rows: int = 1,
        trace_id: str = "",
        parent_span_id: str = "",
        attr_sink: Optional[dict] = None,
    ) -> Any:
        """Queue a job's rows and await its scattered result."""
        if self._closed:
            raise RuntimeError("batcher is stopped")
        bucket = bucket_for(length, self.len_buckets)
        key = (op, bucket)
        now = time.monotonic()
        prev = self._last_arrival.get(key)
        if prev is not None:
            gap = now - prev
            ewma = self._arrival_ewma.get(key)
            self._arrival_ewma[key] = (
                gap if ewma is None
                else (1 - ARRIVAL_EWMA_ALPHA) * ewma + ARRIVAL_EWMA_ALPHA * gap
            )
        self._last_arrival[key] = now

        item = BatchItem(
            job_id=job_id,
            rows=rows,
            n_rows=max(1, n_rows),
            enqueued_at=now,
            future=asyncio.get_running_loop().create_future(),
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            attr_sink=attr_sink if attr_sink is not None else {},
        )
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = _Queue()
        q.items.append(item)
        q.n_rows += item.n_rows
        if self.metrics is not None:
            self.metrics.batch_queue_depth.set(
                q.n_rows, op=op, bucket=str(bucket)
            )
        if q.n_rows >= self.max_batch_rows:
            self._start_flush(key, q)
        elif q.timer is None:
            delay = self.window_s(key, q.n_rows)
            q.timer = asyncio.get_running_loop().call_later(
                delay, self._start_flush, key, q
            )
        return await item.future

    # ------------------------------------------------------------------
    def cancel(self, job_id: str) -> bool:
        """Remove a still-queued job; its waiter gets :class:`BatchCancelled`
        so the worker publishes an ordinary CANCELLED result.  Returns False
        when the job is not queued (already flushing or never batched)."""
        for key, q in list(self._queues.items()):
            for i, item in enumerate(q.items):
                if item.job_id != job_id:
                    continue
                q.items.pop(i)
                q.n_rows -= item.n_rows
                self.stats.cancelled_in_queue += 1
                if not item.future.done():
                    item.future.set_exception(BatchCancelled(job_id))
                if self.metrics is not None:
                    self.metrics.batch_queue_depth.set(
                        q.n_rows, op=key[0], bucket=str(key[1])
                    )
                if not q.items:
                    if q.timer is not None:
                        q.timer.cancel()
                    self._queues.pop(key, None)
                return True
        return False

    # ------------------------------------------------------------------
    def _start_flush(self, key: tuple[str, int], q: _Queue) -> None:
        # a stale timer for an already-flushed queue must not flush its
        # replacement early: only act when `q` is still the live queue
        if self._queues.get(key) is not q:
            return
        if q.timer is not None:
            q.timer.cancel()
            q.timer = None
        self._queues.pop(key, None)
        if not q.items:
            return
        t = asyncio.ensure_future(self._flush(key, q.items))
        self._flush_tasks.add(t)
        t.add_done_callback(self._flush_tasks.discard)

    async def _flush(self, key: tuple[str, int], items: list[BatchItem]) -> None:
        op, bucket = key
        n_rows = sum(it.n_rows for it in items)
        now = time.monotonic()
        queue_wait_ms = max(0.0, (now - min(it.enqueued_at for it in items)) * 1000)
        self.stats.flushes += 1
        self.stats.flushed_jobs += len(items)
        self.stats.flushed_rows += n_rows
        self.stats.max_batch_rows_seen = max(self.stats.max_batch_rows_seen, n_rows)
        for it in items:
            it.attr_sink["batch_size"] = str(n_rows)
            it.attr_sink["batch_jobs"] = str(len(items))
            it.attr_sink["batch_queue_wait_ms"] = f"{queue_wait_ms:.2f}"
        if self.metrics is not None:
            self.metrics.batch_size.observe(float(n_rows), op=op)
            self.metrics.batch_flushes.inc(op=op, bucket=str(bucket))
            self.metrics.batch_queue_depth.set(0, op=op, bucket=str(bucket))
        oldest = min(items, key=lambda it: it.enqueued_at)
        span = None
        if self.tracer is not None and oldest.trace_id:
            span = self.tracer.begin(
                "batch-flush",
                trace_id=oldest.trace_id,
                parent_span_id=oldest.parent_span_id,
                attrs={
                    "op": op,
                    "bucket": str(bucket),
                    "batch_size": str(n_rows),
                    "batch_jobs": str(len(items)),
                    "queue_wait_ms": f"{queue_wait_ms:.2f}",
                },
            )
        try:
            results = await self.flush_fn(op, bucket, items)
            if len(results) != len(items):
                raise RuntimeError(
                    f"flush_fn returned {len(results)} results for {len(items)} items"
                )
            for it, res in zip(items, results):
                if not it.future.done():
                    it.future.set_result(res)
            if span is not None and self.tracer is not None:
                await self.tracer.finish(span)
        except Exception as batch_err:  # noqa: BLE001 - isolated per item below
            if span is not None and self.tracer is not None:
                span.attrs["error"] = type(batch_err).__name__
                await self.tracer.finish(span, status="ERROR")
            if len(items) == 1:
                if not items[0].future.done():
                    items[0].future.set_exception(batch_err)
                return
            # whole-batch failure: isolate — rerun each member alone so only
            # the job that actually poisons the program fails
            logx.warn(
                "batch flush failed; isolating per item",
                op=op, bucket=bucket, jobs=len(items), err=str(batch_err),
            )
            for it in items:
                if it.future.done():
                    continue
                self.stats.item_fallbacks += 1
                try:
                    single = await self.flush_fn(op, bucket, [it])
                    if not it.future.done():
                        it.future.set_result(single[0])
                except Exception as item_err:  # noqa: BLE001 - per-job verdict
                    if not it.future.done():
                        it.future.set_exception(item_err)

    # ------------------------------------------------------------------
    async def flush_now(self) -> None:
        """Flush every queue immediately (tests / shutdown drain)."""
        for key, q in list(self._queues.items()):
            self._start_flush(key, q)
        while self._flush_tasks:
            await asyncio.gather(*list(self._flush_tasks), return_exceptions=True)

    async def stop(self) -> None:
        """Drain: flush queued work, then refuse new submits."""
        self._closed = True
        await self.flush_now()
