"""cordumctl — the operator CLI (reference ``cmd/cordumctl``, ~2.9k LoC:
init/dev/up/status/workflow/run/approval/dlq/pack/job/trace).

Talks HTTP to the gateway (env CORDUM_API_URL, CORDUM_API_KEY); ``up``
spawns the full service stack as local subprocesses.

Usage: ``python -m cordum_tpu.cli <command> ...``
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Any, Optional

import httpx

DEFAULT_API = os.environ.get("CORDUM_API_URL", "http://127.0.0.1:8081")


def _client() -> httpx.Client:
    headers = {}
    key = os.environ.get("CORDUM_API_KEY", "")
    if key:
        headers["X-Api-Key"] = key
    role = os.environ.get("CORDUM_ROLE", "")
    if role:
        headers["X-Principal-Role"] = role
    pid = os.environ.get("CORDUM_PRINCIPAL", "")
    if pid:
        headers["X-Principal-Id"] = pid
    return httpx.Client(base_url=DEFAULT_API, headers=headers, timeout=30.0)


def _print(obj: Any) -> None:
    print(json.dumps(obj, indent=2, default=str))


def _die(msg: str, code: int = 1) -> None:
    print(f"error: {msg}", file=sys.stderr)
    sys.exit(code)


def _check(r: httpx.Response) -> Any:
    try:
        body = r.json()
    except ValueError:
        body = {"raw": r.text}
    if r.status_code >= 400:
        _die(f"HTTP {r.status_code}: {body.get('error', body)}")
    return body


# ---------------------------------------------------------------- commands


def cmd_init(args) -> None:
    """Scaffold config files (reference `cordumctl init`)."""
    os.makedirs("config", exist_ok=True)
    files = {
        "config/pools.yaml": (
            "topics:\n  job.default: default\n  job.tpu.>: tpu\n"
            "pools:\n  default:\n    requires: []\n"
            "  tpu:\n    requires: [\"tpu\"]\n    min_chips: 1\n"
        ),
        "config/timeouts.yaml": (
            "reconciler:\n  dispatch_timeout_seconds: 300\n"
            "  running_timeout_seconds: 9000\n  scan_interval_seconds: 30\n"
        ),
        "config/safety.yaml": (
            "default_tenant: default\n"
            "tenants:\n  default:\n    allow_topics: [\"job.*\", \"job.>\"]\n"
            "    deny_topics: [\"sys.*\"]\n"
            "rules: []\n"
        ),
    }
    for path, content in files.items():
        if os.path.exists(path) and not args.force:
            print(f"skip {path} (exists)")
            continue
        with open(path, "w") as f:
            f.write(content)
        print(f"wrote {path}")


SERVICES = [
    ("statebus", "cordum_tpu.cmd.statebus", {}),
    ("safety-kernel", "cordum_tpu.cmd.safety_kernel",
     {"CORDUM_STATEBUS_URL": "statebus://127.0.0.1:7420"}),
    ("scheduler", "cordum_tpu.cmd.scheduler",
     {"CORDUM_STATEBUS_URL": "statebus://127.0.0.1:7420",
      "SAFETY_KERNEL_ADDR": "http://127.0.0.1:7430"}),
    ("workflow-engine", "cordum_tpu.cmd.workflow_engine",
     {"CORDUM_STATEBUS_URL": "statebus://127.0.0.1:7420"}),
    ("gateway", "cordum_tpu.cmd.gateway",
     {"CORDUM_STATEBUS_URL": "statebus://127.0.0.1:7420"}),
    ("worker", "cordum_tpu.cmd.worker",
     {"CORDUM_STATEBUS_URL": "statebus://127.0.0.1:7420",
      "WORKER_TOPICS": "job.tpu.>,job.default,job.hello-pack.echo", "WORKER_POOL": "tpu"}),
]


def _force_cpu_env() -> None:
    os.environ["CORDUM_FORCE_CPU"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"


def cmd_up(args) -> None:
    """Bring up the local stack as subprocesses (reference `cordumctl up`)."""
    procs = []
    logdir = args.logdir
    os.makedirs(logdir, exist_ok=True)
    selected = [s for s in SERVICES if not args.services or s[0] in args.services]
    for name, module, env_extra in selected:
        env = dict(os.environ)
        env.update(env_extra)
        log = open(os.path.join(logdir, f"{name}.log"), "ab")
        p = subprocess.Popen([sys.executable, "-m", module], env=env, stdout=log, stderr=log)
        procs.append((name, p))
        print(f"started {name} (pid {p.pid})")
        if name == "statebus":
            time.sleep(0.5)  # listeners need the bus first
    print(f"logs in {logdir}/; Ctrl-C to stop")
    try:
        while True:
            time.sleep(1)
            for name, p in procs:
                if p.poll() is not None:
                    _die(f"service {name} exited with {p.returncode} (see {logdir}/{name}.log)")
    except KeyboardInterrupt:
        for name, p in reversed(procs):
            p.terminate()
        for name, p in procs:
            p.wait(timeout=10)
        print("stopped")


def cmd_status(args) -> None:
    with _client() as c:
        _print(_check(c.get("/api/v1/status")))


def cmd_job(args) -> None:
    with _client() as c:
        if args.action == "submit":
            payload = json.loads(args.payload) if args.payload else {}
            body = {"topic": args.topic, "payload": payload}
            if args.metadata:
                body["metadata"] = json.loads(args.metadata)
            doc = _check(c.post("/api/v1/jobs", json=body))
            _print(doc)
            if args.wait:
                _wait_job(c, doc["job_id"])
        elif args.action == "status":
            _print(_check(c.get(f"/api/v1/jobs/{args.job_id}?events=true")))
        elif args.action == "result":
            _print(_check(c.get(f"/api/v1/jobs/{args.job_id}?result=true")))
        elif args.action == "cancel":
            _print(_check(c.post(f"/api/v1/jobs/{args.job_id}/cancel")))
        elif args.action == "list":
            _print(_check(c.get("/api/v1/jobs")))


def _wait_job(c: httpx.Client, job_id: str, timeout_s: float = 120.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        doc = _check(c.get(f"/api/v1/jobs/{job_id}?result=true"))
        state = doc.get("state", "")
        if state in ("SUCCEEDED", "FAILED", "CANCELLED", "TIMEOUT", "DENIED"):
            _print(doc)
            return
        time.sleep(0.5)
    _die(f"timed out waiting for job {job_id}")


def cmd_workflow(args) -> None:
    with _client() as c:
        if args.action == "create":
            with open(args.file) as f:
                import yaml

                doc = yaml.safe_load(f)
            _print(_check(c.post("/api/v1/workflows", json=doc)))
        elif args.action == "list":
            _print(_check(c.get("/api/v1/workflows")))
        elif args.action == "show":
            _print(_check(c.get(f"/api/v1/workflows/{args.workflow_id}")))
        elif args.action == "delete":
            _print(_check(c.delete(f"/api/v1/workflows/{args.workflow_id}")))


def cmd_run(args) -> None:
    with _client() as c:
        if args.action == "start":
            body = {"input": json.loads(args.input) if args.input else None,
                    "dry_run": args.dry_run}
            doc = _check(c.post(f"/api/v1/workflows/{args.workflow_id}/runs", json=body))
            _print(doc)
            if args.wait:
                _wait_run(c, doc["run_id"])
        elif args.action == "status":
            _print(_check(c.get(f"/api/v1/runs/{args.run_id}")))
        elif args.action == "timeline":
            doc = _check(c.get(f"/api/v1/runs/{args.run_id}/timeline"))
            if getattr(args, "json", False):
                _print(doc)
            else:
                print(_render_run_timeline(doc.get("timeline") or []))
        elif args.action == "cancel":
            _print(_check(c.post(f"/api/v1/runs/{args.run_id}/cancel")))
        elif args.action == "approve-step":
            _print(_check(c.post(
                f"/api/v1/runs/{args.run_id}/steps/{args.step_id}/approve",
                json={"approve": not args.reject})))
        elif args.action == "rerun":
            _print(_check(c.post(f"/api/v1/runs/{args.run_id}/rerun",
                                 json={"from_step": args.step_id})))
        elif args.action == "list":
            _print(_check(c.get("/api/v1/runs")))


def _render_run_timeline(events: list[dict]) -> str:
    """Human-readable run timeline: +offset from run start, step, event,
    detail.  The raw event list stays available behind --json."""
    if not events:
        return "no timeline events"
    t0 = min(int(e.get("ts_us", 0) or 0) for e in events)
    lines = []
    for e in events:
        dt_ms = (int(e.get("ts_us", 0) or 0) - t0) / 1000.0
        step = str(e.get("step_id", "") or "-")
        lines.append(
            f"+{dt_ms:9.1f}ms  {step:<24} {str(e.get('event', '')):<20} "
            f"{str(e.get('detail', ''))}"
        )
    return "\n".join(lines)


def cmd_runs(args) -> None:
    """Workflow-run fleet table (GET /api/v1/runs?detail=1): one row per run
    with status, SLO class, step progress, and duration."""
    q = f"?detail=1&workflow_id={args.workflow_id}" if args.workflow_id else "?detail=1"
    with _client() as c:
        doc = _check(c.get(f"/api/v1/runs{q}"))
    runs = doc.get("runs") or []
    if args.json:
        _print(runs)
        return
    if not runs:
        print("no runs")
        return
    cols = ["run_id", "workflow", "status", "slo", "steps", "duration_s", "trace_id"]
    rows = []
    for r in runs:
        steps = r.get("steps") or {}
        done = sum(1 for s in steps.values() if s == "SUCCEEDED")
        t0, t1 = int(r.get("created_at_us") or 0), int(r.get("finished_at_us") or 0)
        dur = f"{(t1 - t0) / 1e6:.2f}" if t1 and t0 else ""
        rows.append({
            "run_id": str(r.get("run_id", "")),
            "workflow": str(r.get("workflow_id", "")),
            "status": str(r.get("status", "")),
            "slo": str(r.get("slo_class", "") or "-"),
            "steps": f"{done}/{len(steps)}",
            "duration_s": dur,
            "trace_id": str(r.get("trace_id", "")),
        })
    widths = {c_: max(len(c_), *(len(r[c_]) for r in rows)) for c_ in cols}
    print("  ".join(c_.ljust(widths[c_]) for c_ in cols))
    for r in rows:
        print("  ".join(r[c_].ljust(widths[c_]) for c_ in cols))


def _wait_run(c: httpx.Client, run_id: str, timeout_s: float = 300.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        doc = _check(c.get(f"/api/v1/runs/{run_id}"))
        if doc.get("status") in ("SUCCEEDED", "FAILED", "CANCELLED"):
            _print(doc)
            return
        time.sleep(0.5)
    _die(f"timed out waiting for run {run_id}")


def cmd_approval(args) -> None:
    with _client() as c:
        if args.action == "list":
            _print(_check(c.get("/api/v1/approvals")))
        elif args.action == "approve":
            _print(_check(c.post(f"/api/v1/approvals/{args.job_id}/approve")))
        elif args.action == "reject":
            _print(_check(c.post(f"/api/v1/approvals/{args.job_id}/reject",
                                 json={"reason": args.reason})))


def cmd_dlq(args) -> None:
    with _client() as c:
        if args.action == "list":
            _print(_check(c.get("/api/v1/dlq")))
        elif args.action == "retry":
            _print(_check(c.post(f"/api/v1/dlq/{args.job_id}/retry")))
        elif args.action == "retry-all":
            _print(_check(c.post("/api/v1/dlq/retry-all")))
        elif args.action == "purge":
            _print(_check(c.post("/api/v1/dlq/purge",
                                 json={"max_age_s": args.max_age_s})))
        elif args.action == "delete":
            _print(_check(c.delete(f"/api/v1/dlq/{args.job_id}")))


def cmd_trace(args) -> None:
    """Fetch a trace and render the flight-recorder span waterfall."""
    from .obs.assembler import render_waterfall

    with _client() as c:
        doc = _check(c.get(f"/api/v1/traces/{args.trace_id}"))
    if args.json:
        _print(doc)
        return
    print(render_waterfall(doc, width=args.width))
    jobs = doc.get("jobs") or []
    if jobs:
        print("jobs: " + "  ".join(f"{j['job_id']}={j.get('state')}" for j in jobs))


def cmd_traces(args) -> None:
    """List recent trace ids with root span, duration and service count —
    the entry point into the waterfall when you don't already know an id.
    ``traces blame`` instead aggregates the newest N traces' critical paths
    into per-stage blame shares (where does p99 go)."""
    if args.action == "blame":
        from .obs.assembler import render_blame

        with _client() as c:
            doc = _check(c.get(f"/api/v1/traces/analysis?last={args.last}"))
        if args.json:
            _print(doc)
            return
        print(render_blame(doc))
        return
    with _client() as c:
        doc = _check(c.get(f"/api/v1/traces?last={args.last}"))
    traces = doc.get("traces") or []
    if args.json:
        _print(traces)
        return
    if not traces:
        print("no traces recorded")
        return
    cols = ["trace_id", "root", "root_service", "spans", "services",
            "duration_ms", "age_s"]
    rows = [
        {
            "trace_id": t["trace_id"],
            "root": t.get("root", ""),
            "root_service": t.get("root_service", ""),
            "spans": str(t.get("span_count", 0)),
            "services": str(len(t.get("services") or [])),
            "duration_ms": str(t.get("duration_ms", "")),
            "age_s": str(t.get("age_s", "")),
        }
        for t in traces
    ]
    widths = {c_: max(len(c_), *(len(r[c_]) for r in rows)) for c_ in cols}
    print("  ".join(c_.ljust(widths[c_]) for c_ in cols))
    for r in rows:
        print("  ".join(r[c_].ljust(widths[c_]) for c_ in cols))


def cmd_top(args) -> None:
    """Live fleet table from GET /api/v1/fleet: per-service health beacons,
    fleet rates, SLO burn states.  Refreshes every --interval seconds;
    --once renders a single frame (scripts, smoke tests)."""
    from .obs.fleet import render_fleet_table

    with _client() as c:
        while True:
            doc = _check(c.get("/api/v1/fleet"))
            if args.json:
                _print(doc)
            else:
                frame = render_fleet_table(doc)
                if not args.once:
                    # ANSI clear + home: refresh in place like top(1)
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(frame, flush=True)
            if args.once:
                return
            try:
                time.sleep(args.interval)
            except KeyboardInterrupt:
                return


def cmd_capacity(args) -> None:
    """The fleet's op × worker throughput matrix (GET /api/v1/capacity):
    per-(op, bucket) items/s + decode tokens/s, device p50/p99, compile
    counts, freshness — the capacity observatory's operator view."""
    from .obs.capacity import render_capacity_table

    with _client() as c:
        doc = _check(c.get("/api/v1/capacity"))
    if args.json:
        _print(doc)
        return
    print(render_capacity_table(doc))


def cmd_gangs(args) -> None:
    """The fleet's live gang table (GET /api/v1/gangs): gang id, job,
    state, member workers, rendezvous/done progress, age — merged from
    every scheduler shard's beacon (docs/GANG.md)."""
    from .controlplane.scheduler.gang import render_gang_table

    with _client() as c:
        doc = _check(c.get("/api/v1/gangs"))
    if args.json:
        _print(doc)
        return
    print(render_gang_table(doc))


def cmd_admission(args) -> None:
    """Live admission-controller state (GET /api/v1/admission): per-(op,
    class) headroom against measured capacity, the current brownout tier,
    per-tenant token-bucket levels, shed counts (docs/ADMISSION.md)."""
    from .controlplane.gateway.admission import render_admission_table

    with _client() as c:
        doc = _check(c.get("/api/v1/admission"))
    if args.json:
        _print(doc)
        return
    print(render_admission_table(doc))


def cmd_drain(args) -> None:
    """Gracefully drain a worker: sessions live-migrate to peers (scheduler
    requeue as the fallback), per-job work finishes, then it exits —
    zero CANCELLED sessions (docs/SERVING.md §Migration)."""
    with _client() as c:
        doc = _check(c.post(f"/api/v1/workers/{args.worker_id}/drain",
                            json={"reason": args.reason} if args.reason else {}))
        _print(doc)
        if not args.wait:
            return
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            workers = _check(c.get("/api/v1/workers")).get("workers", {})
            hb = workers.get(args.worker_id)
            if hb is None:
                print(f"worker {args.worker_id} drained (deregistered)")
                return
            if hb.get("draining"):
                print(f"worker {args.worker_id} draining "
                      f"(active_jobs={hb.get('active_jobs', '?')})")
            time.sleep(1.0)
        _die(f"worker {args.worker_id} still registered after {args.timeout}s")


def cmd_pack(args) -> None:
    from .packs import cli_pack

    cli_pack(args)


def cmd_statebus(args) -> None:
    """Statebus fleet status/admin, straight against the servers (no
    gateway): per-partition role/epoch/offset/replication lag from
    CORDUM_STATEBUS_URL (comma = partitions, ``|`` = replica set), and
    explicit replica promotion (docs/PROTOCOL.md §Replication)."""
    import asyncio

    from .infra.replication import admin_call, parse_endpoint

    url = args.url or os.environ.get(
        "CORDUM_STATEBUS_URL", "statebus://127.0.0.1:7420")
    partitions = [u.strip() for u in url.split(",") if u.strip()]

    async def run() -> None:
        if args.action == "promote":
            if not args.endpoint:
                _die("statebus promote requires an endpoint (host:port)")
            host, port = parse_endpoint(args.endpoint)
            doc = await admin_call(host, port, "promote", timeout_s=10.0)
            if doc is None:
                _die(f"promote failed: {host}:{port} unreachable or errored")
            _print(doc)
            return
        rows = []
        for p, part in enumerate(partitions):
            for ep in part.split("|"):
                host, port = parse_endpoint(ep.strip())
                doc = await admin_call(host, port, "role", timeout_s=2.0)
                row = {"partition": p, "endpoint": f"{host}:{port}"}
                if not isinstance(doc, dict):
                    row.update({"role": "DOWN", "epoch": "-", "offset": "-",
                                "lag_ops": "-"})
                else:
                    lag = doc.get("lag_ops")  # replica-side link lag
                    if lag is None and doc.get("replicas"):
                        # primary: worst attached-replica lag
                        lag = max(r.get("lag_ops", 0) for r in doc["replicas"])
                    row.update({
                        "role": doc.get("role", "?"),
                        "epoch": doc.get("epoch", 0),
                        "offset": doc.get("offset", 0),
                        "lag_ops": 0 if lag is None else lag,
                        "sync": doc.get("sync", False),
                        "replicas": len(doc.get("replicas") or []),
                    })
                rows.append(row)
        if args.json:
            _print(rows)
            return
        cols = ["partition", "endpoint", "role", "epoch", "offset",
                "lag_ops", "sync", "replicas"]
        widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
                  for c in cols}
        print("  ".join(c.ljust(widths[c]) for c in cols))
        for r in rows:
            print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))

    asyncio.run(run())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cordumctl", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="scaffold config files")
    sp.add_argument("--force", action="store_true")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("up", help="start the local service stack")
    sp.add_argument("--logdir", default=".cordum-logs")
    sp.add_argument("services", nargs="*", help="subset of services to start")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("dev", help="alias for `up` with CPU-forced workers")
    sp.add_argument("--logdir", default=".cordum-logs")
    sp.add_argument("services", nargs="*")
    sp.set_defaults(fn=lambda a: (_force_cpu_env(), cmd_up(a)))

    sp = sub.add_parser("status", help="gateway status")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("job")
    sp.add_argument("action", choices=["submit", "status", "result", "cancel", "list"])
    sp.add_argument("job_id", nargs="?")
    sp.add_argument("--topic", default="job.default")
    sp.add_argument("--payload", default="")
    sp.add_argument("--metadata", default="")
    sp.add_argument("--wait", action="store_true")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("workflow")
    sp.add_argument("action", choices=["create", "list", "show", "delete"])
    sp.add_argument("workflow_id", nargs="?")
    sp.add_argument("--file", "-f", default="")
    sp.set_defaults(fn=cmd_workflow)

    sp = sub.add_parser("run")
    sp.add_argument("action", choices=["start", "status", "timeline", "cancel",
                                       "approve-step", "rerun", "list"])
    sp.add_argument("run_id", nargs="?")
    sp.add_argument("--workflow-id", dest="workflow_id", default="")
    sp.add_argument("--input", default="")
    sp.add_argument("--step-id", dest="step_id", default="")
    sp.add_argument("--reject", action="store_true")
    sp.add_argument("--dry-run", dest="dry_run", action="store_true")
    sp.add_argument("--wait", action="store_true")
    sp.add_argument("--json", action="store_true",
                    help="timeline: raw JSON instead of the rendered view")
    sp.set_defaults(fn=cmd_run)

    sp = sub.add_parser("runs", help="workflow-run fleet table")
    sp.add_argument("--workflow-id", dest="workflow_id", default="")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_runs)

    sp = sub.add_parser("approval")
    sp.add_argument("action", choices=["list", "approve", "reject"])
    sp.add_argument("job_id", nargs="?")
    sp.add_argument("--reason", default="rejected")
    sp.set_defaults(fn=cmd_approval)

    sp = sub.add_parser("dlq")
    sp.add_argument("action", choices=["list", "retry", "retry-all", "purge", "delete"])
    sp.add_argument("job_id", nargs="?")
    sp.add_argument("--max-age-s", dest="max_age_s", type=float, default=0.0,
                    help="purge: drop entries older than this many seconds")
    sp.set_defaults(fn=cmd_dlq)

    sp = sub.add_parser("trace", help="render a trace's span waterfall")
    sp.add_argument("trace_id")
    sp.add_argument("--json", action="store_true", help="raw JSON instead of ASCII")
    sp.add_argument("--width", type=int, default=48)
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("traces",
                        help="list recent traces / critical-path blame")
    sp.add_argument("action", nargs="?", choices=["list", "blame"],
                    default="list",
                    help="blame: per-stage critical-path blame shares over "
                         "the newest traces (GET /api/v1/traces/analysis)")
    sp.add_argument("--last", type=int, default=20,
                    help="how many recent traces to list/analyze")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_traces)

    sp = sub.add_parser(
        "capacity",
        help="fleet op x worker throughput matrix (GET /api/v1/capacity)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_capacity)

    sp = sub.add_parser(
        "gangs",
        help="live gang table: mesh shape, members, state, age "
             "(GET /api/v1/gangs)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_gangs)

    sp = sub.add_parser(
        "admission",
        help="live admission-controller state: headroom, brownout tier, "
             "tenant buckets (GET /api/v1/admission)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_admission)

    sp = sub.add_parser(
        "top", help="live fleet telemetry table (GET /api/v1/fleet)")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds")
    sp.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    sp.add_argument("--json", action="store_true",
                    help="raw /api/v1/fleet JSON instead of the table")
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser(
        "statebus", help="statebus replication status / promote a replica")
    sp.add_argument("action", choices=["status", "promote"])
    sp.add_argument("endpoint", nargs="?", default="",
                    help="endpoint for promote (statebus://host:port)")
    sp.add_argument("--url", default="",
                    help="override CORDUM_STATEBUS_URL (comma = partitions, "
                         "'|' = replica set)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_statebus)

    sp = sub.add_parser(
        "drain",
        help="gracefully drain a worker (live-migrate its serving sessions "
             "to peers, finish jobs, exit)")
    sp.add_argument("worker_id")
    sp.add_argument("--reason", default="")
    sp.add_argument("--wait", action="store_true",
                    help="poll /api/v1/workers until the worker deregisters")
    sp.add_argument("--timeout", type=float, default=120.0)
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("pack")
    sp.add_argument("action", choices=["create", "install", "uninstall", "list", "show", "verify"])
    sp.add_argument("target", nargs="?")
    sp.add_argument("--dir", default=".")
    sp.set_defaults(fn=cmd_pack)

    return p


def main(argv: Optional[list[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    # `run start` takes the workflow id positionally when --workflow-id absent
    if getattr(args, "command", "") == "run" and args.action == "start" and not args.workflow_id:
        args.workflow_id = args.run_id or ""
        if not args.workflow_id:
            _die("run start requires a workflow id")
    args.fn(args)


if __name__ == "__main__":
    main()
