"""Shared bootstrap for service binaries: env config, logging, statebus
connection, signal-driven shutdown (reference ``cmd/*`` thin mains)."""
from __future__ import annotations

import asyncio
import os
import signal

from ..infra import logging as logx
from ..infra.config import Config, load


def setup() -> Config:
    logx.setup()
    # SIGUSR1 dumps all thread stacks to stderr — the only way to see where
    # a service binary is stuck without restarting it under a debugger
    try:
        import faulthandler

        faulthandler.register(signal.SIGUSR1, all_threads=True)
    except (AttributeError, ValueError):  # pragma: no cover - non-POSIX
        pass
    return load()


async def connect_statebus(cfg: Config):
    from ..infra import statebus

    # comma-separated CORDUM_STATEBUS_URL connects the partitioned client
    # (keyspace-routed KV + subject-routed bus); one endpoint is the plain
    # single-server client wrapped in the same close-handle
    url = cfg.statebus_url or "statebus://127.0.0.1:7420"
    kv, bus, conn = await statebus.connect_partitioned(url)
    logx.info("connected to statebus", url=url,
              partitions=len(conn.conns))
    return kv, bus, conn


async def wait_for_shutdown() -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover
            pass
    await stop.wait()


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default
