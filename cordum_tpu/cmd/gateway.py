"""API-gateway service binary (reference ``cmd/cordum-api-gateway``).

Runs the HTTP/WS surface against the statebus; the workflow engine is
embedded (the gateway is a second consumer of results in the reference too,
gateway.go:610-651), the safety kernel is embedded or remote."""
from __future__ import annotations

import asyncio
import os

from ..context.service import BusEmbedder, ContextService
from ..controlplane.gateway.app import Gateway
from ..controlplane.gateway.auth import BasicAuthProvider
from ..controlplane.safetykernel.kernel import SafetyKernel
from ..infra.configsvc import ConfigService
from ..infra.jobstore import JobStore
from ..infra.memstore import MemoryStore
from ..infra.registry import WorkerRegistry
from ..infra.schemareg import SchemaRegistry
from ..workflow.engine import Engine as WorkflowEngine
from ..workflow.store import WorkflowStore
from . import _boot


async def main() -> None:
    cfg = _boot.setup()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    configsvc = ConfigService(kv)
    kernel = SafetyKernel(policy_path=cfg.safety_policy_path, configsvc=configsvc)
    await kernel.reload()
    schemas = SchemaRegistry(kv)
    mem = MemoryStore(kv)
    wf_store = WorkflowStore(kv)
    # context.* workflow steps run in-engine; their embeds ride the worker
    # pool as micro-batched embed jobs (BusEmbedder, docs/WORKFLOWS.md)
    context_svc = ContextService(kv, embedder=BusEmbedder(bus, mem))
    from ..infra.metrics import Metrics

    # the embedded engine shares the gateway's registry so cordum_workflow_*
    # families land on the same /metrics surface
    metrics = Metrics()
    wf_engine = WorkflowEngine(store=wf_store, bus=bus, mem=mem, schemas=schemas,
                               configsvc=configsvc, instance_id="gateway-wf",
                               metrics=metrics, context_svc=context_svc)
    # SLO objectives + admission-control config come from the pools.yaml
    # slo:/admission: stanzas; an unreadable pool file must not stop the
    # gateway (it just runs without burn tracking or load shedding)
    try:
        from ..infra.config import load_pool_config

        _pool_cfg = load_pool_config(cfg.pool_config_path)
        slo_config = _pool_cfg.slo
        admission_config = _pool_cfg.admission
    except Exception as e:  # noqa: BLE001 - telemetry config is best-effort
        from ..infra import logging as logx

        logx.warn("pool config unreadable; fleet SLO tracking disabled",
                  path=cfg.pool_config_path, err=str(e))
        slo_config = {}
        admission_config = {}
    admin_keys = [k for k in os.environ.get("CORDUM_ADMIN_KEYS", "").split(",") if k]
    # CORDUM_KEY_TENANTS="key1:tenantA,key2:tenantB" scopes keys to tenants
    key_tenants: dict[str, str] = {}
    for pair in os.environ.get("CORDUM_KEY_TENANTS", "").split(","):
        k, sep, t = pair.partition(":")
        if sep and k and t:
            key_tenants[k] = t
    gw = Gateway(
        kv=kv, bus=bus, job_store=JobStore(kv), mem=mem, kernel=kernel, metrics=metrics,
        wf_store=wf_store, wf_engine=wf_engine, schemas=schemas, configsvc=configsvc,
        registry=WorkerRegistry(), context_svc=context_svc,
        auth=BasicAuthProvider(
            cfg.api_keys, admin_keys=admin_keys,
            default_tenant=os.environ.get("CORDUM_DEFAULT_TENANT", "default"),
            key_tenants=key_tenants,
        ),
        rate_rps=_boot.env_float("API_RATE_LIMIT_RPS", 0.0),
        max_concurrent_runs=_boot.env_int("MAX_CONCURRENT_RUNS", 0),
        scheduler_shards=cfg.scheduler_shards,
        slo_config=slo_config,
        admission_config=admission_config,
        # tail-based trace retention: < 1.0 keeps every slower-than-p95
        # trace and samples the fast rest (docs/OBSERVABILITY.md)
        trace_keep_fraction=_boot.env_float("CORDUM_TRACE_KEEP_FRACTION", 1.0),
    )
    host, _, port = cfg.gateway_http_addr.partition(":")
    await gw.start(host or "127.0.0.1", int(port or 8081))
    try:
        await _boot.wait_for_shutdown()
    finally:
        await gw.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
