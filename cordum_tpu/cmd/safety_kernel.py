"""Safety-kernel service binary (reference ``cmd/cordum-safety-kernel``)."""
from __future__ import annotations

import asyncio
import os

from ..controlplane.safetykernel.kernel import SafetyKernel
from ..controlplane.safetykernel.service import KernelService
from ..infra.configsvc import ConfigService
from . import _boot


async def main() -> None:
    cfg = _boot.setup()
    configsvc = None
    conn = None
    tracer = None
    telemetry = profiler = None
    if cfg.statebus_url:
        kv, bus, conn = await _boot.connect_statebus(cfg)
        configsvc = ConfigService(kv)
        from ..infra.metrics import Metrics
        from ..obs.profiler import RuntimeProfiler
        from ..obs.telemetry import TelemetryExporter
        from ..obs.tracer import Tracer

        tracer = Tracer("safety-kernel", bus)
        metrics = Metrics()
        profiler = RuntimeProfiler(metrics, service="safety-kernel")
        telemetry = TelemetryExporter(
            "safety-kernel", bus, metrics,
            instance_id=os.environ.get("SAFETY_KERNEL_ID", "safety-kernel-0"),
            health_fn=lambda: {"role": "safety-kernel", **profiler.health()},
        )
    kernel = SafetyKernel(policy_path=cfg.safety_policy_path, configsvc=configsvc)
    svc = KernelService(kernel, reload_interval_s=_boot.env_float("SAFETY_RELOAD_INTERVAL", 30.0),
                        tracer=tracer)
    host = os.environ.get("SAFETY_KERNEL_HOST", "127.0.0.1")
    port = _boot.env_int("SAFETY_KERNEL_PORT", 7430)
    await svc.start(host, port)
    if telemetry is not None:
        await telemetry.start()
        await profiler.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        if telemetry is not None:
            await profiler.stop()
            await telemetry.stop()
        await svc.stop()
        if conn:
            await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
