"""Scheduler service binary (reference ``cmd/cordum-scheduler/main.go:24-179``):
statebus connection → job store → safety client (remote kernel or embedded)
→ pool config + overlay bootstrap/watch → engine + reconciler + pending
replayer + worker-snapshot writer → shutdown on signal.

Keyspace sharding: run N copies with ``--shard-index i --shard-count n``
(or SCHEDULER_SHARD_INDEX / SCHEDULER_SHARD_COUNT, or ``scheduler.shards``
in pools.yaml for the count); shard i owns every job whose
``partition_of(job_id, n) == i`` and consumes ``sys.job.submit.<i>`` /
``result.<i>`` / ``cancel.<i>`` with no cross-shard locks."""
from __future__ import annotations

import argparse
import asyncio
import os

import yaml

from ..controlplane.safetykernel.kernel import SafetyKernel
from ..controlplane.safetykernel.service import remote_check
from ..controlplane.scheduler.engine import Engine
from ..controlplane.scheduler.overlay import ConfigOverlay, WorkerSnapshotWriter
from ..controlplane.scheduler.reconciler import (
    PendingReplayer,
    Reconciler,
    WorkerFailover,
)
from ..controlplane.scheduler.safety_client import SafetyClient
from ..controlplane.scheduler.strategy import (
    LeastLoadedStrategy,
    ThroughputAwareStrategy,
)
from ..infra import logging as logx
from ..infra.configsvc import ConfigService
from ..infra.jobstore import JobStore
from ..infra.metrics import Metrics
from ..infra.registry import WorkerRegistry
from ..infra.config import load_pool_config, load_timeouts
from . import _boot


def _shard_args() -> tuple[int, int]:
    """CLI flags > env vars > pools.yaml ``scheduler.shards`` (count only)."""
    ap = argparse.ArgumentParser(description="cordum scheduler shard")
    ap.add_argument("--shard-index", type=int,
                    default=_boot.env_int("SCHEDULER_SHARD_INDEX", 0))
    ap.add_argument("--shard-count", type=int,
                    default=_boot.env_int("SCHEDULER_SHARD_COUNT", 0))
    ns, _ = ap.parse_known_args()
    return ns.shard_index, ns.shard_count


async def main() -> None:
    cfg = _boot.setup()
    shard_index, shard_count = _shard_args()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    job_store = JobStore(kv)
    configsvc = ConfigService(kv)
    # SCHEDULER_REGISTRY_TTL bounds dead-worker detection: a worker whose
    # heartbeats stop for this long is expired and its in-flight jobs fail
    # over (WorkerFailover)
    registry = WorkerRegistry(
        ttl_s=_boot.env_float("SCHEDULER_REGISTRY_TTL", 30.0)
    )

    pool_cfg = load_pool_config(cfg.pool_config_path)
    timeouts = load_timeouts(cfg.timeout_config_path)
    # one registry shared by strategy (session-affinity counters) and engine
    metrics = Metrics()
    # capacity-aware routing (docs/ADMISSION.md §Routing) is the default:
    # the strategy consumes the workers' capacity beacons and degrades to
    # exact LeastLoaded behavior while the matrix is cold/stale.
    # SCHEDULER_STRATEGY=least_loaded opts out.
    capacity_view = None
    rebalancer = None
    if os.environ.get("SCHEDULER_STRATEGY", "throughput") == "least_loaded":
        strategy = LeastLoadedStrategy(registry, pool_cfg, metrics=metrics)
    else:
        from ..controlplane.scheduler.placer import (
            DecodeRebalancer,
            ServingPlacer,
        )
        from ..obs.capacity import CapacityView

        capacity_view = CapacityView()
        # disaggregated serving placement (docs/SERVING.md §Disaggregation):
        # new llm.generate sessions route by measured prefill tokens/s
        # headroom; the decode rebalancer migrates sessions off skewed
        # workers (SCHEDULER_REBALANCER=0 / rebalancer.enabled opt out)
        strategy = ThroughputAwareStrategy(
            registry, pool_cfg, capacity=capacity_view,
            placer=ServingPlacer(capacity_view, metrics=metrics),
            metrics=metrics,
        )
        if os.environ.get("SCHEDULER_REBALANCER", "1") != "0":
            rebalancer = DecodeRebalancer.from_config(
                bus, capacity_view, registry, pool_cfg.rebalancer,
                metrics=metrics,
            )
    if shard_count <= 0:  # flag/env unset: pools.yaml scheduler.shards
        shard_count = pool_cfg.scheduler_shards

    kernel_addr = cfg.safety_kernel_addr
    if kernel_addr:
        # remote kernel: span context rides the RPC headers; the kernel
        # service emits its own evaluate spans
        check_fn = remote_check(kernel_addr)
    else:  # embedded kernel (single-binary deployments)
        from ..obs.tracer import Tracer

        kernel = SafetyKernel(policy_path=cfg.safety_policy_path, configsvc=configsvc,
                              tracer=Tracer("safety-kernel", bus))
        await kernel.reload()
        check_fn = kernel.check
    safety = SafetyClient(check_fn)

    engine = Engine(
        bus=bus, job_store=job_store, safety=safety, strategy=strategy,
        registry=registry, configsvc=configsvc, metrics=metrics,
        instance_id=os.environ.get(
            "SCHEDULER_ID",
            f"scheduler-{shard_index}" if shard_count > 1 else "scheduler-0",
        ),
        tenant_concurrency_limit=_boot.env_int("TENANT_CONCURRENCY_LIMIT", 0),
        shard_index=shard_index,
        shard_count=max(1, shard_count),
    )
    # gang scheduling (docs/GANG.md): all-or-nothing multi-worker placement
    # for jobs carrying the gateway-stamped cordum.gang_workers label;
    # SCHEDULER_GANG=0 / gang.enabled opts out
    gangs = None
    gang_cfg = pool_cfg.gang
    if (
        os.environ.get("SCHEDULER_GANG", "1") != "0"
        and gang_cfg.get("enabled", True)
    ):
        from ..controlplane.scheduler.gang import GangScheduler

        gangs = GangScheduler(
            engine, pool_cfg,
            rendezvous_timeout_s=float(
                gang_cfg.get("rendezvous_timeout_s", 10.0)),
            queued_timeout_s=float(gang_cfg.get("queued_timeout_s", 300.0)),
        )
    reconciler = Reconciler(job_store, timeouts, instance_id=engine.instance_id)
    replayer = PendingReplayer(engine, job_store, timeouts)
    # serving-session crash failover: dead workers' in-flight jobs are
    # re-dispatched (with the streamed-token resume prefix) instead of
    # waiting out the running timeout (docs/SERVING.md)
    failover = WorkerFailover(engine, job_store, registry, timeouts)

    # fleet telemetry plane (docs/OBSERVABILITY.md §Fleet telemetry): this
    # shard's registry + a health beacon carrying its shard coordinates and
    # live queue depth, plus the runtime profiler feeding loop/GC health
    # into the same registry
    from ..obs.profiler import RuntimeProfiler
    from ..obs.telemetry import TelemetryExporter

    profiler = RuntimeProfiler(metrics, service="scheduler")

    def _telemetry_health() -> dict:
        out = {
            "role": "scheduler",
            "shard_index": engine.shard_index,
            "shard_count": engine.shard_count,
            "queue_depth": engine._inflight,
            "jobs_scheduled": metrics.jobs_dispatched.total(),
            "workers_live": len(registry.snapshot()),
            **profiler.health(),
        }
        if gangs is not None:
            # live gang table (docs/GANG.md): merged fleet-wide by the
            # gateway aggregator into GET /api/v1/gangs
            out["gangs"] = gangs.doc()
            out["gang_queue_depth"] = len(gangs._fifo)
        return out

    telemetry = TelemetryExporter(
        "scheduler", bus, metrics,
        instance_id=engine.instance_id, health_fn=_telemetry_health,
    )
    overlay = ConfigOverlay(
        configsvc, strategy, reconciler,
        interval_s=_boot.env_float("SCHEDULER_CONFIG_RELOAD_INTERVAL", 30.0),
    )
    snapshotter = WorkerSnapshotWriter(kv, registry)

    def _load_yaml(path: str) -> dict:
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return yaml.safe_load(f) or {}

    # config reads happen off the event loop (CL003): startup shares the
    # loop with the bus connection heartbeats
    pools_doc = await asyncio.to_thread(_load_yaml, cfg.pool_config_path)
    timeouts_doc = await asyncio.to_thread(_load_yaml, cfg.timeout_config_path)
    await overlay.bootstrap(pools_doc, timeouts_doc)

    if capacity_view is not None:
        await capacity_view.start(bus)

    # session ownership announcements (docs/SERVING.md §Disaggregation):
    # a migration commit retargets the session's affinity entry so
    # follow-up turns/cancels route to the new page-holding worker
    from ..protocol import subjects as subj

    async def _on_session_moved(subject: str, pkt) -> None:
        mv = pkt.session_moved
        if mv is not None and mv.session_key:
            # reason="hibernated": the session's KV went to the worker's
            # host-RAM cold arena — pin its affinity past the normal TTL so
            # the next turn routes back to the only copy; "restored" (and
            # every migration reason) retargets normally, which unpins
            strategy.retarget_session(
                mv.session_key, mv.to_worker,
                pinned=(mv.reason == "hibernated"),
            )

    moved_sub = await bus.subscribe(subj.SERVING_MOVED, _on_session_moved)
    await engine.start()
    if gangs is not None:
        await gangs.start()
    await reconciler.start()
    await replayer.start()
    await failover.start()
    await overlay.start()
    await snapshotter.start()
    await telemetry.start()
    await profiler.start()
    if rebalancer is not None:
        await rebalancer.start()
    logx.info("scheduler running", instance=engine.instance_id,
              shard=engine.shard_index, shards=engine.shard_count)
    try:
        await _boot.wait_for_shutdown()
    finally:
        if rebalancer is not None:
            await rebalancer.stop()
        moved_sub.unsubscribe()
        if gangs is not None:
            await gangs.stop()
        await profiler.stop()
        await telemetry.stop()
        await snapshotter.stop()
        await overlay.stop()
        await failover.stop()
        await replayer.stop()
        await reconciler.stop()
        await engine.stop()
        if capacity_view is not None:
            await capacity_view.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
