"""Statebus server binary: ``python -m cordum_tpu.cmd.statebus``.

``STATEBUS_PARTITIONS=N`` serves N keyspace partitions from one process on
consecutive ports (STATEBUS_PORT .. STATEBUS_PORT+N-1), each with its own
AOF (``<STATEBUS_AOF>.<p>``) — the dev/smoke topology.  Production runs one
process per partition: ``STATEBUS_PARTITION_INDEX=p`` starts only partition
``p`` on ``STATEBUS_PORT+p``.  Clients list every endpoint in
``CORDUM_STATEBUS_URL`` (comma-separated) and route by keyspace.
"""
from __future__ import annotations

import asyncio
import os

from ..infra.statebus import StateBusServer
from . import _boot


def _aof_path(base: str, partition: int, partitions: int) -> str:
    if not base:
        return ""
    return base if partitions <= 1 else f"{base}.{partition}"


async def main() -> None:
    _boot.setup()
    host = os.environ.get("STATEBUS_HOST", "127.0.0.1")
    port = _boot.env_int("STATEBUS_PORT", 7420)
    aof = os.environ.get("STATEBUS_AOF", "")
    partitions = max(1, _boot.env_int("STATEBUS_PARTITIONS", 1))
    only = _boot.env_int("STATEBUS_PARTITION_INDEX", -1)
    indices = [only] if 0 <= only < partitions else list(range(partitions))
    servers = [
        StateBusServer(host, port + p, aof_path=_aof_path(aof, p, partitions))
        for p in indices
    ]
    for srv in servers:
        await srv.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        for srv in servers:
            await srv.stop()


if __name__ == "__main__":
    asyncio.run(main())
