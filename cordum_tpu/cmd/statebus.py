"""Statebus server binary: ``python -m cordum_tpu.cmd.statebus``.

``STATEBUS_PARTITIONS=N`` serves N keyspace partitions from one process on
consecutive ports (STATEBUS_PORT .. STATEBUS_PORT+N-1), each with its own
AOF (``<STATEBUS_AOF>.<p>``) — the dev/smoke topology.  Production runs one
process per partition: ``STATEBUS_PARTITION_INDEX=p`` starts only partition
``p`` on ``STATEBUS_PORT+p``.  Clients list every endpoint in
``CORDUM_STATEBUS_URL`` (comma-separated; ``|``-separated replica sets per
partition) and route by keyspace.

Replication (docs/PROTOCOL.md §Replication): start a partition's replica
with ``--replica-of statebus://host:port`` (env ``STATEBUS_REPLICA_OF``).
The replica tails the primary's committed-record stream, serves reads, and
is promoted on primary failure — automatically after
``STATEBUS_HEARTBEAT_TIMEOUT`` quiet seconds (disable with
``STATEBUS_AUTO_PROMOTE=0``), or explicitly via the admin ``promote``
frame (``cordumctl statebus promote``).  ``STATEBUS_PEERS`` lists the
partition's full replica set so a restarted old primary probes its peers
and demotes itself when a higher-epoch primary exists (no split-brain).
``STATEBUS_SYNC_REPLICATION=1`` makes every commit wait for one replica
ack before the client sees ok (zero acked-commit loss on primary death).
Defaults for the replication knobs may also come from the ``statebus:``
stanza in pools.yaml (env wins).
"""
from __future__ import annotations

import argparse
import asyncio
import os

from ..infra.statebus import StateBusServer
from . import _boot


def _aof_path(base: str, partition: int, partitions: int) -> str:
    if not base:
        return ""
    return base if partitions <= 1 else f"{base}.{partition}"


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v.strip().lower() not in ("0", "false", "no", "off")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="cordum-statebus", description="statebus partition server")
    p.add_argument("--replica-of", default=os.environ.get("STATEBUS_REPLICA_OF", ""),
                   help="primary endpoint this server replicates "
                        "(statebus://host:port); empty = start as primary")
    p.add_argument("--peers", default=os.environ.get("STATEBUS_PEERS", ""),
                   help="comma-separated replica-set endpoints for this "
                        "partition (startup probe demotes a stale primary)")
    p.add_argument("--sync-replication", action="store_true",
                   default=_env_bool("STATEBUS_SYNC_REPLICATION", False),
                   help="commits wait for one replica ack before acking")
    p.add_argument("--no-auto-promote", action="store_true",
                   default=not _env_bool("STATEBUS_AUTO_PROMOTE", True),
                   help="never self-promote on primary-dead (admin-only)")
    return p.parse_args(argv)


def _pool_statebus_defaults() -> dict:
    """The pools.yaml ``statebus:`` stanza (missing file → {}); env wins.

    Read with a bare yaml.safe_load, NOT the full config loader: its
    jsonschema import chain costs close to a second on small hosts, and
    the statebus must bind before the rest of the stack dials in
    (``cordumctl up`` / platform_smoke give it well under a second).  The
    stanza still schema-validates wherever the full config IS loaded
    (scheduler, tests)."""
    path = os.environ.get("POOL_CONFIG_PATH", "config/pools.yaml")
    try:
        import yaml

        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        return dict(doc.get("statebus") or {})
    except Exception:  # noqa: BLE001 - optional defaults; env still applies
        return {}


async def main(argv=None) -> None:
    _boot.setup()
    args = parse_args(argv)
    defaults = _pool_statebus_defaults()
    host = os.environ.get("STATEBUS_HOST", "127.0.0.1")
    port = _boot.env_int("STATEBUS_PORT", 7420)
    aof = os.environ.get("STATEBUS_AOF", "")
    partitions = max(1, _boot.env_int("STATEBUS_PARTITIONS", 1))
    only = _boot.env_int("STATEBUS_PARTITION_INDEX", -1)
    sync = args.sync_replication or bool(defaults.get("sync_replication"))
    hb_timeout = _boot.env_float(
        "STATEBUS_HEARTBEAT_TIMEOUT",
        float(defaults.get("heartbeat_timeout_s", 3.0)))
    hb_interval = _boot.env_float(
        "STATEBUS_HEARTBEAT_INTERVAL", min(1.0, max(0.05, hb_timeout / 3)))
    peers = tuple(p.strip() for p in args.peers.split(",") if p.strip())
    indices = [only] if 0 <= only < partitions else list(range(partitions))
    servers = [
        StateBusServer(
            host, port + p, aof_path=_aof_path(aof, p, partitions),
            replica_of=args.replica_of, peers=peers,
            sync_replication=sync, auto_promote=not args.no_auto_promote,
            heartbeat_interval_s=hb_interval, heartbeat_timeout_s=hb_timeout,
            partition=p if partitions > 1 or only >= 0 else -1,
        )
        for p in indices
    ]
    for srv in servers:
        await srv.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        # SIGTERM path: each stop() fsyncs the AOF and broadcasts GOAWAY so
        # clients fail over immediately instead of waiting out heartbeats
        for srv in servers:
            await srv.stop()


if __name__ == "__main__":
    asyncio.run(main())
