"""Statebus server binary: ``python -m cordum_tpu.cmd.statebus``."""
from __future__ import annotations

import asyncio
import os

from ..infra.statebus import StateBusServer
from . import _boot


async def main() -> None:
    _boot.setup()
    host = os.environ.get("STATEBUS_HOST", "127.0.0.1")
    port = _boot.env_int("STATEBUS_PORT", 7420)
    aof = os.environ.get("STATEBUS_AOF", "")
    srv = StateBusServer(host, port, aof_path=aof)
    await srv.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        await srv.stop()


if __name__ == "__main__":
    asyncio.run(main())
