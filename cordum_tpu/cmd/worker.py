"""TPU worker binary: the in-tree worker that owns a slice and executes jobs
as JAX computations (the north star's ``sdk/runtime`` TPU worker).

Env: WORKER_ID, WORKER_POOL, WORKER_TOPICS (comma), WORKER_CAPABILITIES,
WORKER_MAX_PARALLEL, WORKER_TP (tensor-parallel width for the local mesh).
"""
from __future__ import annotations

import asyncio
import os

if os.environ.get("CORDUM_FORCE_CPU") == "1":
    # neutralize the axon sitecustomize platform override BEFORE any jax
    # backend initializes (the TPU grant is exclusive; CI/smoke runs must
    # not claim it)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

from ..infra.memstore import MemoryStore
from ..worker.handlers import attach_default_tpu_worker
from ..worker.runtime import Worker
from . import _boot


async def main() -> None:
    cfg = _boot.setup()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    env = os.environ
    worker = Worker(
        bus=bus,
        store=MemoryStore(kv),
        worker_id=env.get("WORKER_ID", f"tpu-worker-{os.getpid()}"),
        pool=env.get("WORKER_POOL", "tpu-default"),
        topics=[t for t in env.get("WORKER_TOPICS", "job.tpu.>").split(",") if t],
        capabilities=[c for c in env.get("WORKER_CAPABILITIES", "tpu,echo").split(",") if c],
        max_parallel_jobs=_boot.env_int("WORKER_MAX_PARALLEL", 4),
        heartbeat_interval_s=_boot.env_float("WORKER_HEARTBEAT_INTERVAL", 10.0),
        region=env.get("WORKER_REGION", ""),
    )
    attach_default_tpu_worker(worker, tp=_boot.env_int("WORKER_TP", 1))
    await worker.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        await worker.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
