"""TPU worker binary: the in-tree worker that owns a slice and executes jobs
as JAX computations (the north star's ``sdk/runtime`` TPU worker).

Env: WORKER_ID, WORKER_POOL, WORKER_TOPICS (comma), WORKER_CAPABILITIES,
WORKER_MAX_PARALLEL, WORKER_TP (tensor-parallel width for the local mesh).

Micro-batching (cordum_tpu/batching) is on by default; limits come from the
worker's pool stanza in pools.yaml (``max_batch_size`` /
``max_batch_wait_ms``), overridable via WORKER_MAX_BATCH_SIZE /
WORKER_BATCH_WAIT_MS, and WORKER_BATCHING=0 disables it.

Serving (cordum_tpu/serving, ``llm.generate``) is on by default too; the
pool stanza's ``serving_cache_pages`` / ``serving_page_size`` /
``serving_max_sessions`` / ``serving_max_new_tokens`` /
``serving_prefill_budget`` size the paged KV cache, admission control, and
the ragged step's chunked-prefill token budget, overridable via
WORKER_SERVING_CACHE_PAGES / WORKER_SERVING_PAGE_SIZE /
WORKER_SERVING_MAX_SESSIONS / WORKER_SERVING_MAX_NEW_TOKENS /
WORKER_SERVING_PREFILL_BUDGET, and WORKER_SERVING=0 disables the engine.
Disaggregation (docs/SERVING.md §Disaggregation): WORKER_SERVING_ROLE
(prefill | decode | mixed, or the pool's ``serving_role``) sets the
placement role — a "prefill" worker live-migrates each session to the
best decode peer once its prompt finishes prefilling, or earlier once
prefill crosses WORKER_SERVING_HANDOFF_TOKENS (``serving_handoff_tokens``).
Prefix cache + tiering (docs/SERVING.md §Prefix cache and tiering):
WORKER_SERVING_PREFIX_CACHE=0 (``serving_prefix_cache``) disables
copy-on-write shared-prefix KV pages; WORKER_SERVING_HIBERNATE_AFTER
(``serving_hibernate_after_s``, seconds) > 0 tiers cached prefixes idle
past the threshold into the host-RAM cold arena and pins the session's
scheduler affinity until the next turn restores them.
WORKER_SERVING_COLD_TIER=statebus (``serving_cold_tier``) journals the
cold arena through the statebus KV so hibernated sessions survive a
worker restart (restored on boot, re-admitted on the next turn).
Speculative decoding (docs/SERVING.md §Speculative decoding):
WORKER_SERVING_SPECULATIVE=0 (``serving_speculative``) disables the
zero-extra-weights n-gram drafter inside the ragged step;
WORKER_SERVING_DRAFT_K (``serving_draft_k``) caps tokens drafted per
session per step (0 = engine default).

Graceful drain (docs/SERVING.md §Migration, drain, and failover): SIGTERM
(unless WORKER_DRAIN_ON_TERM=0) and ``cordumctl drain <worker>`` both put
the worker in drain mode — stop admitting, live-migrate serving sessions
to peers, finish per-job work (WORKER_DRAIN_TIMEOUT, default 30s), then
exit with zero CANCELLED sessions.  WORKER_LLAMA_DTYPE (float32|bfloat16)
overrides the tiny model's dtype — the chaos suite pins float32 so resumed
token streams compare exactly against the fp32 sequential oracle.
"""
from __future__ import annotations

import asyncio
import os
import signal

if os.environ.get("CORDUM_FORCE_CPU") == "1":
    # neutralize the axon sitecustomize platform override BEFORE any jax
    # backend initializes (the TPU grant is exclusive; CI/smoke runs must
    # not claim it)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

from ..infra.memstore import MemoryStore
from ..infra.metrics import Metrics
from ..obs.profiler import RuntimeProfiler
from ..obs.telemetry import TelemetryExporter
from ..worker.handlers import attach_default_tpu_worker
from ..worker.runtime import Worker
from . import _boot


def _pool_limits(cfg, pool_name: str):
    """This worker's pool stanza from pools.yaml (None = defaults).
    A missing or invalid pool file must not stop a worker from booting."""
    try:
        from ..infra.config import load_pool_config

        return load_pool_config(cfg.pool_config_path).pools.get(pool_name)
    except Exception as e:  # noqa: BLE001 - batching/serving config is best-effort
        from ..infra import logging as logx

        logx.warn("pool config unreadable; using built-in worker defaults",
                  path=cfg.pool_config_path, err=str(e))
        return None


async def main() -> None:
    cfg = _boot.setup()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    env = os.environ
    pool_name = env.get("WORKER_POOL", "tpu-default")
    pool = _pool_limits(cfg, pool_name)
    worker = Worker(
        bus=bus,
        store=MemoryStore(kv),
        worker_id=env.get("WORKER_ID", f"tpu-worker-{os.getpid()}"),
        pool=pool_name,
        topics=[t for t in env.get("WORKER_TOPICS", "job.tpu.>").split(",") if t],
        capabilities=[c for c in env.get("WORKER_CAPABILITIES", "tpu,echo").split(",") if c],
        max_parallel_jobs=_boot.env_int("WORKER_MAX_PARALLEL", 4),
        heartbeat_interval_s=_boot.env_float("WORKER_HEARTBEAT_INTERVAL", 10.0),
        region=env.get("WORKER_REGION", ""),
        # prefill/decode disaggregation (docs/SERVING.md §Disaggregation):
        # "prefill" workers hand sessions to decode peers post-prefill
        serving_role=env.get("WORKER_SERVING_ROLE", "")
        or (pool.serving_role if pool else "") or "mixed",
    )
    # one registry shared by the batcher, the serving engine and the fleet
    # telemetry exporter, so worker-side metrics reach the aggregator
    metrics = Metrics()
    extra_kw = {}
    dtype_name = env.get("WORKER_LLAMA_DTYPE", "")
    if dtype_name in ("float32", "bfloat16"):
        import dataclasses

        import jax.numpy as jnp

        from ..models import llama

        extra_kw["llama_cfg"] = dataclasses.replace(
            llama.LlamaConfig.tiny(),
            dtype=jnp.float32 if dtype_name == "float32" else jnp.bfloat16,
        )
    attach_default_tpu_worker(
        worker,
        metrics=metrics,
        **extra_kw,
        tp=_boot.env_int("WORKER_TP", 1),
        batching=env.get("WORKER_BATCHING", "1") != "0",
        max_batch_rows=_boot.env_int("WORKER_MAX_BATCH_SIZE", 0)
        or (pool.max_batch_size if pool else 0) or 32,
        max_batch_wait_ms=_boot.env_float("WORKER_BATCH_WAIT_MS", 0.0)
        or (pool.max_batch_wait_ms if pool else 0.0) or 25.0,
        serving=env.get("WORKER_SERVING", "1") != "0",
        serving_cache_pages=_boot.env_int("WORKER_SERVING_CACHE_PAGES", 0)
        or (pool.serving_cache_pages if pool else 0) or 128,
        serving_page_size=_boot.env_int("WORKER_SERVING_PAGE_SIZE", 0)
        or (pool.serving_page_size if pool else 0) or 16,
        serving_max_sessions=_boot.env_int("WORKER_SERVING_MAX_SESSIONS", 0)
        or (pool.serving_max_sessions if pool else 0) or 8,
        serving_max_new_tokens=_boot.env_int("WORKER_SERVING_MAX_NEW_TOKENS", 0)
        or (pool.serving_max_new_tokens if pool else 0) or 64,
        serving_prefill_budget=_boot.env_int("WORKER_SERVING_PREFILL_BUDGET", 0)
        or (pool.serving_prefill_budget if pool else 0) or 16,
        serving_handoff_tokens=_boot.env_int("WORKER_SERVING_HANDOFF_TOKENS", 0)
        or (pool.serving_handoff_tokens if pool else 0),
        # prefix cache + tiering (docs/SERVING.md §Prefix cache and tiering)
        serving_prefix_cache=(
            env["WORKER_SERVING_PREFIX_CACHE"] != "0"
            if "WORKER_SERVING_PREFIX_CACHE" in env
            else (pool.serving_prefix_cache if pool else True)
        ),
        serving_hibernate_after_s=_boot.env_float(
            "WORKER_SERVING_HIBERNATE_AFTER", 0.0)
        or (pool.serving_hibernate_after_s if pool else 0.0),
        # self-speculative decoding (docs/SERVING.md §Speculative decoding)
        serving_speculative=(
            env["WORKER_SERVING_SPECULATIVE"] != "0"
            if "WORKER_SERVING_SPECULATIVE" in env
            else (pool.serving_speculative if pool else True)
        ),
        serving_draft_k=_boot.env_int("WORKER_SERVING_DRAFT_K", 0)
        or (pool.serving_draft_k if pool else 0),
        serving_cold_tier=env.get("WORKER_SERVING_COLD_TIER", "")
        or (pool.serving_cold_tier if pool else ""),
        # gang scheduling (docs/GANG.md): member jobs rendezvous + run the
        # SPMD/MPMD step program; WORKER_GANG=0 opts the worker out
        gang=env.get("WORKER_GANG", "1") != "0",
        gang_rendezvous_timeout_s=_boot.env_float(
            "WORKER_GANG_RENDEZVOUS_TIMEOUT", 10.0),
        gang_peer_timeout_s=_boot.env_float("WORKER_GANG_PEER_TIMEOUT", 30.0),
    )
    profiler = RuntimeProfiler(metrics, service="worker")
    telemetry = TelemetryExporter(
        "worker", bus, metrics, instance_id=worker.worker_id,
        health_fn=lambda: {**worker.telemetry_health(), **profiler.health()},
    )
    await worker.start()
    # statebus-backed cold tier: re-populate the mirror from the journal
    # so sessions hibernated before a restart are restorable here
    tiering = getattr(worker._serving, "tiering", None)
    arena = getattr(tiering, "arena", None)
    if callable(getattr(arena, "load", None)):
        await arena.load()
    await telemetry.start()
    await profiler.start()
    # SIGTERM drains by default (live-migrate sessions, finish jobs, exit);
    # SIGINT stays the immediate-stop path.  A `cordumctl drain` arriving
    # over the bus completes the same drained event.
    stop = asyncio.Event()
    drain_timeout = _boot.env_float("WORKER_DRAIN_TIMEOUT", 30.0)

    def _on_term() -> None:
        if env.get("WORKER_DRAIN_ON_TERM", "1") != "0":
            asyncio.ensure_future(worker.drain(timeout_s=drain_timeout))
        else:
            stop.set()

    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGTERM, _on_term)
        loop.add_signal_handler(signal.SIGINT, stop.set)
    except NotImplementedError:  # pragma: no cover - non-unix
        pass
    try:
        stop_w = asyncio.ensure_future(stop.wait())
        drained_w = asyncio.ensure_future(worker.wait_drained())
        done, pending = await asyncio.wait(
            {stop_w, drained_w}, return_when=asyncio.FIRST_COMPLETED
        )
        for t in pending:
            t.cancel()
    finally:
        await profiler.stop()
        await telemetry.stop()
        await worker.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
