"""Workflow-engine service binary (reference ``cmd/cordum-workflow-engine``)."""
from __future__ import annotations

import asyncio
import os

from ..controlplane.workflowengine.service import WorkflowEngineService
from ..infra.configsvc import ConfigService
from ..infra.jobstore import JobStore
from ..infra.memstore import MemoryStore
from ..infra.schemareg import SchemaRegistry
from ..workflow.engine import Engine as WorkflowEngine
from ..workflow.store import WorkflowStore
from . import _boot


async def main() -> None:
    cfg = _boot.setup()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    engine = WorkflowEngine(
        store=WorkflowStore(kv), bus=bus, mem=MemoryStore(kv),
        schemas=SchemaRegistry(kv), configsvc=ConfigService(kv),
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
    )
    svc = WorkflowEngineService(
        engine=engine, bus=bus, job_store=JobStore(kv),
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
        reconcile_interval_s=_boot.env_float("WF_RECONCILE_INTERVAL", 5.0),
    )
    from ..infra.metrics import Metrics
    from ..obs.profiler import RuntimeProfiler
    from ..obs.telemetry import TelemetryExporter

    metrics = Metrics()
    profiler = RuntimeProfiler(metrics, service="workflow-engine")
    telemetry = TelemetryExporter(
        "workflow-engine", bus, metrics,
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
        health_fn=lambda: {"role": "workflow-engine", **profiler.health()},
    )
    await svc.start()
    await telemetry.start()
    await profiler.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        await profiler.stop()
        await telemetry.stop()
        await svc.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
