"""Workflow-engine service binary (reference ``cmd/cordum-workflow-engine``)."""
from __future__ import annotations

import asyncio
import os

from ..controlplane.workflowengine.service import WorkflowEngineService
from ..infra.configsvc import ConfigService
from ..infra.jobstore import JobStore
from ..infra.memstore import MemoryStore
from ..infra.schemareg import SchemaRegistry
from ..workflow.engine import Engine as WorkflowEngine
from ..workflow.store import WorkflowStore
from . import _boot


async def main() -> None:
    cfg = _boot.setup()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    engine = WorkflowEngine(
        store=WorkflowStore(kv), bus=bus, mem=MemoryStore(kv),
        schemas=SchemaRegistry(kv), configsvc=ConfigService(kv),
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
    )
    svc = WorkflowEngineService(
        engine=engine, bus=bus, job_store=JobStore(kv),
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
        reconcile_interval_s=_boot.env_float("WF_RECONCILE_INTERVAL", 5.0),
    )
    await svc.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        await svc.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
