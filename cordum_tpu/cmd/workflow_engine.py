"""Workflow-engine service binary (reference ``cmd/cordum-workflow-engine``)."""
from __future__ import annotations

import asyncio
import os

from ..context.service import BusEmbedder, ContextService
from ..controlplane.workflowengine.service import WorkflowEngineService
from ..infra.configsvc import ConfigService
from ..infra.jobstore import JobStore
from ..infra.memstore import MemoryStore
from ..infra.schemareg import SchemaRegistry
from ..workflow.engine import Engine as WorkflowEngine
from ..workflow.store import WorkflowStore
from . import _boot


async def main() -> None:
    cfg = _boot.setup()
    kv, bus, conn = await _boot.connect_statebus(cfg)
    from ..infra.metrics import Metrics

    mem = MemoryStore(kv)
    # ONE Metrics registry shared between engine and telemetry exporter, so
    # the cordum_workflow_* families actually reach the fleet plane
    metrics = Metrics()
    engine = WorkflowEngine(
        store=WorkflowStore(kv), bus=bus, mem=mem,
        schemas=SchemaRegistry(kv), configsvc=ConfigService(kv),
        metrics=metrics,
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
        context_svc=ContextService(kv, embedder=BusEmbedder(bus, mem)),
    )
    svc = WorkflowEngineService(
        engine=engine, bus=bus, job_store=JobStore(kv),
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
        reconcile_interval_s=_boot.env_float("WF_RECONCILE_INTERVAL", 5.0),
    )
    from ..obs.profiler import RuntimeProfiler
    from ..obs.telemetry import TelemetryExporter

    profiler = RuntimeProfiler(metrics, service="workflow-engine")
    telemetry = TelemetryExporter(
        "workflow-engine", bus, metrics,
        instance_id=os.environ.get("WF_ENGINE_ID", "wf-engine-0"),
        health_fn=lambda: {"role": "workflow-engine", **profiler.health()},
    )
    await svc.start()
    await telemetry.start()
    await profiler.start()
    try:
        await _boot.wait_for_shutdown()
    finally:
        await profiler.stop()
        await telemetry.stop()
        await svc.stop()
        await conn.close()


if __name__ == "__main__":
    asyncio.run(main())
