"""Context engine: memory-backed window building with TPU embedding recall.

Recreates the reference context engine's API (``core/context/engine/
service.go:55-176``): ``build_window(memory_id, mode, payload, budgets)`` →
list of model messages; ``update_memory`` appends chat events/summaries.
Memory lives under ``mem:<memory_id>:*`` keys.

TPU-native upgrade (the north-star headline): RAG recall is *semantic* —
chunks are embedded on the TPU worker pool (or a local embedder) and ranked
by cosine similarity against the query, instead of the reference's
substring ``file_path`` matching.  Embeddings are cached per chunk in the
KV store so re-indexing is incremental.

Token budget trimming keeps the reference's 4-chars≈1-token estimate.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from ..infra.kv import KV
from ..protocol import subjects as subj
from ..protocol.types import (
    BusPacket,
    JobRequest,
    JobState,
    LABEL_BATCH_KEY,
    LABEL_OP,
    TERMINAL_STATES,
)
from ..utils.ids import new_id

HISTORY_WINDOW = 20  # last-N chat events (reference service.go:55-132)
HISTORY_CAP = 500
DEFAULT_MAX_INPUT_TOKENS = 4000

MODE_RAW = "RAW"
MODE_CHAT = "CHAT"
MODE_RAG = "RAG"


@dataclass
class ModelMessage:
    role: str = "user"
    content: str = ""
    source: str = ""

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def estimate_tokens(text: str) -> int:
    """Reference estimate: 4 chars ≈ 1 token (service.go:271)."""
    return max(1, len(text) // 4)


def _events_key(memory_id: str) -> str:
    return f"mem:{memory_id}:events"


def _summary_key(memory_id: str) -> str:
    return f"mem:{memory_id}:summary"


def _chunks_key(memory_id: str) -> str:
    return f"mem:{memory_id}:chunks"


def _embed_key(memory_id: str, chunk_hash: str) -> str:
    return f"mem:{memory_id}:embed:{chunk_hash}"


class EmbedFn:
    """Anything with embed(texts) -> array[N, D]; the Embedder model or a
    TPU-pool-dispatching client."""

    def embed(self, texts: Sequence[str]) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError


class PoolEmbedder(EmbedFn):
    """EmbedFn that runs on the TPU worker pool instead of in-process.

    Texts are split into per-job slices and submitted through the gateway's
    bulk endpoint (``POST /api/v1/jobs:batch``) in ONE HTTP round trip; the
    scheduler's batch affinity routes the slices to one worker, whose
    micro-batcher fuses them into a single padded XLA call
    (docs/BATCHING.md).  Synchronous (httpx.Client) — meant for re-indexing
    tools and benches, not for calling inside an event loop."""

    def __init__(
        self,
        base_url: str,
        *,
        api_key: str = "",
        topic: str = "job.tpu.embed",
        texts_per_job: int = 16,
        timeout_s: float = 120.0,
        poll_s: float = 0.05,
    ) -> None:
        import httpx

        headers = {"X-Api-Key": api_key} if api_key else {}
        self._c = httpx.Client(base_url=base_url, headers=headers, timeout=timeout_s)
        self.topic = topic
        self.texts_per_job = max(1, texts_per_job)
        self.timeout_s = timeout_s
        self.poll_s = poll_s

    def close(self) -> None:
        self._c.close()

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        import time

        jobs = [
            {"topic": self.topic,
             "payload": {"op": "embed", "texts": list(texts[i:i + self.texts_per_job])}}
            for i in range(0, len(texts), self.texts_per_job)
        ]
        r = self._c.post("/api/v1/jobs:batch", json={"jobs": jobs})
        r.raise_for_status()
        docs = r.json()["jobs"]
        parts: list[np.ndarray] = []
        deadline = time.monotonic() + self.timeout_s
        for doc in docs:
            jid = doc.get("job_id")
            if not jid:
                raise RuntimeError(f"bulk submit rejected a slice: {doc}")
            while True:
                s = self._c.get(f"/api/v1/jobs/{jid}?result=true").json()
                state = s.get("state")
                if state == "SUCCEEDED":
                    parts.append(np.asarray(s["result"]["embeddings"], np.float32))
                    break
                if state in ("FAILED", "DENIED", "CANCELLED", "TIMEOUT"):
                    raise RuntimeError(f"embed job {jid} reached {state}: "
                                       f"{s.get('error_message', '')}")
                if time.monotonic() > deadline:
                    raise TimeoutError(f"embed job {jid} not terminal "
                                       f"after {self.timeout_s}s")
                time.sleep(self.poll_s)
        return np.concatenate(parts, axis=0)


class BusEmbedder(EmbedFn):
    """Async EmbedFn that runs embeds on the TPU worker pool over the bus.

    The engine-side counterpart of :class:`PoolEmbedder`: same per-job
    slicing, but bus-native and non-blocking, so it is safe to await from
    inside the control plane's event loop (``context.*`` workflow steps —
    PoolEmbedder's synchronous polling would deadlock there, since the
    results it waits for are produced by the same loop).  Each slice is a
    normal JobRequest on ``sys.job.submit`` stamped with the batch-affinity
    labels, so the scheduler coalesces concurrent slices onto one worker's
    micro-batcher exactly like gateway-submitted embeds
    (docs/BATCHING.md)."""

    def __init__(
        self,
        bus: Any,
        mem: Any,
        *,
        topic: str = "job.tpu.embed",
        texts_per_job: int = 16,
        timeout_s: float = 60.0,
        tenant_id: str = "",
    ) -> None:
        self.bus = bus
        self.mem = mem  # MemoryStore: pointers in, pointers out
        self.topic = topic
        self.texts_per_job = max(1, texts_per_job)
        self.timeout_s = timeout_s
        self.tenant_id = tenant_id
        self.embeds_total = 0  # texts embedded (bench: context_embeds_per_sec)
        self.jobs_total = 0
        self._pending: dict[str, asyncio.Future] = {}
        self._subs: list = []

    async def start(self) -> None:
        """Plain (non-queue-group) result subscriptions: see every result
        broadcast alongside the scheduler/engine queue groups, filter by
        our own job ids.  Both the plain subject and the partition-stamped
        ``sys.job.result.<p>`` variants are covered — under a sharded
        scheduler the worker echoes the owning shard's partition, so the
        embed results never ride the plain subject.  Lazy — first
        ``aembed`` call attaches them."""
        if not self._subs:
            self._subs.append(await self.bus.subscribe(subj.RESULT, self._on_result))
            self._subs.append(
                await self.bus.subscribe(f"{subj.RESULT}.>", self._on_result)
            )

    async def stop(self) -> None:
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        for fut in self._pending.values():
            if not fut.done():
                fut.cancel()
        self._pending.clear()

    async def _on_result(self, subject: str, pkt: BusPacket) -> None:
        res = pkt.job_result
        if res is None or res.job_id not in self._pending:
            return
        if res.status not in (s.value for s in TERMINAL_STATES):
            return  # RUNNING hint; keep waiting for the terminal state
        fut = self._pending.pop(res.job_id)
        if not fut.done():
            fut.set_result(res)

    def embed(self, texts: Sequence[str]) -> np.ndarray:  # pragma: no cover
        raise RuntimeError("BusEmbedder is async-only; await aembed(texts)")

    async def aembed(self, texts: Sequence[str]) -> np.ndarray:
        await self.start()
        loop = asyncio.get_running_loop()
        job_ids: list[str] = []
        futs: list[asyncio.Future] = []
        for i in range(0, len(texts), self.texts_per_job):
            job_id = f"ctxembed-{new_id()}"
            ptr = await self.mem.put_context(
                job_id, {"op": "embed", "texts": list(texts[i:i + self.texts_per_job])}
            )
            fut = loop.create_future()
            self._pending[job_id] = fut
            req = JobRequest(
                job_id=job_id,
                topic=self.topic,
                context_ptr=ptr,
                tenant_id=self.tenant_id,
                labels={LABEL_OP: "embed", LABEL_BATCH_KEY: "embed"},
            )
            await self.bus.publish(
                subj.SUBMIT, BusPacket.wrap(req, sender_id="bus-embedder")
            )
            job_ids.append(job_id)
            futs.append(fut)
        try:
            results = await asyncio.wait_for(asyncio.gather(*futs), self.timeout_s)
        finally:
            for jid in job_ids:
                self._pending.pop(jid, None)
        parts: list[np.ndarray] = []
        for jid, res in zip(job_ids, results):
            if res.status != JobState.SUCCEEDED.value:
                raise RuntimeError(
                    f"embed job {jid} reached {res.status}: {res.error_message}"
                )
            out = await self.mem.get_pointer(res.result_ptr)
            if not out or "embeddings" not in out:
                raise RuntimeError(f"embed job {jid} result missing embeddings")
            parts.append(np.asarray(out["embeddings"], np.float32))
        self.embeds_total += len(texts)
        self.jobs_total += len(job_ids)
        return np.concatenate(parts, axis=0)


class ContextService:
    def __init__(
        self,
        kv: KV,
        *,
        embedder: Optional[Any] = None,
        max_chunks: int = 10,
        embed_batch: int = 64,
    ):
        self.kv = kv
        self.embedder = embedder
        self.max_chunks = max_chunks
        # re-index embedding slice size (the `context.embed_batch` effective-
        # config field): bounds one embed call / one pool job per slice
        self.embed_batch = max(1, embed_batch)

    def _embed_texts(self, texts: list[str]) -> np.ndarray:
        """Embed through the bulk path in ``embed_batch``-sized slices so a
        large re-index becomes a few padded batch calls (local embedder) or
        a few pool jobs (PoolEmbedder) instead of one unbounded call."""
        parts = [
            np.asarray(self.embedder.embed(texts[i:i + self.embed_batch]))
            for i in range(0, len(texts), self.embed_batch)
        ]
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    async def _aembed_texts(self, texts: list[str]) -> np.ndarray:
        """Async twin of ``_embed_texts``: awaits an ``aembed``-capable
        embedder (BusEmbedder — pool jobs without blocking the event loop);
        sync embedders run inline as before."""
        aembed = getattr(self.embedder, "aembed", None)
        if aembed is None:
            return self._embed_texts(texts)
        parts = [
            np.asarray(await aembed(texts[i:i + self.embed_batch]))
            for i in range(0, len(texts), self.embed_batch)
        ]
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    # ------------------------------------------------------------------
    async def update_memory(
        self,
        memory_id: str,
        *,
        user_payload: Any = None,
        model_response: str = "",
        mode: str = MODE_CHAT,
    ) -> None:
        """Append chat events (RPUSH + LTRIM, reference :134-176)."""
        if user_payload is not None:
            ev = {"role": "user", "content": _as_text(user_payload)}
            await self.kv.rpush(_events_key(memory_id), json.dumps(ev).encode())
        if model_response:
            ev = {"role": "assistant", "content": model_response}
            await self.kv.rpush(_events_key(memory_id), json.dumps(ev).encode())
        await self.kv.ltrim(_events_key(memory_id), -HISTORY_CAP, -1)

    async def set_summary(self, memory_id: str, summary: str) -> None:
        await self.kv.set(_summary_key(memory_id), summary.encode())

    async def put_chunks(self, memory_id: str, chunks: list[dict[str, Any]]) -> int:
        """Store RAG chunks [{file_path, content, labels?}]; embeds them
        (incrementally — cached by content hash) when an embedder is wired."""
        await self.kv.set(_chunks_key(memory_id), json.dumps(chunks).encode())
        if self.embedder is None:
            return 0
        missing: list[tuple[str, str]] = []
        for c in chunks:
            h = _chunk_hash(c)
            if await self.kv.get(_embed_key(memory_id, h)) is None:
                missing.append((h, _chunk_text(c)))
        if missing:
            vecs = await self._aembed_texts([t for _, t in missing])
            for (h, _), v in zip(missing, np.asarray(vecs)):
                await self.kv.set(
                    _embed_key(memory_id, h), np.asarray(v, np.float32).tobytes()
                )
        return len(missing)

    # ------------------------------------------------------------------
    async def build_window(
        self,
        memory_id: str,
        *,
        mode: str = MODE_RAW,
        payload: Any = None,
        max_input_tokens: int = DEFAULT_MAX_INPUT_TOKENS,
    ) -> list[ModelMessage]:
        """RAW: payload only.  CHAT: last-20 history + payload.  RAG: ranked
        chunks (semantic when embedder present, else path/substring match)
        + summary fallback + history + payload."""
        msgs: list[ModelMessage] = []
        query = _as_text(payload)
        if mode in (MODE_CHAT, MODE_RAG):
            raw = await self.kv.lrange(_events_key(memory_id), -HISTORY_WINDOW, -1)
            for b in raw:
                try:
                    ev = json.loads(b)
                except ValueError:
                    continue
                msgs.append(ModelMessage(role=ev.get("role", "user"), content=ev.get("content", ""), source="history"))
        if mode == MODE_RAG:
            chunks = await self._rank_chunks(memory_id, query)
            if chunks:
                # reversed so the best-ranked chunk ends up first in the window
                for c, score in reversed(chunks):
                    msgs.insert(
                        0,
                        ModelMessage(
                            role="system",
                            content=f"[{c.get('file_path', 'chunk')}] {_chunk_text(c)}",
                            source=f"rag:{score:.3f}",
                        ),
                    )
            else:
                summary = await self.kv.get(_summary_key(memory_id))
                if summary:
                    msgs.insert(0, ModelMessage(role="system", content=summary.decode(), source="summary"))
        if payload is not None:
            msgs.append(ModelMessage(role="user", content=query, source="payload"))
        return trim_to_budget(msgs, max_input_tokens)

    async def _rank_chunks(self, memory_id: str, query: str) -> list[tuple[dict, float]]:
        b = await self.kv.get(_chunks_key(memory_id))
        if not b:
            return []
        chunks = json.loads(b)
        if not chunks:
            return []
        if self.embedder is not None and query:
            qv = np.asarray(await self._aembed_texts([query]))[0]
            scored = []
            to_embed: list[tuple[int, str]] = []
            vecs: dict[int, np.ndarray] = {}
            for i, c in enumerate(chunks):
                cached = await self.kv.get(_embed_key(memory_id, _chunk_hash(c)))
                if cached is not None:
                    vecs[i] = np.frombuffer(cached, np.float32)
                else:
                    to_embed.append((i, _chunk_text(c)))
            if to_embed:
                new_vecs = np.asarray(await self._aembed_texts([t for _, t in to_embed]))
                for (i, _), v in zip(to_embed, new_vecs):
                    vecs[i] = np.asarray(v, np.float32)
                    await self.kv.set(
                        _embed_key(memory_id, _chunk_hash(chunks[i])), vecs[i].tobytes()
                    )
            for i, c in enumerate(chunks):
                v = vecs[i]
                denom = float(np.linalg.norm(qv) * np.linalg.norm(v)) or 1.0
                scored.append((c, float(qv @ v) / denom))
            scored.sort(key=lambda cs: cs[1], reverse=True)
            return scored[: self.max_chunks]
        # lexical fallback (reference behavior: file_path substring match)
        q = query.lower()
        hits = [
            (c, 1.0)
            for c in chunks
            if q and (str(c.get("file_path", "")).lower() in q or _overlap(q, _chunk_text(c)))
        ]
        return hits[: self.max_chunks]


def trim_to_budget(msgs: list[ModelMessage], max_tokens: int) -> list[ModelMessage]:
    """Drop oldest non-payload messages until under budget (reference
    trimToBudget :279-296)."""
    if max_tokens <= 0:
        return msgs
    total = sum(estimate_tokens(m.content) for m in msgs)
    out = list(msgs)
    i = 0
    while total > max_tokens and i < len(out):
        if out[i].source == "payload":
            i += 1
            continue
        total -= estimate_tokens(out[i].content)
        out.pop(i)
    # a single over-budget payload gets hard-truncated
    if total > max_tokens and out:
        last = out[-1]
        keep = max_tokens * 4
        out[-1] = ModelMessage(role=last.role, content=last.content[:keep], source=last.source)
    return out


def _as_text(payload: Any) -> str:
    if payload is None:
        return ""
    if isinstance(payload, str):
        return payload
    try:
        return json.dumps(payload)
    except (TypeError, ValueError):
        return str(payload)


def _chunk_text(c: dict) -> str:
    return str(c.get("content", c.get("text", "")))


def _chunk_hash(c: dict) -> str:
    return hashlib.blake2b(
        (_chunk_text(c) + "|" + str(c.get("file_path", ""))).encode(), digest_size=8
    ).hexdigest()


def _overlap(query: str, text: str) -> bool:
    qtok = set(query.lower().split())
    ttok = set(text.lower().split())
    return len(qtok & ttok) >= max(1, len(qtok) // 4)
