"""Capacity-aware admission control — the gateway's overload defense.

The fleet already measures everything it needs to survive overload: a live
per-worker (op, bucket) throughput matrix (``FleetAggregator.capacity_doc``,
PR 10) and per-class SLO burn rates (``SLOTracker``, PR 9).  This module
closes the loop (docs/ADMISSION.md): admission becomes an **analytical
decision against measured capacity** (FleetOpt, PAPERS.md) instead of a
queue-depth heuristic.

Per submission the controller:

1. records the arrival in the per-(op, job_class) offered-rate EWMA
   (offered = everything that arrives, shed or not — shedding must not
   hide the overload it is reacting to);
2. charges the tenant's token bucket (``pools.yaml admission.tenants``);
3. walks the **brownout ladder** driven by the interactive SLO burn signal:
   tier 1 (5m burn ≥ 1.0) sheds all BATCH, tier 2 (page state) also sheds
   best-effort ops, tier 3 (page + deep backlog) bounds even INTERACTIVE
   behind ``interactive_queue_bound``;
4. sheds analytically on per-(op, class) **headroom** — measured fleet
   items/s (fresh matrix rows only, scaled by ``safety_factor``) minus the
   EWMA offered rate.  INTERACTIVE is admitted until *its own* share of
   capacity is exhausted; BATCH is shed first, as soon as the *total*
   offered rate exceeds capacity;
5. falls back to the queue-depth heuristic while the matrix is cold or
   stale for the op (no fresh rows → shed batch past
   ``queue_depth_limit`` of fleet scheduler backlog), re-engaging
   analytically the moment fresh rows appear.

Every shed carries an honest, headroom-derived ``Retry-After``: the time
the measured fleet needs to absorb one second of excess arrivals
(``(offered − capacity) / capacity``, clamped to the configured bounds).

The controller also publishes :class:`AdmissionPressure` beacons on
``sys.admission.pressure`` when the tier changes (and periodically while
shedding): the scheduler's preemption governor requeues dispatched BATCH
jobs on ``preempt_batch`` and serving engines deprioritize batch prefill.

Surfaced at ``GET /api/v1/admission`` / ``cordumctl admission`` and as
``cordum_gateway_shed_total`` / ``cordum_admission_headroom`` /
``cordum_admission_brownout_tier`` metrics.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ...infra.metrics import Metrics
from ...protocol import subjects as subj
from ...protocol.types import AdmissionPressure, BusPacket

INTERACTIVE_CLASSES = frozenset({"INTERACTIVE", "CRITICAL"})

DEFAULT_SAFETY_FACTOR = 0.9
DEFAULT_SMOOTHING_ALPHA = 0.3
DEFAULT_QUEUE_DEPTH_LIMIT = 256
DEFAULT_MIN_RETRY_AFTER_S = 0.25
DEFAULT_MAX_RETRY_AFTER_S = 15.0
DEFAULT_BEST_EFFORT_OPS = ("embed",)
REFRESH_INTERVAL_S = 1.0  # rate roll + capacity/SLO re-read cadence
PRESSURE_INTERVAL_S = 2.0  # re-beacon cadence while tier >= 1


@dataclass
class Verdict:
    """One admission decision; ``retry_after_s`` rides the 429 header."""

    allowed: bool
    reason: str = ""  # shed reason ("" when allowed)
    retry_after_s: float = 0.0
    mode: str = "analytic"  # analytic | fallback | disabled


class _TenantBucket:
    """Token bucket with monotonic refill; ``take`` reports the wait until
    the next token when empty (the honest tenant-quota Retry-After)."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate_rps: float, burst: float, now: float) -> None:
        self.rate = max(0.0, rate_rps)
        self.burst = max(1.0, burst or self.rate or 1.0)
        self.tokens = self.burst
        self.stamp = now

    def take(self, now: float) -> tuple[bool, float]:
        if self.rate <= 0:
            return True, 0.0  # unlimited
        self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.rate


class AdmissionController:
    """Analytical gateway admission against the measured capacity matrix.

    ``fleet`` is the gateway's :class:`~cordum_tpu.obs.fleet.FleetAggregator`
    and ``slo_tracker`` its :class:`~cordum_tpu.obs.slo.SLOTracker`; both
    are read (never written) on each refresh.  ``clock`` is injectable for
    deterministic tests.
    """

    def __init__(
        self,
        *,
        fleet: Any,
        slo_tracker: Any = None,
        config: Optional[dict] = None,
        metrics: Optional[Metrics] = None,
        bus: Any = None,
        instance_id: str = "gateway-0",
        clock: Callable[[], float] = time.monotonic,
        rng: Callable[[], float] = random.random,
    ) -> None:
        cfg = dict(config or {})
        self.fleet = fleet
        self.slo_tracker = slo_tracker
        self.metrics = metrics
        self.bus = bus
        self.instance_id = instance_id
        self.clock = clock
        self.rng = rng  # injectable for deterministic shed-fraction tests
        self.enabled = bool(cfg) and bool(cfg.get("enabled", True))
        self.safety_factor = float(cfg.get("safety_factor", DEFAULT_SAFETY_FACTOR))
        self.alpha = float(cfg.get("smoothing_alpha", DEFAULT_SMOOTHING_ALPHA))
        self.queue_depth_limit = int(
            cfg.get("queue_depth_limit", DEFAULT_QUEUE_DEPTH_LIMIT)
        )
        self.interactive_queue_bound = int(
            cfg.get("interactive_queue_bound", 4 * self.queue_depth_limit)
        )
        self.min_retry_after_s = float(
            cfg.get("min_retry_after_s", DEFAULT_MIN_RETRY_AFTER_S)
        )
        self.max_retry_after_s = float(
            cfg.get("max_retry_after_s", DEFAULT_MAX_RETRY_AFTER_S)
        )
        self.best_effort_ops = frozenset(
            cfg.get("best_effort_ops") or DEFAULT_BEST_EFFORT_OPS
        )
        self._tenant_cfg: dict[str, dict] = {
            str(k): dict(v or {}) for k, v in (cfg.get("tenants") or {}).items()
        }
        self._buckets: dict[str, _TenantBucket] = {}
        # offered-rate tracking: arrivals counted per (op, class) between
        # refreshes, folded into an EWMA rate at each roll
        self._arrivals: dict[tuple[str, str], int] = {}
        self._rates: dict[tuple[str, str], float] = {}
        self._last_roll = clock()
        self._last_refresh = 0.0
        # refreshed state
        self._capacity: dict[str, float] = {}  # op → admitted items/s budget
        self._queue_depth = 0
        self._tier = 0
        self._interactive_burn = 0.0
        self._slo_states: list[dict] = []
        # pressure beacon state
        self._last_pressure_tier: Optional[int] = None
        self._last_pressure_at = 0.0
        # shed accounting for the admission doc (metrics carry the same)
        self._shed: dict[tuple[str, str], int] = {}
        self._admitted = 0

    # ------------------------------------------------------------------
    # refresh: offered-rate roll + capacity matrix + SLO tier
    # ------------------------------------------------------------------
    def _roll(self, now: float) -> None:
        dt = now - self._last_roll
        if dt <= 0:
            return
        self._last_roll = now
        a = min(1.0, self.alpha * max(1.0, dt / REFRESH_INTERVAL_S))
        seen = set(self._arrivals)
        for key, n in self._arrivals.items():
            rate = n / dt
            prev = self._rates.get(key)
            self._rates[key] = rate if prev is None else a * rate + (1 - a) * prev
        self._arrivals = {}
        # decay quiet series toward zero so old bursts stop shedding
        for key in list(self._rates):
            if key not in seen:
                self._rates[key] *= 1 - a
                if self._rates[key] < 0.01:
                    del self._rates[key]

    def refresh(self, now: Optional[float] = None) -> None:
        """Roll offered rates and re-read the capacity matrix + SLO burn
        states; sets the brownout tier and the headroom/tier gauges."""
        now = self.clock() if now is None else now
        self._last_refresh = now
        self._roll(now)
        # fresh per-op fleet capacity (capacity_doc's `ops` sums only rows
        # whose worker beaconed recently), scaled by the safety factor
        try:
            doc = self.fleet.capacity_doc()
        except Exception:  # noqa: BLE001 - a cold aggregator must not 500 submits
            doc = {}
        self._capacity = {
            str(op): float(v) * self.safety_factor
            for op, v in (doc.get("ops") or {}).items()
            if float(v) > 0
        }
        self._queue_depth = self._fleet_queue_depth()
        self._slo_states = []
        burn = 0.0
        page = False
        if self.slo_tracker is not None:
            try:
                self._slo_states = self.slo_tracker.evaluate(self.fleet)
            except Exception:  # noqa: BLE001 - SLO eval failure ≠ shed everything
                self._slo_states = []
            for state in self._slo_states:
                if str(state.get("job_class", "")).upper() not in INTERACTIVE_CLASSES:
                    continue
                w5 = (state.get("windows") or {}).get("5m") or {}
                burn = max(burn, float(w5.get("burn_rate", 0.0)))
                if state.get("state") == "page":
                    page = True
        self._interactive_burn = burn
        tier = 0
        if burn >= 1.0:
            tier = 1
        if page:
            tier = 2
            if self._queue_depth > self.interactive_queue_bound:
                tier = 3
        self._tier = tier
        if self.metrics is not None:
            self.metrics.admission_tier.set(float(tier))
            for op, cap in self._capacity.items():
                self.metrics.admission_headroom.set(
                    cap - self._offered(op, interactive_only=True),
                    op=op, job_class="INTERACTIVE",
                )
                self.metrics.admission_headroom.set(
                    cap - self._offered(op), op=op, job_class="BATCH",
                )

    def _fleet_queue_depth(self) -> int:
        """Summed live submit backlog across healthy scheduler beacons —
        the cold/stale-matrix fallback signal."""
        depth = 0
        try:
            for s in self.fleet.services():
                if s.get("service") == "scheduler" and s.get("healthy"):
                    depth += int(s.get("queue_depth") or 0)
        except Exception:  # noqa: BLE001 - beacon shape drift must not 500 submits
            return 0
        return depth

    def _offered(self, op: str, *, interactive_only: bool = False) -> float:
        total = 0.0
        for (o, klass), rate in self._rates.items():
            if o != op:
                continue
            if interactive_only and klass not in INTERACTIVE_CLASSES:
                continue
            total += rate
        return total

    # ------------------------------------------------------------------
    # the per-submission decision
    # ------------------------------------------------------------------
    def admit(
        self, *, op: str, job_class: str, tenant: str = "",
        now: Optional[float] = None,
    ) -> Verdict:
        """Decide one submission.  Always records the arrival (offered rate
        includes shed traffic); never raises."""
        now = self.clock() if now is None else now
        op = op or "-"
        klass = (job_class or "BATCH").upper()
        self._arrivals[(op, klass)] = self._arrivals.get((op, klass), 0) + 1
        if not self.enabled:
            return Verdict(True, mode="disabled")
        if now - self._last_refresh >= REFRESH_INTERVAL_S:
            self.refresh(now)

        # tenant token-bucket quota
        ok, wait = self._take_tenant_token(tenant, now)
        if not ok:
            return self._shed_verdict(
                "tenant_quota", klass,
                max(self.min_retry_after_s, min(self.max_retry_after_s, wait)),
                mode="analytic",
            )

        interactive = klass in INTERACTIVE_CLASSES
        cap = self._capacity.get(op, 0.0)

        # brownout ladder (interactive SLO burn signal)
        if self._tier >= 1 and not interactive:
            return self._shed_verdict(
                "brownout_batch", klass, self._capacity_retry_after(op, cap)
            )
        if self._tier >= 2 and op in self.best_effort_ops:
            return self._shed_verdict(
                "brownout_best_effort", klass,
                self._capacity_retry_after(op, cap),
            )
        if (
            self._tier >= 3
            and interactive
            and self._queue_depth > self.interactive_queue_bound
        ):
            return self._shed_verdict(
                "brownout_interactive", klass,
                self._depth_retry_after(self.interactive_queue_bound),
            )

        if cap <= 0.0:
            # matrix cold or stale for this op: queue-depth fallback — never
            # divide by a zero capacity, never shed interactive on it unless
            # the backlog passes the (much larger) interactive bound
            if not interactive and self._queue_depth > self.queue_depth_limit:
                return self._shed_verdict(
                    "queue_depth", klass,
                    self._depth_retry_after(self.queue_depth_limit),
                    mode="fallback",
                )
            if interactive and self._queue_depth > self.interactive_queue_bound:
                return self._shed_verdict(
                    "queue_depth", klass,
                    self._depth_retry_after(self.interactive_queue_bound),
                    mode="fallback",
                )
            self._admitted += 1
            return Verdict(True, mode="fallback")

        # analytic headroom: interactive admitted until its OWN share is
        # exhausted; batch absorbs the whole overload first.  Shedding is
        # PROPORTIONAL — each class sheds exactly its excess fraction, so
        # the admitted stream converges on the capacity budget instead of
        # flapping between shed-everything and admit-everything.
        if interactive:
            offered_int = self._offered(op, interactive_only=True)
            excess = offered_int - cap
            if excess > 0 and self.rng() < min(1.0, excess / offered_int):
                return self._shed_verdict(
                    "capacity_interactive", klass,
                    self._capacity_retry_after(op, cap),
                )
        else:
            offered = self._offered(op)
            batch_offered = offered - self._offered(op, interactive_only=True)
            excess = offered - cap
            if excess > 0 and batch_offered > 0 and self.rng() < min(
                1.0, excess / batch_offered
            ):
                return self._shed_verdict(
                    "capacity", klass, self._capacity_retry_after(op, cap)
                )
        self._admitted += 1
        return Verdict(True, mode="analytic")

    def _take_tenant_token(self, tenant: str, now: float) -> tuple[bool, float]:
        if not tenant or not self._tenant_cfg:
            return True, 0.0
        cfg = self._tenant_cfg.get(tenant) or self._tenant_cfg.get("default")
        if not cfg:
            return True, 0.0
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = _TenantBucket(
                float(cfg.get("rate_rps") or 0.0),
                float(cfg.get("burst") or 0.0), now,
            )
        return bucket.take(now)

    def _shed_verdict(
        self, reason: str, klass: str, retry_after: float, *,
        mode: str = "analytic",
    ) -> Verdict:
        self._shed[(reason, klass)] = self._shed.get((reason, klass), 0) + 1
        if self.metrics is not None:
            self.metrics.gateway_shed.inc(reason=reason, job_class=klass)
        return Verdict(False, reason, round(retry_after, 3), mode=mode)

    def _capacity_retry_after(self, op: str, cap: float) -> float:
        """Honest headroom-derived delay: the time the measured fleet needs
        to absorb one second of excess arrivals for this op."""
        if cap <= 0:
            return self.min_retry_after_s
        excess = max(0.0, self._offered(op) - cap)
        return max(self.min_retry_after_s,
                   min(self.max_retry_after_s, excess / cap))

    def _depth_retry_after(self, limit: int) -> float:
        over = max(0.0, self._queue_depth - limit) / max(1, limit)
        return max(self.min_retry_after_s,
                   min(self.max_retry_after_s, self.min_retry_after_s * (1 + over)))

    # ------------------------------------------------------------------
    # pressure beacons (the scheduler's preemption trigger)
    # ------------------------------------------------------------------
    async def publish_pressure(self, now: Optional[float] = None) -> bool:
        """Publish an :class:`AdmissionPressure` beacon when the tier
        changed, periodically while shedding (tier ≥ 1), and once as the
        all-clear on the transition back to 0.  Returns True if published."""
        if self.bus is None:
            return False
        now = self.clock() if now is None else now
        changed = self._last_pressure_tier != self._tier
        hot = self._tier >= 1 and (
            now - self._last_pressure_at >= PRESSURE_INTERVAL_S
        )
        if not changed and not hot:
            return False
        self._last_pressure_tier = self._tier
        self._last_pressure_at = now
        await self.bus.publish(
            subj.ADMISSION_PRESSURE,
            BusPacket.wrap(
                AdmissionPressure(
                    tier=self._tier,
                    interactive_burn_5m=round(self._interactive_burn, 3),
                    preempt_batch=self._tier >= 1,
                    reason="slo_pressure" if self._tier >= 1 else "clear",
                ),
                sender_id=self.instance_id,
            ),
        )
        return True

    # ------------------------------------------------------------------
    # introspection (GET /api/v1/admission, cordumctl admission)
    # ------------------------------------------------------------------
    @property
    def tier(self) -> int:
        return self._tier

    def doc(self) -> dict:
        """The live controller state document."""
        ops: dict[str, dict] = {}
        seen_ops = set(self._capacity) | {op for op, _ in self._rates}
        for op in sorted(seen_ops):
            cap = self._capacity.get(op, 0.0)
            offered = {
                klass: round(rate, 2)
                for (o, klass), rate in sorted(self._rates.items())
                if o == op
            }
            ops[op] = {
                "capacity_per_s": round(cap, 2),
                "offered": offered,
                "headroom_interactive": round(
                    cap - self._offered(op, interactive_only=True), 2
                ),
                "headroom_batch": round(cap - self._offered(op), 2),
                "mode": "analytic" if cap > 0 else "fallback",
            }
        tenants = {}
        for name, cfg in sorted(self._tenant_cfg.items()):
            bucket = self._buckets.get(name)
            tenants[name] = {
                "rate_rps": float(cfg.get("rate_rps") or 0.0),
                "burst": float(cfg.get("burst") or 0.0),
                "tokens": round(bucket.tokens, 2) if bucket else None,
            }
        return {
            "enabled": self.enabled,
            "tier": self._tier,
            "interactive_burn_5m": round(self._interactive_burn, 3),
            "queue_depth": self._queue_depth,
            "queue_depth_limit": self.queue_depth_limit,
            "interactive_queue_bound": self.interactive_queue_bound,
            "safety_factor": self.safety_factor,
            "admitted": self._admitted,
            "shed": {
                f"{reason}|{klass}": n
                for (reason, klass), n in sorted(self._shed.items())
            },
            "ops": ops,
            "tenants": tenants,
            "slo": self._slo_states,
        }


# ---------------------------------------------------------------------------
# `cordumctl admission` rendering (pure function so tests cover it offline)
# ---------------------------------------------------------------------------

_ADM_COLS = (
    ("op", "op"), ("cap/s", "capacity_per_s"), ("offered", "offered"),
    ("headroom(int)", "headroom_interactive"),
    ("headroom(batch)", "headroom_batch"), ("mode", "mode"),
)


def render_admission_table(doc: dict) -> str:
    """ASCII controller-state table for ``cordumctl admission`` from a
    ``GET /api/v1/admission`` document."""
    head = (
        "cordum admission — {state}, brownout tier {tier}, "
        "interactive burn(5m) {burn}, scheduler backlog {q}/{lim}".format(
            state="enabled" if doc.get("enabled") else "DISABLED",
            tier=doc.get("tier", 0),
            burn=doc.get("interactive_burn_5m", 0.0),
            q=doc.get("queue_depth", 0),
            lim=doc.get("queue_depth_limit", 0),
        )
    )
    shed = doc.get("shed") or {}
    lines = [head]
    if shed:
        lines.append("shed: " + "  ".join(
            f"{k}={v}" for k, v in sorted(shed.items())))
    rows = []
    for op, o in sorted((doc.get("ops") or {}).items()):
        rows.append({
            "op": op,
            "capacity_per_s": f"{o.get('capacity_per_s', 0.0):g}",
            "offered": " ".join(
                f"{k}={v:g}" for k, v in sorted((o.get("offered") or {}).items())
            ) or "-",
            "headroom_interactive": f"{o.get('headroom_interactive', 0.0):g}",
            "headroom_batch": f"{o.get('headroom_batch', 0.0):g}",
            "mode": str(o.get("mode", "")),
        })
    if rows:
        widths = {
            key: max(len(title), *(len(r[key]) for r in rows))
            for title, key in _ADM_COLS
        }
        lines.append("  ".join(t.ljust(widths[k]) for t, k in _ADM_COLS))
        for r in rows:
            lines.append("  ".join(r[k].ljust(widths[k]) for _, k in _ADM_COLS))
    else:
        lines.append("(no offered traffic or capacity rows yet)")
    tenants = doc.get("tenants") or {}
    if tenants:
        lines.append("tenants: " + "  ".join(
            "{n}[rate={r:g} burst={b:g} tokens={t}]".format(
                n=name, r=t.get("rate_rps", 0.0), b=t.get("burst", 0.0),
                t=t.get("tokens") if t.get("tokens") is not None else "-",
            )
            for name, t in sorted(tenants.items())
        ))
    return "\n".join(lines)
