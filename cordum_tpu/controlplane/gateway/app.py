"""API gateway: the HTTP/WS surface of the control plane.

Recreates the reference gateway's API (``core/controlplane/gateway/
gateway.go``, 4373 LoC — route table :701-805) on aiohttp:

  jobs            POST/GET/list/cancel/remediate, trace reader
  approvals       list / approve / reject with job-hash + snapshot binding
  workflows       CRUD + run start (Idempotency-Key header, max-concurrent
                  guard) / cancel / rerun / step-approve / timeline
  DLQ             list / get / delete / retry-with-new-job-id
  policy          evaluate / simulate / explain / snapshots
  config          scoped get/set + effective view
  schemas         CRUD
  locks           list / acquire / release
  artifacts       put / get
  memory          pointer reader (``?ptr=kv://...``)
  workers         live registry snapshot
  status/healthz  bus+kv health;  /metrics Prometheus text
  /api/v1/stream  WebSocket event stream (bus tap broadcast)

Bus taps (reference gateway.go:531-650): heartbeats → worker map, DLQ tap →
DLQStore, ``sys.job.>`` + workflow events → WS broadcast.
"""
from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, Optional

from aiohttp import WSMsgType, web

from ...infra import logging as logx
from ...infra.artifacts import ArtifactStore
from ...infra.bus import Bus
from ...infra.configsvc import ConfigService
from ...infra.dlq import DLQEntry, DLQStore
from ...infra.jobstore import ApprovalRecord, JobStore
from ...infra.kv import KV
from ...infra.locks import LockStore
from ...infra.memstore import MemoryStore
from ...infra.metrics import Metrics
from ...infra.registry import WorkerRegistry
from ...infra.schemareg import SchemaError, SchemaRegistry
from ...infra.secrets import contains_secret_refs
from ...obs.assembler import aggregate_critical_paths, assemble
from ...obs.collector import SpanCollector
from ...obs.fleet import FleetAggregator
from ...obs.profiler import RuntimeProfiler
from ...obs.slo import SLOTracker
from ...obs.telemetry import TelemetryExporter
from ...obs.tracer import Tracer
from ...protocol import subjects as subj
from ...protocol.jobhash import job_hash
from ...protocol.partition import partition_of
from ...protocol.types import (
    Budget,
    BusPacket,
    ContextHints,
    JobCancel,
    JobMetadata,
    JobRequest,
    JobState,
    LABEL_APPROVAL_GRANTED,
    LABEL_BATCH_KEY,
    LABEL_BUS_MSG_ID,
    LABEL_GANG_CHIPS,
    LABEL_GANG_KIND,
    LABEL_GANG_WORKERS,
    LABEL_OP,
    LABEL_SECRETS_PRESENT,
    LABEL_SESSION_KEY,
    LABEL_SLO_CLASS,
    PolicyCheckRequest,
    TERMINAL_STATES,
    WorkerDrain,
    payload_batch_key,
    payload_gang,
    payload_session_key,
)
from ...utils.ids import new_id, now_us
from ...workflow.engine import Engine as WorkflowEngine, WorkflowError
from ...workflow.models import Workflow
from ...workflow.store import WorkflowStore
from ..safetykernel.kernel import SafetyKernel
from .admission import AdmissionController
from .auth import AuthProvider, BasicAuthProvider, Principal, TokenBucket

MAX_BODY_BYTES = 2 * 1024 * 1024  # 2 MiB submit cap (reference gateway.go:1757)
MAX_BULK_JOBS = 256  # jobs per POST /api/v1/jobs:batch


def _err(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def _retry_after_headers(status: int, doc: dict) -> Optional[dict[str, str]]:
    """429 responses carry an honest Retry-After so SDK clients back off
    instead of retrying immediately (docs/ADMISSION.md)."""
    if status != 429:
        return None
    retry = float(doc.get("retry_after_s") or 0.25)
    return {"Retry-After": f"{retry:.3f}"}


class Gateway:
    def __init__(
        self,
        *,
        kv: KV,
        bus: Bus,
        job_store: JobStore,
        mem: MemoryStore,
        kernel: SafetyKernel,
        wf_store: WorkflowStore,
        wf_engine: WorkflowEngine,
        schemas: Optional[SchemaRegistry] = None,
        configsvc: Optional[ConfigService] = None,
        registry: Optional[WorkerRegistry] = None,
        context_svc: Optional[Any] = None,
        auth: Optional[AuthProvider] = None,
        metrics: Optional[Metrics] = None,
        rate_rps: float = 0.0,
        max_concurrent_runs: int = 0,
        ws_allowed_origins: Optional[list[str]] = None,
        instance_id: str = "gateway-0",
        scheduler_shards: int = 1,
        slo_config: Optional[dict] = None,
        admission_config: Optional[dict] = None,
        telemetry: bool = True,
        trace_keep_fraction: float = 1.0,
    ):
        self.kv = kv
        self.bus = bus
        self.job_store = job_store
        self.mem = mem
        self.kernel = kernel
        self.wf_store = wf_store
        self.wf_engine = wf_engine
        self.schemas = schemas or SchemaRegistry(kv)
        self.configsvc = configsvc
        self.registry = registry
        self.context_svc = context_svc
        self.dlq = DLQStore(kv)
        self.locks = LockStore(kv)
        self.artifacts = ArtifactStore(kv)
        self.auth = auth or BasicAuthProvider()
        self.metrics = metrics or Metrics()
        self.tracer = Tracer("gateway", bus)
        # the gateway hosts the deployment's span collector: it owns /metrics
        # (stage histograms land there) and serves the trace API from the
        # same KV the collector writes.  trace_keep_fraction < 1.0 turns on
        # tail-based retention: every slower-than-rolling-p95 trace is kept,
        # the fast rest is sampled (docs/OBSERVABILITY.md §Capacity
        # observatory)
        self.span_collector = SpanCollector(
            kv, bus, metrics=self.metrics,
            tail_keep_fraction=trace_keep_fraction,
        )
        # ... and the fleet telemetry plane (ISSUE 9): the aggregator merges
        # every process's sys.telemetry.<service> snapshots into the fleet
        # view (/api/v1/fleet, /metrics?scope=fleet, cordumctl top); the SLO
        # tracker burns the pools.yaml slo: objectives against it; the
        # gateway exports its own registry + runs the runtime profiler like
        # any other process
        self.fleet = FleetAggregator(bus, metrics=self.metrics)
        self.slo_tracker = SLOTracker.from_config(
            slo_config or {}, metrics=self.metrics
        )
        self.profiler = RuntimeProfiler(self.metrics, service="gateway")
        # overload resilience (docs/ADMISSION.md): the admission controller
        # sheds analytically against the capacity matrix + SLO burn rates
        # the aggregator/tracker above already maintain, and beacons
        # pressure to the scheduler's preemption governor.  No admission:
        # stanza → disabled (pure pass-through).
        self.admission = AdmissionController(
            fleet=self.fleet, slo_tracker=self.slo_tracker,
            config=admission_config, metrics=self.metrics, bus=bus,
            instance_id=instance_id,
        )
        self._admission_task: Optional[asyncio.Task] = None
        self._telemetry_enabled = telemetry
        self.telemetry = TelemetryExporter(
            "gateway", bus, self.metrics, instance_id=instance_id,
            health_fn=self._telemetry_health,
        )
        self.rate = TokenBucket(rate_rps)
        self.max_concurrent_runs = max_concurrent_runs
        self.ws_allowed_origins = ws_allowed_origins
        self.instance_id = instance_id
        # keyspace-sharded scheduler: the gateway stamps the partition at
        # submit time by publishing straight to the owner shard's subject
        # (sys.job.submit.<p>); 1 = unsharded plain subjects
        self.scheduler_shards = max(1, scheduler_shards)
        self._ws_clients: set[web.WebSocketResponse] = set()
        self._subs: list = []
        self._runner: Optional[web.AppRunner] = None
        self.app = self._build_app()

    # ------------------------------------------------------------------
    def _build_app(self) -> web.Application:
        app = web.Application(client_max_size=MAX_BODY_BYTES, middlewares=[self._middleware])
        r = app.router
        v1 = "/api/v1"
        r.add_post(f"{v1}/jobs", self.submit_job)
        # bulk submit: many jobs, one HTTP round trip (micro-batching's
        # client-side leg — amortizes per-job HTTP+bus overhead)
        r.add_post(f"{v1}/jobs:batch", self.submit_jobs_bulk)
        r.add_get(f"{v1}/jobs", self.list_jobs)
        r.add_get(f"{v1}/jobs/{{job_id}}", self.get_job)
        r.add_post(f"{v1}/jobs/{{job_id}}/cancel", self.cancel_job)
        r.add_post(f"{v1}/jobs/{{job_id}}/remediate", self.remediate_job)
        r.add_get(f"{v1}/approvals", self.list_approvals)
        r.add_post(f"{v1}/approvals/{{job_id}}/approve", self.approve_job)
        r.add_post(f"{v1}/approvals/{{job_id}}/reject", self.reject_job)
        r.add_post(f"{v1}/workflows", self.put_workflow)
        r.add_get(f"{v1}/workflows", self.list_workflows)
        r.add_get(f"{v1}/workflows/{{wf_id}}", self.get_workflow)
        r.add_delete(f"{v1}/workflows/{{wf_id}}", self.delete_workflow)
        r.add_post(f"{v1}/workflows/{{wf_id}}/runs", self.start_run)
        r.add_get(f"{v1}/runs", self.list_runs)
        r.add_get(f"{v1}/runs/{{run_id}}", self.get_run)
        r.add_post(f"{v1}/runs/{{run_id}}/cancel", self.cancel_run)
        r.add_post(f"{v1}/runs/{{run_id}}/rerun", self.rerun)
        r.add_post(f"{v1}/runs/{{run_id}}/steps/{{step_id}}/approve", self.approve_step)
        r.add_get(f"{v1}/runs/{{run_id}}/timeline", self.run_timeline)
        r.add_get(f"{v1}/dlq", self.list_dlq)
        r.add_post(f"{v1}/dlq/retry-all", self.retry_all_dlq)
        r.add_post(f"{v1}/dlq/purge", self.purge_dlq)
        r.add_delete(f"{v1}/dlq/{{job_id}}", self.delete_dlq)
        r.add_post(f"{v1}/dlq/{{job_id}}/retry", self.retry_dlq)
        r.add_post(f"{v1}/policy/evaluate", self.policy_evaluate)
        r.add_post(f"{v1}/policy/simulate", self.policy_simulate)
        r.add_post(f"{v1}/policy/explain", self.policy_explain)
        r.add_get(f"{v1}/policy/snapshots", self.policy_snapshots)
        r.add_get(f"{v1}/policy/bundles", self.bundles_list)
        r.add_get(f"{v1}/policy/bundles/{{bundle_id}}", self.bundles_get)
        r.add_put(f"{v1}/policy/bundles/{{bundle_id}}", self.bundles_put)
        r.add_delete(f"{v1}/policy/bundles/{{bundle_id}}", self.bundles_delete)
        r.add_post(f"{v1}/policy/bundles/{{bundle_id}}/publish", self.bundles_publish)
        r.add_post(f"{v1}/policy/bundles/{{bundle_id}}/unpublish", self.bundles_unpublish)
        r.add_post(f"{v1}/policy/bundles/{{bundle_id}}/simulate", self.bundles_simulate)
        r.add_post(f"{v1}/policy/snapshots/capture", self.snapshots_capture)
        r.add_get(f"{v1}/policy/snapshots/captured", self.snapshots_captured)
        r.add_post(f"{v1}/policy/snapshots/{{snapshot_id}}/rollback", self.snapshots_rollback)
        r.add_get(f"{v1}/policy/audit", self.policy_audit)
        r.add_post(f"{v1}/packs", self.install_pack)
        r.add_get(f"{v1}/packs", self.list_packs)
        r.add_get(f"{v1}/packs/{{pack_id}}", self.show_pack)
        r.add_delete(f"{v1}/packs/{{pack_id}}", self.uninstall_pack)
        r.add_get(f"{v1}/pack-catalogs", self.list_catalogs)
        r.add_post(f"{v1}/pack-catalogs", self.add_catalog)
        r.add_get(f"{v1}/pack-catalogs/{{catalog}}/packs", self.catalog_packs)
        r.add_post(f"{v1}/pack-catalogs/{{catalog}}/install/{{pack_id}}", self.catalog_install)
        r.add_get(f"{v1}/config/effective", self.config_effective)
        r.add_get(f"{v1}/config/{{scope}}/{{doc_id:.+}}", self.config_get)
        r.add_put(f"{v1}/config/{{scope}}/{{doc_id:.+}}", self.config_set)
        r.add_get(f"{v1}/schemas", self.list_schemas)
        r.add_get(f"{v1}/schemas/{{schema_id}}", self.get_schema)
        r.add_put(f"{v1}/schemas/{{schema_id}}", self.put_schema)
        r.add_delete(f"{v1}/schemas/{{schema_id}}", self.delete_schema)
        r.add_get(f"{v1}/locks", self.list_locks)
        r.add_post(f"{v1}/locks/{{resource}}/acquire", self.acquire_lock)
        r.add_post(f"{v1}/locks/{{resource}}/release", self.release_lock)
        r.add_post(f"{v1}/artifacts", self.put_artifact)
        r.add_get(f"{v1}/artifacts/{{artifact_id}}", self.get_artifact)
        r.add_get(f"{v1}/memory", self.read_pointer)
        r.add_post(f"{v1}/context/window", self.context_window)
        r.add_post(f"{v1}/context/memory/{{memory_id}}", self.context_update)
        r.add_put(f"{v1}/context/chunks/{{memory_id}}", self.context_chunks)
        r.add_get(f"{v1}/traces", self.list_traces)
        # literal route must register before the {trace_id} wildcard or
        # "analysis" would be read as a trace id
        r.add_get(f"{v1}/traces/analysis", self.traces_analysis)
        r.add_get(f"{v1}/traces/{{trace_id}}", self.get_trace)
        r.add_get(f"{v1}/fleet", self.get_fleet)
        r.add_get(f"{v1}/capacity", self.get_capacity)
        r.add_get(f"{v1}/gangs", self.get_gangs)
        r.add_get(f"{v1}/admission", self.get_admission)
        r.add_get(f"{v1}/workers", self.get_workers)
        r.add_post(f"{v1}/workers/{{worker_id}}/drain", self.drain_worker)
        r.add_get(f"{v1}/status", self.get_status)
        r.add_get(f"{v1}/stream", self.ws_stream)
        r.add_get("/healthz", self.healthz)
        r.add_get("/metrics", self.get_metrics)
        # operations dashboard (reference dashboard/ React app → served-static
        # SPA here; same /api/v1 + WS surface underneath)
        dash = os.path.join(os.path.dirname(os.path.abspath(__file__)), "dashboard")
        if os.path.isdir(dash):
            r.add_get("/", self._dash_index)
            r.add_get("/ui", self._dash_index)
            r.add_get("/ui/", self._dash_index)
            r.add_static("/ui/", dash)
        return app

    async def _dash_index(self, request: web.Request) -> web.Response:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "dashboard", "index.html")
        return web.FileResponse(path)

    @web.middleware
    async def _middleware(self, request: web.Request, handler):
        t0 = time.perf_counter()
        if not self.rate.allow(request.headers.get("X-Api-Key", request.remote or "")):
            # honest Retry-After: one token accrues in 1/rps seconds
            retry = max(0.25, 1.0 / self.rate.rps) if self.rate.rps > 0 else 1.0
            self.metrics.gateway_shed.inc(reason="rate_limit", job_class="unknown")
            return web.json_response(
                {"error": "rate limited", "retry_after_s": round(retry, 3)},
                status=429, headers={"Retry-After": f"{retry:.3f}"},
            )
        if request.path in ("/healthz", "/metrics", "/") or request.path.startswith("/ui"):
            request["principal"] = Principal()
            return await handler(request)
        headers = request.headers
        if (
            request.path.endswith("/stream")
            and "X-Api-Key" not in headers
            and "Authorization" not in headers  # Bearer clients keep working
        ):
            # browsers can't set arbitrary WS headers; accept the API key as
            # the first Sec-WebSocket-Protocol token (reference gateway.go:2002)
            proto = headers.get("Sec-WebSocket-Protocol", "")
            key = proto.split(",")[0].strip()
            if key:
                from multidict import CIMultiDict

                # CIMultiDict copy: case-insensitive lookups (x-tenant-id
                # etc.) must keep working on the overlaid header map
                overlaid = CIMultiDict(headers)
                overlaid["X-Api-Key"] = key
                headers = overlaid
        principal = self.auth.authenticate(headers)
        if principal is None:
            return _err(401, "invalid API key")
        request["principal"] = principal
        try:
            resp = await handler(request)
        except web.HTTPException:
            raise
        except WorkflowError as e:
            resp = _err(400, str(e))
        except SchemaError as e:
            resp = _err(400, str(e))
        except Exception as e:  # noqa: BLE001
            logx.error("gateway handler error", path=request.path, err=str(e))
            resp = _err(500, "internal error")
        self.metrics.http_requests.inc(method=request.method, path=request.match_info.route.resource.canonical if request.match_info.route.resource else request.path, status=str(resp.status))
        self.metrics.http_latency.observe(time.perf_counter() - t0)
        return resp

    # ------------------------------------------------------------------
    # lifecycle + bus taps
    # ------------------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 8081) -> None:
        self._subs.append(await self.bus.subscribe(subj.DLQ, self._tap_dlq))
        self._subs.append(await self.bus.subscribe(subj.JOB_EVENTS_WILDCARD, self._tap_events))
        self._subs.append(await self.bus.subscribe(subj.WORKFLOW_EVENT, self._tap_events))
        await self.span_collector.start()
        if self._telemetry_enabled:
            await self.fleet.start()
            await self.telemetry.start()
            await self.profiler.start()
        if self.admission.enabled and self._admission_task is None:
            self._admission_task = asyncio.ensure_future(self._admission_loop())
        if self.registry is not None:
            self._subs.append(await self.bus.subscribe(subj.HEARTBEAT, self._tap_heartbeat))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        logx.info("gateway listening", host=host, port=port)

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        if self._admission_task is not None:
            task, self._admission_task = self._admission_task, None
            task.cancel()
            await logx.join_task(task, name="admission-refresh")
        if self._telemetry_enabled:
            await self.profiler.stop()
            await self.telemetry.stop()
            await self.fleet.stop()
        await self.span_collector.stop()
        for ws in list(self._ws_clients):
            await ws.close()
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    async def _admission_loop(self) -> None:
        """Periodic admission refresh: rolls offered rates, re-reads the
        capacity matrix + SLO burn states, and beacons pressure to the
        scheduler's preemption governor (docs/ADMISSION.md)."""
        while True:
            await asyncio.sleep(1.0)
            try:
                self.admission.refresh()
                await self.admission.publish_pressure()
            except Exception as e:  # noqa: BLE001 - refresh must never die silently
                logx.warn("admission refresh failed", err=str(e))

    async def _tap_heartbeat(self, subject: str, pkt: BusPacket) -> None:
        if pkt.heartbeat and self.registry is not None:
            self.registry.update(pkt.heartbeat)

    async def _tap_dlq(self, subject: str, pkt: BusPacket) -> None:
        res = pkt.job_result
        if res is None:
            return
        await self.dlq.add(
            DLQEntry(
                job_id=res.job_id,
                topic=res.labels.get("topic", ""),
                status=res.status,
                reason=res.error_message,
                reason_code=res.error_code,
                last_state=res.status,
                tenant_id=res.labels.get("tenant_id", ""),
            )
        )
        # synthesize a result payload for UI reads (reference gateway.go:553-607)
        if not res.result_ptr:
            await self.mem.put_result(
                res.job_id, {"error": res.error_message, "code": res.error_code}
            )

    async def _tap_events(self, subject: str, pkt: BusPacket) -> None:
        if not self._ws_clients:
            return
        event = json.dumps({"subject": subject, "packet": pkt.to_dict()}, default=str)
        dead = []
        for ws in list(self._ws_clients):  # set mutates during awaits
            try:
                await ws.send_str(event)
            except Exception:
                dead.append(ws)
        for ws in dead:
            self._ws_clients.discard(ws)

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------
    async def submit_job(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        try:
            body = await request.json()
        except Exception:
            return _err(400, "invalid JSON body")
        status, doc = await self._submit_one(
            body, principal,
            idempotency_header=request.headers.get("Idempotency-Key", ""),
        )
        return web.json_response(doc, status=status,
                                 headers=_retry_after_headers(status, doc))

    async def submit_jobs_bulk(self, request: web.Request) -> web.Response:
        """``POST /api/v1/jobs:batch`` — submit many jobs in one round trip
        (body ``{"jobs": [<single-submit bodies>]}``).  Per-job verdicts ride
        back positionally; one bad job does not reject its batch-mates."""
        principal: Principal = request["principal"]
        try:
            body = await request.json()
        except Exception:
            return _err(400, "invalid JSON body")
        jobs = body.get("jobs")
        if not isinstance(jobs, list) or not jobs:
            return _err(400, "jobs: non-empty list required")
        if len(jobs) > MAX_BULK_JOBS:
            return _err(400, f"too many jobs in one batch (max {MAX_BULK_JOBS})")
        out: list[dict[str, Any]] = []
        accepted = 0
        retry_after = 0.0
        for doc in jobs:
            if not isinstance(doc, dict):
                out.append({"error": "job body must be an object", "status": 400})
                continue
            status, res = await self._submit_one(doc, principal)
            if status >= 400:
                entry = {"error": str(res.get("error", "rejected")), "status": status}
                if status == 429:
                    entry["retry_after_s"] = res.get("retry_after_s", 0.0)
                    retry_after = max(
                        retry_after, float(res.get("retry_after_s") or 0.0))
                out.append(entry)
            else:
                accepted += 1
                out.append(res)
        headers = (
            {"Retry-After": f"{retry_after:.3f}"} if retry_after > 0 else None
        )
        return web.json_response(
            {"jobs": out, "accepted": accepted, "rejected": len(out) - accepted},
            status=202 if accepted else 400, headers=headers,
        )

    async def _submit_one(
        self, body: dict, principal: Principal, *, idempotency_header: str = ""
    ) -> tuple[int, dict]:
        """The submit core shared by the single and bulk routes: validate,
        stamp labels (secrets, batch key), persist, publish.  Returns
        (http_status, response_doc)."""
        topic = str(body.get("topic", ""))
        if not topic:
            return 400, {"error": "topic is required"}
        payload = body.get("payload", body.get("context"))
        tenant = str(body.get("tenant_id") or principal.tenant_id)
        if tenant != principal.tenant_id and not principal.key_admin:
            # body tenant_id may not escape the key's tenant scope; gate on
            # key-derived admin status, not the forgeable role header
            # (reference RequireTenantAccess, basic_auth.go:100-122)
            return 403, {"error": f"tenant {tenant!r} not permitted for this principal"}
        # capacity-aware admission (docs/ADMISSION.md): shed BEFORE minting
        # state — a shed submission costs no KV writes and no bus traffic.
        # The op keys into the fleet throughput matrix the same way the
        # worker profiles it (payload op, else the topic).
        op = ""
        if isinstance(payload, dict):
            op = str(payload.get("op") or "")
        op = op or topic
        job_class = str(body.get("priority", "BATCH"))
        verdict = self.admission.admit(op=op, job_class=job_class, tenant=tenant)
        if not verdict.allowed:
            return 429, {
                "error": f"shed: {verdict.reason}",
                "reason": verdict.reason,
                "retry_after_s": verdict.retry_after_s,
            }
        job_id = str(body.get("job_id") or new_id())

        idem = str(body.get("idempotency_key") or idempotency_header)
        if idem:
            fresh, existing = await self.job_store.try_set_idempotency_key(tenant, idem, job_id)
            if not fresh:
                return 200, {"job_id": existing, "deduplicated": True}

        labels = {str(k): str(v) for k, v in (body.get("labels") or {}).items()}
        # batchable payloads carry their batch key as a label so the
        # scheduler can batch-affinity-route without reading the payload
        bkey = payload_batch_key(payload)
        if bkey and LABEL_BATCH_KEY not in labels:
            labels[LABEL_BATCH_KEY] = bkey
        # serving payloads carry their session id as a label so the
        # scheduler can route every turn of a conversation to the worker
        # holding its KV pages (session affinity, docs/SERVING.md)
        skey = payload_session_key(payload)
        if skey and LABEL_SESSION_KEY not in labels:
            labels[LABEL_SESSION_KEY] = skey
        # the resolved op rides as a label so capacity-aware consumers (the
        # ThroughputAwareStrategy's matrix lookup) never read the payload
        if LABEL_OP not in labels:
            labels[LABEL_OP] = op
        # gang payloads (docs/GANG.md) carry their placement ask as labels
        # so the scheduler's gang path (reserve N co-located workers,
        # all-or-nothing) never reads the payload behind the context pointer
        gspec = payload_gang(payload)
        if gspec is not None and LABEL_GANG_WORKERS not in labels:
            labels[LABEL_GANG_WORKERS] = str(int(gspec.get("workers", 1)))
            try:
                chips = int(gspec.get("chips_per_worker", 0) or 0)
            except (TypeError, ValueError):
                chips = 0
            if chips > 0:
                labels[LABEL_GANG_CHIPS] = str(chips)
            kind = str(gspec.get("kind", "") or "")
            if kind:
                # "serving" routes members into the worker's sharded
                # serving path (docs/SERVING.md §Sharded serving)
                labels[LABEL_GANG_KIND] = kind
        meta_doc = body.get("metadata") or {}
        metadata = JobMetadata(
            capability=str(meta_doc.get("capability", "")),
            risk_tags=list(meta_doc.get("risk_tags") or []),
            requires=list(meta_doc.get("requires") or []),
            pack_id=str(meta_doc.get("pack_id", "")),
        )
        if contains_secret_refs(payload) or contains_secret_refs(body.get("env")):
            labels[LABEL_SECRETS_PRESENT] = "true"
            if "secrets" not in metadata.risk_tags:
                metadata.risk_tags.append("secrets")

        ctx_ptr = await self.mem.put_context(job_id, payload)
        budget = Budget.from_dict(body.get("budget")) if body.get("budget") else None
        hints = ContextHints.from_dict(body.get("context_hints")) if body.get("context_hints") else None
        req = JobRequest(
            job_id=job_id,
            topic=topic,
            priority=str(body.get("priority", "BATCH")),
            context_ptr=ctx_ptr,
            memory_id=str(body.get("memory_id", "")),
            tenant_id=tenant,
            principal_id=principal.principal_id,
            adapter_id=str(body.get("adapter_id", "")),
            labels=labels,
            env={str(k): str(v) for k, v in (body.get("env") or {}).items()},
            metadata=metadata,
            budget=budget,
            context_hints=hints,
        )
        trace_id = str(body.get("trace_id") or new_id())
        # submit span: the trace root for API-submitted jobs; downstream
        # scheduler/kernel/worker spans hang off the packet's span context
        async with self.tracer.span(
            "submit",
            trace_id=trace_id,
            attrs={"job_id": job_id, "topic": topic, "tenant_id": tenant},
        ) as sp:
            await self.job_store.set_state(
                job_id,
                JobState.PENDING,
                fields={
                    "topic": topic,
                    "tenant_id": tenant,
                    "principal_id": principal.principal_id,
                    "context_ptr": ctx_ptr,
                    "trace_id": trace_id,
                    # SLO job class: the result path labels the class-split
                    # e2e/terminal metrics from this persisted field
                    "priority": req.priority,
                    "submitted_at_us": str(now_us()),
                },
                event="submit",
            )
            await self.job_store.put_request(req)
            await self.job_store.add_to_trace(trace_id, job_id)
            await self.bus.publish(
                subj.submit_subject_for(job_id, self.scheduler_shards),
                BusPacket.wrap(
                    req, trace_id=trace_id, sender_id=self.instance_id,
                    span_id=sp.span_id,
                ),
            )
        return 202, {"job_id": job_id, "trace_id": trace_id, "state": JobState.PENDING.value}

    async def get_job(self, request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        meta = await self.job_store.get_meta(job_id)
        if not meta:
            return _err(404, f"unknown job {job_id}")
        out: dict[str, Any] = {"job_id": job_id, **meta}
        if request.query.get("events") == "true":
            out["events"] = await self.job_store.events(job_id)
        if request.query.get("result") == "true" and meta.get("result_ptr"):
            out["result"] = await self.mem.get_pointer(meta["result_ptr"])
        return web.json_response(out)

    async def list_jobs(self, request: web.Request) -> web.Response:
        state = request.query.get("state", "")
        limit = int(request.query.get("limit", "50"))
        ids = (
            await self.job_store.list_by_state(state, limit)
            if state
            else await self.job_store.list_recent(limit)
        )
        jobs = []
        for jid in ids:
            meta = await self.job_store.get_meta(jid)
            jobs.append({"job_id": jid, "state": meta.get("state"), "topic": meta.get("topic"),
                         "tenant_id": meta.get("tenant_id")})
        return web.json_response({"jobs": jobs})

    async def cancel_job(self, request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        principal: Principal = request["principal"]
        if not await self.job_store.get_meta(job_id):
            return _err(404, f"unknown job {job_id}")
        await self.bus.publish(
            subj.cancel_subject(
                partition_of(job_id, self.scheduler_shards), self.scheduler_shards
            ),
            BusPacket.wrap(
                JobCancel(job_id=job_id, reason="api cancel", requested_by=principal.principal_id),
                sender_id=self.instance_id,
            ),
        )
        return web.json_response({"job_id": job_id, "cancelled": True})

    async def remediate_job(self, request: web.Request) -> web.Response:
        """Apply a safety-decision remediation: new job with safer topic/
        capability/labels (reference POST /jobs/{id}/remediate)."""
        job_id = request.match_info["job_id"]
        body = await request.json() if request.can_read_body else {}
        rem_id = str((body or {}).get("remediation_id", ""))
        decision = await self.job_store.get_safety_decision(job_id)
        if decision is None or not decision.remediations:
            return _err(404, "no remediations recorded for this job")
        rem = next((r for r in decision.remediations if not rem_id or r.get("id") == rem_id), None)
        if rem is None:
            return _err(404, f"unknown remediation {rem_id!r}")
        orig = await self.job_store.get_request(job_id)
        if orig is None:
            return _err(404, "original job request not found")
        new_id_ = new_id()
        ctx = await self.mem.get_context(orig.context_ptr) if orig.context_ptr else None
        new_ptr = await self.mem.put_context(new_id_, ctx)
        labels = {k: v for k, v in orig.labels.items() if k not in (rem.get("remove_labels") or [])}
        labels.update(rem.get("add_labels") or {})
        meta = orig.metadata or JobMetadata()
        new_req = JobRequest(
            job_id=new_id_,
            topic=rem.get("replacement_topic") or orig.topic,
            priority=orig.priority,
            context_ptr=new_ptr,
            memory_id=orig.memory_id,
            tenant_id=orig.tenant_id,
            principal_id=orig.principal_id,
            labels=labels,
            env=dict(orig.env),
            metadata=JobMetadata(
                capability=rem.get("replacement_capability") or meta.capability,
                risk_tags=list(meta.risk_tags),
                requires=list(meta.requires),
                pack_id=meta.pack_id,
            ),
        )
        await self.job_store.set_state(
            new_id_, JobState.PENDING,
            fields={"topic": new_req.topic, "tenant_id": new_req.tenant_id,
                    "remediated_from": job_id, "submitted_at_us": str(now_us())},
            event="remediate",
        )
        await self.job_store.put_request(new_req)
        await self.bus.publish(
            subj.submit_subject_for(new_id_, self.scheduler_shards),
            BusPacket.wrap(new_req, sender_id=self.instance_id),
        )
        return web.json_response({"job_id": new_id_, "remediated_from": job_id}, status=202)

    # ------------------------------------------------------------------
    # approvals (reference gateway.go:3700-3838)
    # ------------------------------------------------------------------
    async def list_approvals(self, request: web.Request) -> web.Response:
        ids = await self.job_store.list_by_state(JobState.APPROVAL_REQUIRED.value, 200)
        out = []
        for jid in ids:
            meta = await self.job_store.get_meta(jid)
            rec = await self.job_store.get_safety_decision(jid)
            out.append({
                "job_id": jid,
                "topic": meta.get("topic"),
                "tenant_id": meta.get("tenant_id"),
                "reason": meta.get("approval_reason", ""),
                "policy_snapshot": rec.policy_snapshot if rec else "",
            })
        return web.json_response({"approvals": out})

    async def approve_job(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        if principal.role != "admin":
            return _err(403, "approvals require the admin role")
        job_id = request.match_info["job_id"]
        state = await self.job_store.get_state(job_id)
        if state != JobState.APPROVAL_REQUIRED.value:
            return _err(409, f"job is {state or 'unknown'}, not APPROVAL_REQUIRED")
        rec = await self.job_store.get_safety_decision(job_id)
        req = await self.job_store.get_request(job_id)
        if rec is None or req is None or not rec.job_hash:
            return _err(409, "no bound safety decision for this job")
        if rec.job_hash != job_hash(req):
            return _err(409, "stored request no longer matches the approved content")
        # re-check against the CURRENT kernel: policy may have tightened
        fresh = await self.kernel.check(
            PolicyCheckRequest(
                job_id=job_id, tenant_id=req.tenant_id, principal_id=req.principal_id,
                topic=req.topic, labels=dict(req.labels), metadata=req.metadata,
            )
        )
        if fresh.decision == "DENY":
            return _err(409, f"current policy denies this job: {fresh.reason}")
        await self.job_store.put_approval(
            ApprovalRecord(job_id=job_id, approved_by=principal.principal_id, approved=True,
                           job_hash=rec.job_hash, policy_snapshot=rec.policy_snapshot)
        )
        await self.job_store.append_event(job_id, "approved", by=principal.principal_id)
        republish = JobRequest.from_dict(req.to_dict())
        republish.labels = dict(republish.labels or {})
        republish.labels[LABEL_APPROVAL_GRANTED] = "true"
        republish.labels[LABEL_BUS_MSG_ID] = f"approve-{job_id}-{now_us()}"
        await self.bus.publish(
            subj.submit_subject_for(job_id, self.scheduler_shards),
            BusPacket.wrap(republish, sender_id=self.instance_id),
        )
        return web.json_response({"job_id": job_id, "approved": True})

    async def reject_job(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        if principal.role != "admin":
            return _err(403, "approvals require the admin role")
        job_id = request.match_info["job_id"]
        state = await self.job_store.get_state(job_id)
        if state != JobState.APPROVAL_REQUIRED.value:
            return _err(409, f"job is {state or 'unknown'}, not APPROVAL_REQUIRED")
        body = await request.json() if request.can_read_body else {}
        reason = str((body or {}).get("reason", "rejected"))
        await self.job_store.put_approval(
            ApprovalRecord(job_id=job_id, approved_by=principal.principal_id, approved=False, reason=reason)
        )
        await self.job_store.set_state(
            job_id, JobState.DENIED, fields={"deny_reason": f"approval rejected: {reason}"},
            event="approval_rejected",
        )
        return web.json_response({"job_id": job_id, "approved": False})

    # ------------------------------------------------------------------
    # workflows + runs
    # ------------------------------------------------------------------
    async def put_workflow(self, request: web.Request) -> web.Response:
        doc = await request.json()
        wf = Workflow.from_dict(doc)
        if not wf.id:
            wf.id = new_id()
        errs = wf.validate()
        if errs:
            return _err(400, "; ".join(errs))
        await self.wf_store.put_workflow(wf)
        return web.json_response({"id": wf.id, "version": wf.version}, status=201)

    async def list_workflows(self, request: web.Request) -> web.Response:
        ids = await self.wf_store.list_workflows()
        return web.json_response({"workflows": ids})

    async def get_workflow(self, request: web.Request) -> web.Response:
        wf = await self.wf_store.get_workflow(request.match_info["wf_id"])
        if wf is None:
            return _err(404, "unknown workflow")
        return web.json_response(wf.to_dict())

    async def delete_workflow(self, request: web.Request) -> web.Response:
        ok = await self.wf_store.delete_workflow(request.match_info["wf_id"])
        return web.json_response({"deleted": ok}, status=200 if ok else 404)

    async def start_run(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        wf_id = request.match_info["wf_id"]
        body = await request.json() if request.can_read_body else {}
        body = body or {}
        org = str(body.get("org_id") or principal.tenant_id)
        if org != principal.tenant_id and not principal.key_admin:
            # body org_id may not escape the key's tenant scope (same class
            # as the submit_job tenant guard)
            return _err(403, f"org {org!r} not permitted for this principal")
        labels = {str(k): str(v) for k, v in (body.get("labels") or {}).items()}
        wf = await self.wf_store.get_workflow(wf_id)
        if wf is None:
            return _err(404, "unknown workflow")
        # a run rides the admission ladder like a job: its SLO class (per-run
        # label override > workflow default) is the job class every dispatched
        # step inherits, so shedding happens before any step is scheduled
        slo = str(labels.get(LABEL_SLO_CLASS) or wf.slo_class or "").upper()
        if slo:
            labels[LABEL_SLO_CLASS] = slo
        verdict = self.admission.admit(
            op="workflow.run", job_class=slo or "BATCH", tenant=org
        )
        if not verdict.allowed:
            doc = {
                "error": f"shed: {verdict.reason}",
                "reason": verdict.reason,
                "retry_after_s": verdict.retry_after_s,
            }
            return web.json_response(
                doc, status=429, headers=_retry_after_headers(429, doc))
        run = await self.wf_engine.start_run(
            wf_id,
            body.get("input"),
            org_id=org,
            idempotency_key=request.headers.get("Idempotency-Key", str(body.get("idempotency_key", ""))),
            dry_run=bool(body.get("dry_run", False)),
            labels=labels,
            max_concurrent_runs=self.max_concurrent_runs,
        )
        return web.json_response({"run_id": run.run_id, "status": run.status}, status=202)

    async def list_runs(self, request: web.Request) -> web.Response:
        ids = await self.wf_store.list_runs(request.query.get("workflow_id", ""))
        if request.query.get("detail") in ("1", "true"):
            # summary docs in one batched fetch (cordumctl runs table)
            runs = await self.wf_store.get_runs(ids)
            docs = [
                {
                    "run_id": r.run_id,
                    "workflow_id": r.workflow_id,
                    "status": r.status,
                    "org_id": r.org_id,
                    "slo_class": r.labels.get(LABEL_SLO_CLASS, ""),
                    "trace_id": r.trace_id,
                    "created_at_us": r.created_at_us,
                    "finished_at_us": r.finished_at_us,
                    "steps": {k: sr.status for k, sr in r.steps.items()},
                }
                for r in runs
                if r is not None
            ]
            return web.json_response({"runs": docs})
        return web.json_response({"runs": ids})

    async def get_run(self, request: web.Request) -> web.Response:
        run = await self.wf_store.get_run(request.match_info["run_id"])
        if run is None:
            return _err(404, "unknown run")
        return web.json_response(run.to_dict())

    async def _with_run_lock(self, run_id: str, fn):
        """Run mutations must hold the same per-run lock the workflow-engine
        service uses, or concurrent result handling loses updates."""
        for _ in range(40):  # ~2s of 50ms retries before giving up
            if await self.wf_engine.store.acquire_run_lock(run_id, self.instance_id):
                try:
                    return await fn()
                finally:
                    await self.wf_engine.store.release_run_lock(run_id, self.instance_id)
            await asyncio.sleep(0.05)
        raise web.HTTPConflict(reason=f"run {run_id} is busy; retry")

    async def cancel_run(self, request: web.Request) -> web.Response:
        run_id = request.match_info["run_id"]
        run = await self._with_run_lock(
            run_id, lambda: self.wf_engine.cancel_run(run_id, reason="api cancel")
        )
        return web.json_response({"run_id": run.run_id, "status": run.status})

    async def rerun(self, request: web.Request) -> web.Response:
        body = await request.json() if request.can_read_body else {}
        body = body or {}
        step_id = str(body.get("from_step", ""))
        if not step_id:
            return _err(400, "from_step is required")
        run_id = request.match_info["run_id"]
        run = await self._with_run_lock(
            run_id,
            lambda: self.wf_engine.rerun_from(
                run_id, step_id, dry_run=bool(body.get("dry_run", False))
            ),
        )
        return web.json_response({"run_id": run.run_id, "status": run.status}, status=202)

    async def approve_step(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        if principal.role != "admin":
            return _err(403, "step approvals require the admin role")
        body = await request.json() if request.can_read_body else {}
        body = body or {}
        run_id = request.match_info["run_id"]
        run = await self._with_run_lock(
            run_id,
            lambda: self.wf_engine.approve_step(
                run_id,
                request.match_info["step_id"],
                approve=bool(body.get("approve", True)),
                approved_by=principal.principal_id,
            ),
        )
        return web.json_response({"run_id": run.run_id, "status": run.status})

    async def run_timeline(self, request: web.Request) -> web.Response:
        tl = await self.wf_store.timeline(request.match_info["run_id"])
        return web.json_response({"timeline": tl})

    # ------------------------------------------------------------------
    # DLQ
    # ------------------------------------------------------------------
    async def list_dlq(self, request: web.Request) -> web.Response:
        offset = int(request.query.get("offset", "0"))
        limit = int(request.query.get("limit", "50"))
        entries = await self.dlq.list(offset, limit)
        return web.json_response({
            "entries": [e.__dict__ for e in entries],
            "total": await self.dlq.count(),
        })

    async def delete_dlq(self, request: web.Request) -> web.Response:
        ok = await self.dlq.delete(request.match_info["job_id"])
        return web.json_response({"deleted": ok}, status=200 if ok else 404)

    async def _retry_dlq_job(self, job_id: str) -> Optional[str]:
        """The per-job DLQ re-drive: NEW job id, rehydrated context, fresh
        submit (reference gateway.go:3452).  Returns the new job id, or None
        when the entry/original request is gone.  Shared by the single-job
        route and ``retry-all``."""
        entry = await self.dlq.get(job_id)
        orig = await self.job_store.get_request(job_id)
        if entry is None or orig is None:
            return None
        new_jid = new_id()
        ctx = await self.mem.get_context(orig.context_ptr) if orig.context_ptr else None
        new_ptr = await self.mem.put_context(new_jid, ctx)
        req = JobRequest.from_dict(orig.to_dict())
        req.job_id = new_jid
        req.context_ptr = new_ptr
        req.labels = {k: v for k, v in (req.labels or {}).items() if k != LABEL_BUS_MSG_ID}
        await self.job_store.set_state(
            new_jid, JobState.PENDING,
            fields={"topic": req.topic, "tenant_id": req.tenant_id, "retried_from": job_id,
                    "submitted_at_us": str(now_us())},
            event="dlq_retry",
        )
        await self.job_store.put_request(req)
        await self.bus.publish(
            subj.submit_subject_for(new_jid, self.scheduler_shards),
            BusPacket.wrap(req, sender_id=self.instance_id),
        )
        await self.dlq.delete(job_id)
        return new_jid

    async def retry_dlq(self, request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        new_jid = await self._retry_dlq_job(job_id)
        if new_jid is None:
            return _err(404, "job not found in DLQ")
        return web.json_response({"job_id": new_jid, "retried_from": job_id}, status=202)

    async def retry_all_dlq(self, request: web.Request) -> web.Response:
        """Re-drive every dead-lettered job via the per-job retry path
        (admin: a bulk resubmit can flood the scheduler)."""
        if (deny := self._require_admin(request)) is not None:
            return deny
        body = await request.json() if request.can_read_body else {}
        results = await self.dlq.retry_all(
            self._retry_dlq_job, limit=int((body or {}).get("limit", 0))
        )
        return web.json_response({
            "retried": [
                {"job_id": jid, "new_job_id": new} for jid, new in results if new
            ],
            "skipped": [jid for jid, new in results if not new],
            "count": sum(1 for _, new in results if new),
        }, status=202)

    async def purge_dlq(self, request: web.Request) -> web.Response:
        """Drop DLQ entries older than a cutoff: body ``{"older_than_us": N}``
        or ``{"max_age_s": N}`` (admin: purging is irreversible)."""
        if (deny := self._require_admin(request)) is not None:
            return deny
        body = await request.json() if request.can_read_body else {}
        body = body or {}
        if "older_than_us" in body:
            cutoff = int(body["older_than_us"])
        elif "max_age_s" in body:
            cutoff = now_us() - int(float(body["max_age_s"]) * 1e6)
        else:
            return _err(400, "older_than_us or max_age_s is required")
        purged = await self.dlq.purge_older_than(cutoff)
        return web.json_response({"purged": purged})

    # ------------------------------------------------------------------
    # policy admin
    # ------------------------------------------------------------------
    @staticmethod
    def _policy_check_request(doc: dict) -> PolicyCheckRequest:
        meta = doc.get("metadata")
        return PolicyCheckRequest(
            job_id=str(doc.get("job_id", "")),
            tenant_id=str(doc.get("tenant_id", "")),
            principal_id=str(doc.get("principal_id", "")),
            topic=str(doc.get("topic", "")),
            labels={str(k): str(v) for k, v in (doc.get("labels") or {}).items()},
            metadata=JobMetadata.from_dict(meta) if meta else None,
            actor_id=str(doc.get("actor_id", "")),
            actor_type=str(doc.get("actor_type", "")),
            effective_config=doc.get("effective_config") or {},
        )

    async def policy_evaluate(self, request: web.Request) -> web.Response:
        doc = await request.json()
        resp = await self.kernel.evaluate_raw(self._policy_check_request(doc))
        return web.json_response(resp.to_dict())

    async def policy_simulate(self, request: web.Request) -> web.Response:
        doc = await request.json()
        results = await self.kernel.simulate(
            doc.get("policy") or {},
            [self._policy_check_request(r) for r in (doc.get("requests") or [])],
        )
        return web.json_response({"results": results})

    async def policy_explain(self, request: web.Request) -> web.Response:
        doc = await request.json()
        return web.json_response(await self.kernel.explain(self._policy_check_request(doc)))

    async def policy_snapshots(self, request: web.Request) -> web.Response:
        return web.json_response({"snapshots": self.kernel.list_snapshots(),
                                  "current": self.kernel.snapshot_id})

    # ------------------------------------------------------------------
    # pack catalogs (local-directory marketplace equivalent)
    # ------------------------------------------------------------------
    def _catalog(self):
        from ...packs import PackCatalog

        return PackCatalog(self.configsvc, self._pack_installer())

    async def list_catalogs(self, request: web.Request) -> web.Response:
        return web.json_response({"catalogs": await self._catalog().list_catalogs()})

    async def add_catalog(self, request: web.Request) -> web.Response:
        from ...packs import PackError

        if (deny := self._require_admin(request)) is not None:
            return deny
        body = await request.json()
        try:
            cat = self._catalog()
            if body.get("allowed_roots") is not None:
                await cat.set_allowed_roots(list(body["allowed_roots"]))
            entry = None
            if body.get("name") and body.get("path"):
                entry = await cat.add_catalog(str(body["name"]), str(body["path"]))
        except PackError as e:
            return _err(400, str(e))
        return web.json_response({"added": entry}, status=201)

    async def catalog_packs(self, request: web.Request) -> web.Response:
        from ...packs import PackError

        try:
            packs = await self._catalog().list_packs(request.match_info["catalog"])
        except PackError as e:
            return _err(404, str(e))
        return web.json_response({"packs": packs})

    async def catalog_install(self, request: web.Request) -> web.Response:
        from ...packs import PackError

        if (deny := self._require_admin(request)) is not None:
            return deny
        try:
            record = await self._catalog().install_from_catalog(
                request.match_info["catalog"], request.match_info["pack_id"]
            )
        except PackError as e:
            return _err(400, str(e))
        return web.json_response(record, status=201)

    # ------------------------------------------------------------------
    # policy bundles (reference policy_bundles.go)
    # ------------------------------------------------------------------
    def _bundles(self):
        from ..safetykernel.bundles import PolicyBundleAdmin

        if self.configsvc is None:
            raise web.HTTPNotImplemented(reason="config service not wired")
        return PolicyBundleAdmin(self.kv, self.configsvc, self.kernel)

    @staticmethod
    def _bundle_id(request: web.Request) -> str:
        from ..safetykernel.bundles import unescape_bundle_id

        return unescape_bundle_id(request.match_info["bundle_id"])

    def _require_admin(self, request: web.Request) -> Optional[web.Response]:
        if request["principal"].role != "admin":
            return _err(403, "policy administration requires the admin role")
        return None

    async def bundles_list(self, request: web.Request) -> web.Response:
        return web.json_response({"bundles": await self._bundles().list_bundles()})

    async def bundles_get(self, request: web.Request) -> web.Response:
        b = await self._bundles().get_bundle(self._bundle_id(request))
        return web.json_response(b) if b else _err(404, "unknown bundle")

    async def bundles_put(self, request: web.Request) -> web.Response:
        if (deny := self._require_admin(request)) is not None:
            return deny
        result = await self._bundles().put_bundle(
            self._bundle_id(request), await request.json(),
            actor=request["principal"].principal_id,
        )
        return web.json_response(result, status=201)

    async def bundles_delete(self, request: web.Request) -> web.Response:
        if (deny := self._require_admin(request)) is not None:
            return deny
        ok = await self._bundles().delete_bundle(
            self._bundle_id(request), actor=request["principal"].principal_id
        )
        return web.json_response({"deleted": ok}, status=200 if ok else 404)

    async def bundles_publish(self, request: web.Request) -> web.Response:
        if (deny := self._require_admin(request)) is not None:
            return deny
        try:
            result = await self._bundles().publish(
                self._bundle_id(request), actor=request["principal"].principal_id
            )
        except KeyError as e:
            return _err(404, str(e))
        return web.json_response(result)

    async def bundles_unpublish(self, request: web.Request) -> web.Response:
        if (deny := self._require_admin(request)) is not None:
            return deny
        try:
            result = await self._bundles().unpublish(
                self._bundle_id(request), actor=request["principal"].principal_id
            )
        except KeyError as e:
            return _err(404, str(e))
        return web.json_response(result)

    async def bundles_simulate(self, request: web.Request) -> web.Response:
        doc = await request.json()
        bundle = await self._bundles().get_bundle(self._bundle_id(request))
        data = doc.get("draft") or (bundle or {}).get("data") or {}
        results = await self._bundles().simulate_draft(
            data, [self._policy_check_request(r) for r in (doc.get("requests") or [])]
        )
        return web.json_response({"results": results})

    async def snapshots_capture(self, request: web.Request) -> web.Response:
        if (deny := self._require_admin(request)) is not None:
            return deny
        body = await request.json() if request.can_read_body else {}
        result = await self._bundles().capture_snapshot(
            actor=request["principal"].principal_id, note=str((body or {}).get("note", ""))
        )
        return web.json_response(result, status=201)

    async def snapshots_captured(self, request: web.Request) -> web.Response:
        return web.json_response({"snapshots": await self._bundles().list_captured()})

    async def snapshots_rollback(self, request: web.Request) -> web.Response:
        if (deny := self._require_admin(request)) is not None:
            return deny
        try:
            result = await self._bundles().rollback(
                request.match_info["snapshot_id"], actor=request["principal"].principal_id
            )
        except KeyError as e:
            return _err(404, str(e))
        return web.json_response(result)

    async def policy_audit(self, request: web.Request) -> web.Response:
        return web.json_response({"audit": await self._bundles().audit_log()})

    # ------------------------------------------------------------------
    # packs (reference gateway packs.go installer endpoints)
    # ------------------------------------------------------------------
    def _pack_installer(self):
        from ...packs import PackInstaller

        if self.configsvc is None:
            raise web.HTTPNotImplemented(reason="config service not wired")
        return PackInstaller(
            configsvc=self.configsvc, schemas=self.schemas,
            wf_store=self.wf_store, kernel=self.kernel,
        )

    async def install_pack(self, request: web.Request) -> web.Response:
        from ...packs import PackError, manifest_from_doc

        principal: Principal = request["principal"]
        if principal.role != "admin":
            return _err(403, "pack installs require the admin role")
        try:
            m = manifest_from_doc(await request.json())
            record = await self._pack_installer().install(m)
        except PackError as e:
            return _err(400, str(e))
        return web.json_response(record, status=201)

    async def list_packs(self, request: web.Request) -> web.Response:
        installed = await self._pack_installer().list_installed()
        return web.json_response({"packs": installed})

    async def show_pack(self, request: web.Request) -> web.Response:
        installed = await self._pack_installer().list_installed()
        rec = installed.get(request.match_info["pack_id"])
        if rec is None:
            return _err(404, "pack not installed")
        return web.json_response(rec)

    async def uninstall_pack(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        if principal.role != "admin":
            return _err(403, "pack uninstalls require the admin role")
        ok = await self._pack_installer().uninstall(request.match_info["pack_id"])
        return web.json_response({"uninstalled": ok}, status=200 if ok else 404)

    # ------------------------------------------------------------------
    # config / schemas / locks / artifacts / memory / traces
    # ------------------------------------------------------------------
    async def config_get(self, request: web.Request) -> web.Response:
        if self.configsvc is None:
            return _err(501, "config service not wired")
        doc = await self.configsvc.get(request.match_info["scope"], request.match_info["doc_id"])
        if doc is None:
            return _err(404, "unknown config doc")
        return web.json_response({"scope": doc.scope, "id": doc.doc_id, "revision": doc.revision,
                                  "data": doc.data})

    async def config_set(self, request: web.Request) -> web.Response:
        principal: Principal = request["principal"]
        if principal.role != "admin":
            return _err(403, "config writes require the admin role")
        if self.configsvc is None:
            return _err(501, "config service not wired")
        body = await request.json()
        scope, doc_id = request.match_info["scope"], request.match_info["doc_id"]
        if body.get("patch"):
            doc = await self.configsvc.patch(scope, doc_id, body["patch"])
        else:
            doc = await self.configsvc.set(scope, doc_id, body.get("data") or {})
        await self.kernel.reload()  # policy fragments may have changed
        return web.json_response({"scope": scope, "id": doc_id, "revision": doc.revision})

    async def config_effective(self, request: web.Request) -> web.Response:
        if self.configsvc is None:
            return _err(501, "config service not wired")
        q = request.query
        eff = await self.configsvc.effective(
            org=q.get("org", ""), team=q.get("team", ""),
            workflow=q.get("workflow", ""), step=q.get("step", ""),
        )
        return web.json_response({"effective": eff})

    async def list_schemas(self, request: web.Request) -> web.Response:
        return web.json_response({"schemas": await self.schemas.list()})

    async def get_schema(self, request: web.Request) -> web.Response:
        s = await self.schemas.get(request.match_info["schema_id"])
        if s is None:
            return _err(404, "unknown schema")
        return web.json_response(s)

    async def put_schema(self, request: web.Request) -> web.Response:
        await self.schemas.put(request.match_info["schema_id"], await request.json())
        return web.json_response({"id": request.match_info["schema_id"]}, status=201)

    async def delete_schema(self, request: web.Request) -> web.Response:
        ok = await self.schemas.delete(request.match_info["schema_id"])
        return web.json_response({"deleted": ok}, status=200 if ok else 404)

    async def list_locks(self, request: web.Request) -> web.Response:
        infos = await self.locks.list()
        return web.json_response({"locks": [i.__dict__ for i in infos]})

    async def acquire_lock(self, request: web.Request) -> web.Response:
        body = await request.json() if request.can_read_body else {}
        body = body or {}
        principal: Principal = request["principal"]
        ok = await self.locks.acquire(
            request.match_info["resource"],
            str(body.get("owner") or principal.principal_id),
            mode=str(body.get("mode", "exclusive")),
            ttl_s=float(body.get("ttl_s", 30.0)),
        )
        return web.json_response({"acquired": ok}, status=200 if ok else 409)

    async def release_lock(self, request: web.Request) -> web.Response:
        body = await request.json() if request.can_read_body else {}
        body = body or {}
        principal: Principal = request["principal"]
        ok = await self.locks.release(
            request.match_info["resource"], str(body.get("owner") or principal.principal_id)
        )
        return web.json_response({"released": ok}, status=200 if ok else 404)

    async def put_artifact(self, request: web.Request) -> web.Response:
        data = await request.read()
        meta = await self.artifacts.put(
            data,
            content_type=request.content_type or "application/octet-stream",
            retention=request.query.get("retention", "standard"),
        )
        return web.json_response(
            {"artifact_id": meta.artifact_id, "pointer": self.artifacts.pointer(meta.artifact_id),
             "size": meta.size},
            status=201,
        )

    async def get_artifact(self, request: web.Request) -> web.Response:
        data, meta = await self.artifacts.get(request.match_info["artifact_id"])
        if data is None:
            return _err(404, "unknown artifact")
        return web.Response(body=data, content_type=meta.content_type if meta else "application/octet-stream")

    async def context_window(self, request: web.Request) -> web.Response:
        if getattr(self, "context_svc", None) is None:
            return _err(501, "context engine not wired")
        body = await request.json()
        msgs = await self.context_svc.build_window(
            str(body.get("memory_id", "")),
            mode=str(body.get("mode", "RAW")).upper(),
            payload=body.get("payload"),
            max_input_tokens=int(body.get("max_input_tokens", 4000)),
        )
        return web.json_response({"messages": [m.to_dict() for m in msgs]})

    async def context_update(self, request: web.Request) -> web.Response:
        if getattr(self, "context_svc", None) is None:
            return _err(501, "context engine not wired")
        body = await request.json()
        await self.context_svc.update_memory(
            request.match_info["memory_id"],
            user_payload=body.get("payload"),
            model_response=str(body.get("model_response", "")),
            mode=str(body.get("mode", "CHAT")).upper(),
        )
        return web.json_response({"ok": True})

    async def context_chunks(self, request: web.Request) -> web.Response:
        if getattr(self, "context_svc", None) is None:
            return _err(501, "context engine not wired")
        body = await request.json()
        n = await self.context_svc.put_chunks(
            request.match_info["memory_id"], list(body.get("chunks") or [])
        )
        return web.json_response({"embedded": n})

    async def read_pointer(self, request: web.Request) -> web.Response:
        ptr = request.query.get("ptr", "")
        if not ptr:
            return _err(400, "ptr query param required")
        value = await self.mem.get_pointer(ptr)
        if value is None:
            return _err(404, "pointer not found")
        return web.json_response({"ptr": ptr, "value": value})

    async def get_trace(self, request: web.Request) -> web.Response:
        """Trace reader: job-id grouping (legacy shape) + the flight-recorder
        span waterfall — tree, per-stage durations, critical path."""
        trace_id = request.match_info["trace_id"]
        job_ids = sorted(await self.job_store.trace(trace_id))
        jobs = []
        for jid in job_ids:
            meta = await self.job_store.get_meta(jid)
            jobs.append({"job_id": jid, "state": meta.get("state"), "topic": meta.get("topic")})
        doc = assemble(trace_id, await self.span_collector.spans(trace_id))
        doc["jobs"] = jobs
        return web.json_response(doc)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    async def get_workers(self, request: web.Request) -> web.Response:
        if self.registry is not None:
            return web.json_response(self.registry.snapshot_json())
        snap = await self.kv.get("sys:workers:snapshot")
        return web.json_response(json.loads(snap) if snap else {"workers": {}, "count": 0})

    async def drain_worker(self, request: web.Request) -> web.Response:
        """``POST /api/v1/workers/{worker_id}/drain`` — ask a worker to
        drain gracefully: stop admitting, live-migrate its serving sessions
        to peers, finish per-job work, then exit (docs/SERVING.md
        §Migration, drain, and failover).  Fire-and-forget: the drain
        request fans out on the bus and progress shows up as the worker's
        ``draining`` heartbeat and its fleet beacon."""
        principal: Principal = request["principal"]
        worker_id = request.match_info["worker_id"]
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:  # noqa: BLE001 - body is optional
                body = {}
        await self.bus.publish(
            subj.DRAIN,
            BusPacket.wrap(
                WorkerDrain(
                    worker_id=worker_id,
                    reason=str((body or {}).get("reason", "api drain")),
                    requested_by=principal.principal_id,
                ),
                sender_id=self.instance_id,
            ),
        )
        return web.json_response(
            {"worker_id": worker_id, "draining": True}, status=202
        )

    async def get_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "bus": await self.bus.ping(),
            "kv": await self.kv.ping(),
            "policy_snapshot": self.kernel.snapshot_id,
            "workers": len(self.registry.snapshot()) if self.registry else None,
            "ws_clients": len(self._ws_clients),
        })

    async def healthz(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    def _telemetry_health(self) -> dict:
        return {
            "role": "gateway",
            "ws_clients": len(self._ws_clients),
            "scheduler_shards": self.scheduler_shards,
            **self.profiler.health(),
        }

    async def get_fleet(self, request: web.Request) -> web.Response:
        """``GET /api/v1/fleet`` — per-service health beacons, fleet-wide
        rates and stage latencies, SLO burn states (docs/OBSERVABILITY.md
        §Fleet telemetry)."""
        return web.json_response(self.fleet.fleet_doc(self.slo_tracker))

    async def list_traces(self, request: web.Request) -> web.Response:
        """``GET /api/v1/traces?last=N`` — newest trace summaries from the
        collector index (`cordum traces --last N`)."""
        n = min(200, max(1, int(request.query.get("last", "20"))))
        return web.json_response(
            {"traces": await self.span_collector.recent(n)}
        )

    async def traces_analysis(self, request: web.Request) -> web.Response:
        """``GET /api/v1/traces/analysis?last=N`` — cross-trace critical-path
        blame over the newest N stored traces: per-stage blame shares
        (summing to ~1.0) with p50/p99 of each stage's exclusive time, plus
        the slowest trace ids as exemplars (`cordum traces blame`)."""
        n = min(500, max(1, int(request.query.get("last", "100"))))
        ids = await self.span_collector.recent_trace_ids(n)
        docs = [
            assemble(tid, await self.span_collector.spans(tid)) for tid in ids
        ]
        return web.json_response(aggregate_critical_paths(docs))

    async def get_capacity(self, request: web.Request) -> web.Response:
        """``GET /api/v1/capacity`` — the op × worker throughput matrix
        folded from the workers' capacity beacons (`cordumctl capacity`;
        the heterogeneity-aware strategy's read-only input)."""
        return web.json_response(self.fleet.capacity_doc())

    async def get_admission(self, request: web.Request) -> web.Response:
        """``GET /api/v1/admission`` — live admission-controller state:
        per-(op, class) headroom, current brownout tier, per-tenant bucket
        levels (`cordumctl admission`, docs/ADMISSION.md)."""
        return web.json_response(self.admission.doc())

    async def get_gangs(self, request: web.Request) -> web.Response:
        """``GET /api/v1/gangs`` — the live gang table merged from the
        scheduler shards' health beacons (`cordumctl gangs`,
        docs/GANG.md)."""
        return web.json_response(self.fleet.gangs_doc())

    async def get_metrics(self, request: web.Request) -> web.Response:
        # ?scope=fleet: the aggregator's fleet-merged exposition (counters/
        # histograms summed across processes, gauges per instance)
        if request.query.get("scope") == "fleet":
            return web.Response(
                text=self.fleet.render(), content_type="text/plain"
            )
        return web.Response(text=self.metrics.render(), content_type="text/plain")

    async def ws_stream(self, request: web.Request) -> web.WebSocketResponse:
        origin = request.headers.get("Origin", "")
        if self.ws_allowed_origins is not None and origin and origin not in self.ws_allowed_origins:
            raise web.HTTPForbidden(reason="origin not allowed")
        # echo the offered subprotocol back so browser handshakes complete
        # when the API key rides Sec-WebSocket-Protocol
        offered = [p.strip() for p in
                   request.headers.get("Sec-WebSocket-Protocol", "").split(",") if p.strip()]
        ws = web.WebSocketResponse(heartbeat=30, protocols=offered or ())
        await ws.prepare(request)
        self._ws_clients.add(ws)
        try:
            async for msg in ws:
                if msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                    break
        finally:
            self._ws_clients.discard(ws)
        return ws
