"""Gateway auth: pluggable provider, OSS flat API-key allowlist
(reference ``core/controlplane/gateway/basic_auth.go`` + ``auth_provider.go``).

The OSS provider trusts ``X-Principal-Id`` once the API key checks out, but
``X-Principal-Role`` may never ESCALATE a non-admin key to admin — admin
status is key-derived (``admin_keys``), and tenant selection is bounded by
the key's assigned tenant (reference ResolveTenant/RequireTenantAccess,
``basic_auth.go:100-122``).  Enterprise RBAC is explicitly out of scope
(reference keeps it out-of-repo too).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Principal:
    principal_id: str = "anonymous"
    role: str = "user"  # user | admin
    tenant_id: str = "default"
    authenticated: bool = False
    # True only when the admin status is key-derived (or dev open mode) —
    # never from the client-forgeable X-Principal-Role header. Use this for
    # authorization decisions that cross trust boundaries (tenant escapes).
    key_admin: bool = False


class AuthProvider:
    def authenticate(self, headers) -> Optional[Principal]:
        raise NotImplementedError


class BasicAuthProvider(AuthProvider):
    """Flat API-key allowlist; empty key list = open (dev mode)."""

    def __init__(self, api_keys: Optional[list[str]] = None, *, admin_keys: Optional[list[str]] = None,
                 default_tenant: str = "default",
                 key_tenants: Optional[dict[str, str]] = None):
        self.api_keys = set(api_keys or [])
        self.admin_keys = set(admin_keys or [])
        self.default_tenant = default_tenant
        # key → tenant that key is scoped to (reference ResolveTenant /
        # RequireTenantAccess, basic_auth.go:100-122): a keyholder may not
        # pick an arbitrary tenant — only its assigned one (or the default).
        self.key_tenants = dict(key_tenants or {})

    def authenticate(self, headers) -> Optional[Principal]:
        key = headers.get("X-Api-Key", "")
        auth = headers.get("Authorization", "")
        if not key and auth.startswith("Bearer "):
            key = auth[len("Bearer "):]
        # dev open mode ONLY when no keys of either kind are configured:
        # admin_keys alone must still gate (and must not make anonymous
        # requests key_admin)
        keyed = bool(self.api_keys or self.admin_keys)
        if keyed and key not in self.api_keys and key not in self.admin_keys:
            return None
        key_admin = (key in self.admin_keys) or not keyed
        role = headers.get("X-Principal-Role", "")
        if key and key in self.admin_keys:
            role = role or "admin"
        elif keyed and role == "admin":
            role = "user"  # header may not escalate a non-admin key
        allowed_tenant = self.key_tenants.get(key, self.default_tenant)
        requested = headers.get("X-Tenant-Id", "")
        if requested and requested != allowed_tenant and not key_admin:
            return None
        return Principal(
            principal_id=headers.get("X-Principal-Id", "anonymous"),
            role=role or "user",
            tenant_id=requested or allowed_tenant,
            authenticated=bool(key) or not keyed,
            key_admin=key_admin,
        )


class TokenBucket:
    """Per-key token bucket (reference gateway rate limiting,
    ``API_RATE_LIMIT_RPS/BURST``)."""

    def __init__(self, rps: float = 0.0, burst: int = 0):
        self.rps = rps
        self.burst = burst or int(rps * 2) or 1
        self._state: dict[str, tuple[float, float]] = {}

    def allow(self, key: str) -> bool:
        if self.rps <= 0:
            return True
        now = time.monotonic()
        tokens, last = self._state.get(key, (float(self.burst), now))
        tokens = min(self.burst, tokens + (now - last) * self.rps)
        if tokens < 1.0:
            self._state[key] = (tokens, now)
            return False
        self._state[key] = (tokens - 1.0, now)
        return True
