/* Cordum-TPU operations dashboard: dependency-free SPA over /api/v1.
 *
 * Pages (functional subset of the reference dashboard's 18): overview, jobs,
 * approvals, workflows, runs, dlq, workers, policy, packs, config, settings.
 * Live updates ride the /api/v1/stream WebSocket (API key via
 * Sec-WebSocket-Protocol, as in the reference gateway).
 */
"use strict";

const $ = (sel, el = document) => el.querySelector(sel);
const main = () => $("#page");

// ---------------------------------------------------------------- api
function apiKey() { return localStorage.getItem("cordum_api_key") || ""; }
function principalRole() { return localStorage.getItem("cordum_role") || ""; }

async function api(path, opts = {}) {
  const headers = { "Content-Type": "application/json", ...(opts.headers || {}) };
  if (apiKey()) headers["X-Api-Key"] = apiKey();
  if (principalRole()) headers["X-Principal-Role"] = principalRole();
  const res = await fetch(`/api/v1${path}`, { ...opts, headers });
  let body = null;
  try { body = await res.json(); } catch { /* non-JSON */ }
  if (!res.ok) throw new Error(body?.error || `${res.status} ${res.statusText}`);
  return body;
}

function toast(msg, isErr = false) {
  const box = $("#toast");
  const el = document.createElement("div");
  el.className = "msg" + (isErr ? " err" : "");
  el.textContent = msg;
  box.appendChild(el);
  setTimeout(() => el.remove(), 5000);
}

// ---------------------------------------------------------------- helpers
const STATE_CLASS = {
  SUCCEEDED: "good", RUNNING: "accent", DISPATCHED: "accent", SCHEDULED: "accent",
  PENDING: "warning", APPROVAL_REQUIRED: "warning", THROTTLED: "warning",
  FAILED: "critical", DENIED: "critical", TIMEOUT: "serious", CANCELLED: "serious",
  DLQ: "critical", WAITING_APPROVAL: "warning", waiting_approval: "warning", running: "accent", pending: "warning",
  succeeded: "good", failed: "critical", cancelled: "serious",
};
const badge = (state) =>
  `<span class="badge ${STATE_CLASS[state] || ""}">${esc(state ?? "—")}</span>`;
const esc = (s) => String(s ?? "").replace(/[&<>"']/g, (c) =>
  ({ "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;" }[c]));
const json = (o) => `<pre class="json">${esc(JSON.stringify(o, null, 2))}</pre>`;
const ts = (us) => us ? new Date(us / 1000).toLocaleTimeString() : "—";

function table(headers, rows, onRow) {
  const id = "t" + Math.random().toString(36).slice(2, 8);
  const html = `<table id="${id}"><thead><tr>${headers.map((h) => `<th>${h}</th>`).join("")}</tr></thead>
    <tbody>${rows.map((r, i) => `<tr data-i="${i}">${r.cells.map((c) => `<td>${c}</td>`).join("")}</tr>`).join("") ||
    `<tr class="noclick"><td colspan="${headers.length}" class="muted">none</td></tr>`}</tbody></table>`;
  queueMicrotask(() => {
    if (onRow) $(`#${id}`)?.querySelectorAll("tbody tr[data-i]").forEach((tr) =>
      tr.addEventListener("click", (ev) => {
        if (ev.target.closest("button")) return; // row buttons win
        onRow(rows[+tr.dataset.i], ev);
      }));
  });
  return html;
}

function bind(sel, event, fn) { queueMicrotask(() => $(sel)?.addEventListener(event, fn)); }

// ---------------------------------------------------------------- live stream
let ws = null;
const feed = [];
let feedListeners = [];

function connectWS() {
  try { ws?.close(); } catch { /* noop */ }
  const proto = location.protocol === "https:" ? "wss" : "ws";
  const protocols = apiKey() ? [apiKey()] : undefined;
  ws = new WebSocket(`${proto}://${location.host}/api/v1/stream`, protocols);
  ws.onopen = () => setConn(true);
  ws.onclose = () => { setConn(false); setTimeout(connectWS, 3000); };
  ws.onmessage = (ev) => {
    let doc; try { doc = JSON.parse(ev.data); } catch { return; }
    const flat = { subject: doc.subject, ...(doc.packet || doc) };
    feed.unshift({ at: new Date().toLocaleTimeString(), ...flat });
    if (feed.length > 200) feed.pop();
    feedListeners.forEach((fn) => fn({ subject: doc.subject, ...(doc.packet || doc) }));
  };
}
function setConn(up) {
  const el = $("#conn");
  if (el) el.innerHTML = up
    ? `<span class="badge good">stream live</span>`
    : `<span class="badge serious">stream down</span>`;
}

// ---------------------------------------------------------------- pages
const pages = {};

pages.overview = async () => {
  const [status, workers, jobs, dlq] = await Promise.all([
    api("/status"), api("/workers"), api("/jobs?limit=12"), api("/dlq?limit=1000"),
  ]);
  const nWorkers = workers.count ?? Object.keys(workers.workers || {}).length;
  main().innerHTML = `
    <h1>Overview</h1>
    <div class="tiles">
      <div class="tile"><div class="label">Bus</div>
        <div class="value">${status.bus ? "up" : "DOWN"}</div>
        <div class="sub">${badge(status.bus ? "SUCCEEDED" : "FAILED")}</div></div>
      <div class="tile"><div class="label">State store</div>
        <div class="value">${status.kv ? "up" : "DOWN"}</div>
        <div class="sub">${badge(status.kv ? "SUCCEEDED" : "FAILED")}</div></div>
      <div class="tile"><div class="label">Workers</div><div class="value">${nWorkers}</div>
        <div class="sub">heartbeating</div></div>
      <div class="tile"><div class="label">DLQ depth</div>
        <div class="value">${(dlq.entries || []).length}</div>
        <div class="sub">dead-lettered jobs</div></div>
      <div class="tile"><div class="label">Policy snapshot</div>
        <div class="value mono" style="font-size:14px">${esc(status.policy_snapshot || "—")}</div>
        <div class="sub">safety kernel</div></div>
    </div>
    <h2>Recent jobs</h2>
    ${table(["Job", "Topic", "Tenant", "State"],
      (jobs.jobs || []).map((j) => ({
        id: j.job_id,
        cells: [`<span class="mono">${esc(j.job_id)}</span>`, esc(j.topic), esc(j.tenant_id), badge(j.state)],
      })), (r) => { location.hash = `#/jobs/${r.id}`; })}
    <h2>Live events <span class="muted small">(sys.job.> via WebSocket)</span></h2>
    <div class="card feed" id="feed">${feed.map((f) =>
      `<div><span class="t">${f.at}</span>${esc(f.kind || "?")} ${esc(f.payload?.job_id || "")} ${esc(f.payload?.status || "")}</div>`).join("") || '<div class="muted">waiting for events…</div>'}</div>`;
  feedListeners = [(doc) => {
    const el = $("#feed");
    if (!el) return;
    const d = document.createElement("div");
    d.innerHTML = `<span class="t">${new Date().toLocaleTimeString()}</span>${esc(doc.kind || "?")} ${esc(doc.payload?.job_id || "")} ${esc(doc.payload?.status || "")}`;
    el.prepend(d);
    while (el.children.length > 200) el.lastChild.remove();
  }];
};

pages.jobs = async (jobId) => {
  if (jobId) return jobDetail(jobId);
  const state = sessionStorage.getItem("jobs_state") || "";
  const data = await api(`/jobs?limit=100${state ? `&state=${state}` : ""}`);
  main().innerHTML = `
    <h1>Jobs</h1>
    <div class="row" style="margin-bottom:10px">
      <label>state <select id="stateSel">
        ${["", "PENDING", "SCHEDULED", "DISPATCHED", "RUNNING", "SUCCEEDED", "FAILED",
           "DENIED", "TIMEOUT", "CANCELLED", "APPROVAL_REQUIRED", "DLQ"]
          .map((s) => `<option value="${s}" ${s === state ? "selected" : ""}>${s || "recent"}</option>`).join("")}
      </select></label>
      <span class="grow"></span>
      <button id="submitBtn" class="primary">Submit job…</button>
    </div>
    <div id="submitForm" class="card" style="display:none">
      <div class="row"><label>topic <input id="sTopic" value="job.default" size="24"></label></div>
      <label>payload (JSON)</label><textarea id="sPayload">{"hello": "world"}</textarea>
      <div class="row" style="margin-top:8px"><button id="sGo" class="primary">Submit</button></div>
    </div>
    ${table(["Job", "Topic", "Tenant", "State"],
      (data.jobs || []).map((j) => ({
        id: j.job_id,
        cells: [`<span class="mono">${esc(j.job_id)}</span>`, esc(j.topic), esc(j.tenant_id), badge(j.state)],
      })), (r) => { location.hash = `#/jobs/${r.id}`; })}`;
  bind("#stateSel", "change", (e) => { sessionStorage.setItem("jobs_state", e.target.value); render(); });
  bind("#submitBtn", "click", () => { const f = $("#submitForm"); f.style.display = f.style.display === "none" ? "" : "none"; });
  bind("#sGo", "click", async () => {
    try {
      const payload = JSON.parse($("#sPayload").value || "{}");
      const out = await api("/jobs", { method: "POST", body: JSON.stringify({ topic: $("#sTopic").value, payload }) });
      toast(`submitted ${out.job_id}`);
      location.hash = `#/jobs/${out.job_id}`;
    } catch (e) { toast(e.message, true); }
  });
};

async function jobDetail(jobId) {
  const j = await api(`/jobs/${jobId}?events=true&result=true`);
  const terminal = ["SUCCEEDED", "FAILED", "DENIED", "TIMEOUT", "CANCELLED", "DLQ"].includes(j.state);
  main().innerHTML = `
    <h1 class="row">Job <span class="mono">${esc(jobId)}</span> ${badge(j.state)}
      <span class="grow"></span>
      ${terminal ? "" : `<button id="cancelBtn" class="danger">Cancel</button>`}
    </h1>
    <div class="card"><dl class="kv">
      ${["topic", "tenant_id", "principal_id", "worker_id", "dispatch_subject", "attempts",
         "trace_id", "workflow_id", "run_id", "deny_reason", "approval_reason", "error_message", "error_code"]
        .filter((k) => j[k]).map((k) => `<dt>${k}</dt><dd class="mono">${esc(j[k])}</dd>`).join("")}
    </dl></div>
    ${j.result !== undefined ? `<h2>Result</h2>${json(j.result)}` : ""}
    <h2>Events</h2>
    ${table(["At", "Event", "Detail"], (j.events || []).map((e) => ({
      cells: [ts(e.ts_us ?? e.at_us), esc(e.event),
        `<span class="mono small">${esc(JSON.stringify(Object.fromEntries(Object.entries(e).filter(([k]) => !["event", "ts_us", "at_us"].includes(k)))))}</span>`],
    })))}
    ${j.trace_id ? `<p><a href="#/traces/${esc(j.trace_id)}" class="muted small">trace ${esc(j.trace_id)}</a></p>` : ""}`;
  bind("#cancelBtn", "click", async () => {
    try { await api(`/jobs/${jobId}/cancel`, { method: "POST" }); toast("cancel requested"); render(); }
    catch (e) { toast(e.message, true); }
  });
}

pages.traces = async (traceId) => {
  const t = await api(`/traces/${traceId}`);
  main().innerHTML = `<h1>Trace <span class="mono">${esc(traceId)}</span></h1>
    ${table(["Job", "Topic", "State"], (t.jobs || []).map((j) => ({
      id: j.job_id, cells: [`<span class="mono">${esc(j.job_id)}</span>`, esc(j.topic), badge(j.state)],
    })), (r) => { location.hash = `#/jobs/${r.id}`; })}`;
};

pages.approvals = async () => {
  const data = await api("/approvals");
  main().innerHTML = `
    <h1>Approvals</h1>
    <p class="muted small">Jobs parked by the safety kernel awaiting a human decision.
    Approve re-checks against the current policy and binds to the stored job hash.</p>
    ${table(["Job", "Topic", "Tenant", "Reason", "Snapshot", ""],
      (data.approvals || []).map((a) => ({
        id: a.job_id,
        cells: [`<span class="mono">${esc(a.job_id)}</span>`, esc(a.topic), esc(a.tenant_id),
          esc(a.reason), `<span class="mono small">${esc(a.policy_snapshot)}</span>`,
          `<button data-act="approve" data-id="${esc(a.job_id)}" class="primary">Approve</button>
           <button data-act="reject" data-id="${esc(a.job_id)}" class="danger">Reject</button>`],
      })), (r) => { location.hash = `#/jobs/${r.id}`; })}`;
  queueMicrotask(() => main().querySelectorAll("button[data-act]").forEach((b) =>
    b.addEventListener("click", async (ev) => {
      ev.stopPropagation();
      try {
        await api(`/approvals/${b.dataset.id}/${b.dataset.act}`, { method: "POST" });
        toast(`${b.dataset.act}ed ${b.dataset.id}`); render();
      } catch (e) { toast(e.message, true); }
    })));
};

pages.workflows = async (wfId) => {
  if (wfId) return workflowDetail(wfId);
  const data = await api("/workflows");
  main().innerHTML = `
    <h1>Workflows</h1>
    ${table(["Workflow", "Steps", "Description"], (data.workflows || []).map((w) => ({
      id: w.id ?? w,
      cells: [`<span class="mono">${esc(w.id ?? w)}</span>`, esc(w.steps ?? ""), esc(w.description ?? "")],
    })), (r) => { location.hash = `#/workflows/${r.id}`; })}`;
};

async function workflowDetail(wfId) {
  const wf = await api(`/workflows/${wfId}`);
  main().innerHTML = `
    <h1 class="row">Workflow <span class="mono">${esc(wfId)}</span><span class="grow"></span>
      <button id="runBtn" class="primary">Start run…</button></h1>
    <div id="runForm" class="card" style="display:none">
      <label>input (JSON)</label><textarea id="runInput">{}</textarea>
      <div class="row" style="margin-top:8px"><button id="runGo" class="primary">Start</button></div>
    </div>
    <h2>Steps</h2>
    ${table(["Step", "Type", "Topic", "Depends on", "Condition"],
      Object.entries(wf.steps || {}).map(([sid, s]) => ({
        cells: [`<span class="mono">${esc(sid)}</span>`, esc(s.type || "worker"), esc(s.topic || ""),
          esc((s.depends_on || []).join(", ")), `<span class="mono small">${esc(s.condition || "")}</span>`],
      })))}
    <h2>Definition</h2>${json(wf)}
    <h2>Runs</h2><div id="wfRuns" class="muted">loading…</div>`;
  bind("#runBtn", "click", () => { const f = $("#runForm"); f.style.display = f.style.display === "none" ? "" : "none"; });
  bind("#runGo", "click", async () => {
    try {
      const input = JSON.parse($("#runInput").value || "{}");
      const out = await api(`/workflows/${wfId}/runs`, { method: "POST", body: JSON.stringify({ input }) });
      toast(`run ${out.run_id} started`);
      location.hash = `#/runs/${out.run_id}`;
    } catch (e) { toast(e.message, true); }
  });
  const runs = await api(`/runs?workflow_id=${encodeURIComponent(wfId)}`);
  const ids = runs.runs || [];
  $("#wfRuns").innerHTML = table(["Run"], ids.map((r) => ({
    id: r, cells: [`<span class="mono">${esc(r)}</span>`],
  })), (r) => { location.hash = `#/runs/${r.id}`; });
}

pages.runs = async (runId) => {
  if (runId) return runDetail(runId);
  const data = await api("/runs");
  const ids = (data.runs || []).slice(0, 100);
  const rows = [];
  for (const rid of ids) {
    try {
      const r = await api(`/runs/${rid}`);
      rows.push({ id: rid, cells: [`<span class="mono">${esc(rid)}</span>`, esc(r.workflow_id), badge(r.status), esc(Object.keys(r.steps || {}).length)] });
    } catch { rows.push({ id: rid, cells: [`<span class="mono">${esc(rid)}</span>`, "?", "?", "?"] }); }
  }
  main().innerHTML = `<h1>Runs</h1>
    ${table(["Run", "Workflow", "Status", "Steps"], rows, (r) => { location.hash = `#/runs/${r.id}`; })}`;
};

async function runDetail(runId) {
  const [run, tl] = await Promise.all([
    api(`/runs/${runId}`), api(`/runs/${runId}/timeline`).catch(() => ({ timeline: [] })),
  ]);
  const active = ["pending", "running", "waiting_approval", "PENDING", "RUNNING", "WAITING_APPROVAL"].includes(run.status);
  main().innerHTML = `
    <h1 class="row">Run <span class="mono">${esc(runId)}</span> ${badge(run.status)}
      <span class="grow"></span>
      ${active ? `<button id="cancelRun" class="danger">Cancel</button>` : `<button id="rerun">Rerun</button>`}
    </h1>
    <div class="card"><dl class="kv">
      <dt>workflow</dt><dd class="mono">${esc(run.workflow_id)}</dd>
      <dt>org</dt><dd>${esc(run.org_id || "—")}</dd>
    </dl></div>
    <h2>Steps</h2>
    ${table(["Step", "Status", "Attempt", "Job", "Children", ""],
      Object.entries(run.steps || {}).map(([sid, s]) => ({
        cells: [`<span class="mono">${esc(sid)}</span>`, badge(s.status), esc(s.attempts ?? s.attempt ?? 0),
          s.job_id ? `<a href="#/jobs/${esc(s.job_id)}" class="mono small">${esc(s.job_id)}</a>` : "—",
          esc((s.children || []).length || ""),
          ["waiting_approval", "WAITING_APPROVAL"].includes(s.status)
            ? `<button data-step="${esc(sid)}" class="primary">Approve step</button>` : ""],
      })))}
    <h2>Timeline</h2>
    ${table(["At", "Event", "Step", "Detail"], (tl.timeline || []).map((e) => ({
      cells: [ts(e.ts_us ?? e.at_us), esc(e.event), `<span class="mono">${esc(e.step_id || "")}</span>`,
        `<span class="small muted">${esc(e.detail || e.reason || "")}</span>`],
    })))}
    <h2>Context</h2>${json(run.ctx || run.context || {})}`;
  bind("#cancelRun", "click", async () => {
    try { await api(`/runs/${runId}/cancel`, { method: "POST" }); toast("cancelled"); render(); }
    catch (e) { toast(e.message, true); }
  });
  bind("#rerun", "click", async () => {
    try { const out = await api(`/runs/${runId}/rerun`, { method: "POST", body: "{}" }); toast(`rerun ${out.run_id}`); location.hash = `#/runs/${out.run_id}`; }
    catch (e) { toast(e.message, true); }
  });
  queueMicrotask(() => main().querySelectorAll("button[data-step]").forEach((b) =>
    b.addEventListener("click", async () => {
      try { await api(`/runs/${runId}/steps/${b.dataset.step}/approve`, { method: "POST" }); toast("step approved"); render(); }
      catch (e) { toast(e.message, true); }
    })));
}

pages.dlq = async () => {
  const data = await api("/dlq?limit=200");
  main().innerHTML = `
    <h1>Dead-letter queue</h1>
    ${table(["Job", "Topic", "Reason", "Code", "Last state", "Attempts", ""],
      (data.entries || []).map((e) => ({
        id: e.job_id,
        cells: [`<span class="mono">${esc(e.job_id)}</span>`, esc(e.topic), esc(e.reason),
          `<span class="mono small">${esc(e.reason_code)}</span>`, badge(e.last_state || e.status),
          esc(e.attempts ?? ""),
          `<button data-act="retry" data-id="${esc(e.job_id)}" class="primary">Retry</button>
           <button data-act="delete" data-id="${esc(e.job_id)}" class="danger">Delete</button>`],
      })), (r) => { location.hash = `#/jobs/${r.id}`; })}`;
  queueMicrotask(() => main().querySelectorAll("button[data-act]").forEach((b) =>
    b.addEventListener("click", async (ev) => {
      ev.stopPropagation();
      try {
        if (b.dataset.act === "retry") {
          const out = await api(`/dlq/${b.dataset.id}/retry`, { method: "POST" });
          toast(`retried as ${out.job_id || "new job"}`);
        } else {
          await api(`/dlq/${b.dataset.id}`, { method: "DELETE" });
          toast("deleted");
        }
        render();
      } catch (e) { toast(e.message, true); }
    })));
};

pages.workers = async () => {
  const data = await api("/workers");
  const workers = Object.values(data.workers || {});
  main().innerHTML = `
    <h1>Workers <span class="muted small">${workers.length} heartbeating</span></h1>
    <div class="workers">
      ${workers.map((w) => {
        const duty = Math.round(w.tpu_duty_cycle ?? w.gpu_utilization ?? 0);
        const hbmPct = w.hbm_total_gb ? Math.round(100 * w.hbm_used_gb / w.hbm_total_gb) : null;
        return `<div class="card">
          <div class="row"><b class="mono">${esc(w.worker_id)}</b><span class="grow"></span>
            ${badge(w.devices_healthy === false ? "FAILED" : "RUNNING")}</div>
          <dl class="kv small" style="grid-template-columns:110px 1fr; margin-top:6px">
            <dt>pool</dt><dd>${esc(w.pool)}</dd>
            <dt>device</dt><dd>${esc(w.device_kind || w.type || "—")} ×${esc(w.chip_count ?? 0)}</dd>
            <dt>topology</dt><dd class="mono">${esc(w.slice_topology || "—")}</dd>
            <dt>jobs</dt><dd>${esc(w.active_jobs ?? 0)} / ${esc(w.max_parallel_jobs ?? "∞")}</dd>
            <dt>capabilities</dt><dd>${esc((w.capabilities || []).join(", ") || "—")}</dd>
          </dl>
          <div class="small muted">TPU duty ${duty}%</div>
          <div class="meter ${duty > 85 ? "hot" : ""}"><div style="width:${duty}%"></div></div>
          ${hbmPct !== null ? `<div class="small muted" style="margin-top:6px">HBM ${esc(w.hbm_used_gb?.toFixed?.(1) ?? w.hbm_used_gb)} / ${esc(w.hbm_total_gb)} GB</div>
          <div class="meter ${hbmPct > 85 ? "hot" : ""}"><div style="width:${hbmPct}%"></div></div>` : ""}
        </div>`;
      }).join("") || '<p class="muted">no workers heartbeating</p>'}
    </div>`;
};

pages.policy = async (sub) => {
  const tab = sub || "bundles";
  const tabs = ["bundles", "snapshots", "simulate", "audit"];
  const head = `<h1>Safety policy</h1>
    <div class="tabs">${tabs.map((t) =>
      `<button class="${t === tab ? "active" : ""}" onclick="location.hash='#/policy/${t}'">${t}</button>`).join("")}</div>`;
  if (tab === "bundles") {
    const data = await api("/policy/bundles");
    main().innerHTML = head + table(["Bundle", "Enabled", "Rules"],
      (data.bundles || []).map((b) => ({
        id: b.id ?? b,
        cells: [`<span class="mono">${esc(b.id ?? b)}</span>`, esc(String(b.enabled ?? "")), esc(b.rules ?? "")],
      })), async (r) => {
        const doc = await api(`/policy/bundles/${encodeURIComponent(r.id)}`);
        $("#bundleView").innerHTML = `<h2>${esc(r.id)}</h2>${json(doc)}`;
      }) + `<div id="bundleView"></div>`;
  } else if (tab === "snapshots") {
    const [snaps, captured] = await Promise.all([
      api("/policy/snapshots"), api("/policy/snapshots/captured").catch(() => ({ snapshots: [] })),
    ]);
    main().innerHTML = head +
      `<h2>Kernel snapshots (last 10)</h2>` +
      table(["Snapshot", "Created"], (snaps.snapshots || []).map((s) => ({
        cells: [`<span class="mono">${esc(s.snapshot_id)}</span>`, esc(new Date((s.created_at || 0) * 1000).toLocaleString())],
      }))) +
      `<h2 class="row">Captured <span class="grow"></span><button id="capBtn">Capture now</button></h2>` +
      table(["Snapshot", "Created", ""], (captured.snapshots || []).map((s) => ({
        cells: [`<span class="mono">${esc(s.snapshot_id || s.id)}</span>`,
          esc(new Date((s.created_at || 0) * 1000).toLocaleString()),
          `<button data-roll="${esc(s.snapshot_id || s.id)}" class="danger">Rollback</button>`],
      })));
    bind("#capBtn", "click", async () => {
      try { await api("/policy/snapshots/capture", { method: "POST" }); toast("captured"); render(); }
      catch (e) { toast(e.message, true); }
    });
    queueMicrotask(() => main().querySelectorAll("button[data-roll]").forEach((b) =>
      b.addEventListener("click", async () => {
        try { await api(`/policy/snapshots/${b.dataset.roll}/rollback`, { method: "POST" }); toast("rolled back"); render(); }
        catch (e) { toast(e.message, true); }
      })));
  } else if (tab === "simulate") {
    main().innerHTML = head + `
      <div class="card">
        <p class="muted small">Evaluate a hypothetical request against the live policy (no side effects).</p>
        <div class="row">
          <label>topic <input id="simTopic" value="job.tpu.train"></label>
          <label>tenant <input id="simTenant" value="default" size="10"></label>
          <label>capability <input id="simCap" value="tpu" size="8"></label>
          <label>risk tags <input id="simRisk" value="" size="12"></label>
        </div>
        <div class="row" style="margin-top:8px"><button id="simGo" class="primary">Simulate</button></div>
        <div id="simOut"></div>
      </div>`;
    bind("#simGo", "click", async () => {
      try {
        const out = await api("/policy/simulate", {
          method: "POST",
          body: JSON.stringify({
            topic: $("#simTopic").value, tenant_id: $("#simTenant").value,
            metadata: {
              capability: $("#simCap").value,
              risk_tags: $("#simRisk").value.split(",").map((s) => s.trim()).filter(Boolean),
            },
          }),
        });
        $("#simOut").innerHTML = `<p>${badge(out.decision)} <span class="muted">${esc(out.reason || "")}</span></p>${json(out)}`;
      } catch (e) { toast(e.message, true); }
    });
  } else if (tab === "audit") {
    const data = await api("/policy/audit");
    main().innerHTML = head + table(["At", "Action", "Actor", "Detail"],
      (data.audit || data.entries || []).map((a) => ({
        cells: [esc(new Date((a.at || a.created_at || 0) * 1000).toLocaleString()), esc(a.action),
          esc(a.actor || a.by || ""), `<span class="mono small">${esc(JSON.stringify(a.detail || a.target || ""))}</span>`],
      })));
  }
};

pages.packs = async () => {
  const [packs, catalogs] = await Promise.all([
    api("/packs"), api("/pack-catalogs").catch(() => ({ catalogs: {} })),
  ]);
  const names = Array.isArray(packs.packs) ? packs.packs : Object.keys(packs.packs || {});
  main().innerHTML = `
    <h1>Packs</h1>
    <h2>Installed</h2>
    ${table(["Pack", ""], names.map((p) => ({
      id: p,
      cells: [`<span class="mono">${esc(p)}</span>`,
        `<button data-un="${esc(p)}" class="danger">Uninstall</button>`],
    })), async (r) => {
      const doc = await api(`/packs/${r.id}`);
      $("#packView").innerHTML = `<h2>${esc(r.id)}</h2>${json(doc)}`;
    })}
    <div id="packView"></div>
    <h2>Catalogs</h2>
    ${table(["Catalog", "Path", ""], Object.entries(catalogs.catalogs || {}).map(([name, c]) => ({
      cells: [`<span class="mono">${esc(name)}</span>`, `<span class="mono small">${esc(c.path)}</span>`,
        `<button data-cat="${esc(name)}">Browse</button>`],
    })))}
    <div id="catView"></div>`;
  queueMicrotask(() => {
    main().querySelectorAll("button[data-un]").forEach((b) =>
      b.addEventListener("click", async (ev) => {
        ev.stopPropagation();
        try { await api(`/packs/${b.dataset.un}`, { method: "DELETE" }); toast("uninstalled"); render(); }
        catch (e) { toast(e.message, true); }
      }));
    main().querySelectorAll("button[data-cat]").forEach((b) =>
      b.addEventListener("click", async () => {
        try {
          const data = await api(`/pack-catalogs/${b.dataset.cat}/packs`);
          $("#catView").innerHTML = `<h2>${esc(b.dataset.cat)}</h2>` +
            table(["Pack", "Version", ""], (data.packs || []).map((p) => ({
              cells: [`<span class="mono">${esc(p.id)}</span>`, esc(p.version || ""),
                `<button data-inst="${esc(p.id)}" data-from="${esc(b.dataset.cat)}" class="primary">Install</button>`],
            })));
          main().querySelectorAll("button[data-inst]").forEach((ib) =>
            ib.addEventListener("click", async () => {
              try {
                await api(`/pack-catalogs/${ib.dataset.from}/install/${ib.dataset.inst}`, { method: "POST" });
                toast(`installed ${ib.dataset.inst}`); render();
              } catch (e) { toast(e.message, true); }
            }));
        } catch (e) { toast(e.message, true); }
      }));
  });
};

pages.config = async () => {
  const eff = await api("/config/effective").catch((e) => ({ error: e.message }));
  main().innerHTML = `
    <h1>Config</h1>
    <h2>Effective (system scope, shallow-merged)</h2>${json(eff)}
    <div class="card">
      <h2 style="margin-top:0">Read / write a scoped document</h2>
      <div class="row">
        <label>scope <select id="cfgScope">${["system", "org", "team", "workflow", "step"]
          .map((s) => `<option>${s}</option>`).join("")}</select></label>
        <label>doc id <input id="cfgId" value="default"></label>
        <button id="cfgGet">Load</button>
        <button id="cfgPut" class="primary">Save</button>
      </div>
      <textarea id="cfgDoc" style="margin-top:8px">{}</textarea>
    </div>`;
  bind("#cfgGet", "click", async () => {
    try {
      const doc = await api(`/config/${$("#cfgScope").value}/${$("#cfgId").value}`);
      $("#cfgDoc").value = JSON.stringify(doc.data ?? doc, null, 2);
    } catch (e) { toast(e.message, true); }
  });
  bind("#cfgPut", "click", async () => {
    try {
      const data = JSON.parse($("#cfgDoc").value);
      await api(`/config/${$("#cfgScope").value}/${$("#cfgId").value}`,
        { method: "PUT", body: JSON.stringify({ data }) });
      toast("saved");
    } catch (e) { toast(e.message, true); }
  });
};

pages.settings = async () => {
  main().innerHTML = `
    <h1>Settings</h1>
    <div class="card">
      <div class="row"><label>API key <input id="setKey" type="password" value="${esc(apiKey())}" size="30"></label></div>
      <div class="row" style="margin-top:8px"><label>role header (X-Principal-Role)
        <select id="setRole"><option value="">(none)</option><option ${principalRole() === "admin" ? "selected" : ""}>admin</option></select></label></div>
      <div class="row" style="margin-top:10px"><button id="setSave" class="primary">Save</button></div>
      <p class="muted small">Stored in this browser only. The stream reconnects with the new key.</p>
    </div>
    <div class="card">
      <div class="row"><label>theme
        <select id="setTheme">${["auto", "light", "dark"].map((t) =>
          `<option ${((localStorage.getItem("cordum_theme") || "auto") === t) ? "selected" : ""}>${t}</option>`).join("")}</select></label></div>
    </div>`;
  bind("#setSave", "click", () => {
    localStorage.setItem("cordum_api_key", $("#setKey").value.trim());
    localStorage.setItem("cordum_role", $("#setRole").value);
    toast("saved"); connectWS(); render();
  });
  bind("#setTheme", "change", (e) => {
    localStorage.setItem("cordum_theme", e.target.value);
    applyTheme();
  });
};

function applyTheme() {
  const t = localStorage.getItem("cordum_theme") || "auto";
  if (t === "auto") delete document.documentElement.dataset.theme;
  else document.documentElement.dataset.theme = t;
}

// ---------------------------------------------------------------- router
const NAV = [
  ["overview", "Overview"], ["jobs", "Jobs"], ["approvals", "Approvals"],
  ["workflows", "Workflows"], ["runs", "Runs"], ["dlq", "DLQ"],
  ["workers", "Workers"], ["policy", "Policy"], ["packs", "Packs"],
  ["config", "Config"], ["settings", "Settings"],
];

async function render() {
  const [page, arg] = location.hash.replace(/^#\//, "").split("/", 2);
  const name = pages[page] ? page : "overview";
  document.querySelectorAll("nav a").forEach((a) =>
    a.classList.toggle("active", a.dataset.page === name));
  feedListeners = [];
  try {
    await pages[name](arg ? decodeURIComponent(arg) : undefined);
  } catch (e) {
    main().innerHTML = `<h1>${esc(name)}</h1><div class="card">
      <p>${badge("FAILED")} ${esc(e.message)}</p>
      <p class="muted small">Check the API key under Settings.</p></div>`;
  }
}

function boot() {
  $("#nav-links").innerHTML = NAV.map(([p, label]) =>
    `<a href="#/${p}" data-page="${p}">${label}</a>`).join("");
  applyTheme();
  window.addEventListener("hashchange", render);
  connectWS();
  render();
}
boot();
