"""Policy bundle administration: versioned bundles, snapshots, publish /
rollback with an audit trail.

Recreates reference ``core/controlplane/gateway/policy_bundles.go``
(:122-651 bundles, :671-931 snapshots, :1432-1465 audit):

  * bundles are named policy documents stored under ``cfg:system:policy/``
    (the same fragment namespace the kernel merges) — putting a bundle is a
    staged write: it lands DISABLED until published
  * snapshots capture the full merged policy doc at a point in time
    (``kernel.get_snapshot``); ``publish`` enables a bundle and records the
    resulting kernel snapshot; ``rollback`` re-installs a captured
    snapshot's fragment set
  * every admin mutation appends to the audit log ``policy:audit``
  * bundle ids may contain ``/`` (URL-escaped as ``~`` in routes,
    reference behavior)
"""
from __future__ import annotations

import json
from typing import Any, Optional

from ...infra.configsvc import ConfigService
from ...infra.kv import KV
from ...utils.ids import now_us
from .kernel import POLICY_FRAGMENT_PREFIX, SafetyKernel

AUDIT_KEY = "policy:audit"
AUDIT_CAP = 500
SNAPSHOT_PREFIX = "policy:snapshot:"


def unescape_bundle_id(raw: str) -> str:
    return raw.replace("~", "/")


class PolicyBundleAdmin:
    def __init__(self, kv: KV, configsvc: ConfigService, kernel: SafetyKernel):
        self.kv = kv
        self.configsvc = configsvc
        self.kernel = kernel

    # -- audit ----------------------------------------------------------
    async def _audit(self, action: str, actor: str, detail: str = "") -> None:
        ev = {"ts_us": now_us(), "action": action, "actor": actor, "detail": detail}
        await self.kv.rpush(AUDIT_KEY, json.dumps(ev).encode())
        await self.kv.ltrim(AUDIT_KEY, -AUDIT_CAP, -1)

    async def audit_log(self) -> list[dict]:
        return [json.loads(b) for b in await self.kv.lrange(AUDIT_KEY)]

    # -- bundles --------------------------------------------------------
    def _frag_id(self, bundle_id: str) -> str:
        return f"{POLICY_FRAGMENT_PREFIX}/{bundle_id}"

    async def list_bundles(self) -> list[dict]:
        out = []
        for frag_id in sorted(await self.configsvc.list("system")):
            if not frag_id.startswith(POLICY_FRAGMENT_PREFIX + "/"):
                continue
            doc = await self.configsvc.get("system", frag_id)
            if doc is None:
                continue
            out.append({
                "bundle_id": frag_id[len(POLICY_FRAGMENT_PREFIX) + 1:],
                "enabled": bool(doc.data.get("enabled", True)),
                "revision": doc.revision,
                "rules": len(doc.data.get("rules") or []),
            })
        return out

    async def get_bundle(self, bundle_id: str) -> Optional[dict]:
        doc = await self.configsvc.get("system", self._frag_id(bundle_id))
        if doc is None:
            return None
        return {"bundle_id": bundle_id, "revision": doc.revision, "data": doc.data}

    async def put_bundle(self, bundle_id: str, data: dict, *, actor: str) -> dict:
        """Staged write: new bundles land disabled until published."""
        data = dict(data)
        data.setdefault("enabled", False)
        doc = await self.configsvc.set("system", self._frag_id(bundle_id), data)
        await self._audit("put_bundle", actor, f"{bundle_id} rev {doc.revision}")
        await self.kernel.reload()
        return {"bundle_id": bundle_id, "revision": doc.revision, "enabled": data["enabled"]}

    async def delete_bundle(self, bundle_id: str, *, actor: str) -> bool:
        ok = await self.configsvc.delete("system", self._frag_id(bundle_id))
        if ok:
            await self._audit("delete_bundle", actor, bundle_id)
            await self.kernel.reload()
        return ok

    async def publish(self, bundle_id: str, *, actor: str) -> dict:
        doc = await self.configsvc.get("system", self._frag_id(bundle_id))
        if doc is None:
            raise KeyError(f"unknown bundle {bundle_id!r}")
        data = dict(doc.data)
        data["enabled"] = True
        await self.configsvc.set("system", self._frag_id(bundle_id), data)
        snap = await self.kernel.reload()
        await self._audit("publish", actor, f"{bundle_id} → snapshot {snap}")
        return {"bundle_id": bundle_id, "enabled": True, "policy_snapshot": snap}

    async def unpublish(self, bundle_id: str, *, actor: str) -> dict:
        doc = await self.configsvc.get("system", self._frag_id(bundle_id))
        if doc is None:
            raise KeyError(f"unknown bundle {bundle_id!r}")
        data = dict(doc.data)
        data["enabled"] = False
        await self.configsvc.set("system", self._frag_id(bundle_id), data)
        snap = await self.kernel.reload()
        await self._audit("unpublish", actor, f"{bundle_id} → snapshot {snap}")
        return {"bundle_id": bundle_id, "enabled": False, "policy_snapshot": snap}

    # -- draft simulation ------------------------------------------------
    async def simulate_draft(self, bundle_data: dict, requests: list) -> list[dict]:
        """Evaluate requests against current policy + draft bundle rules."""
        merged = dict(self.kernel._merged_doc)
        merged = json.loads(json.dumps(merged))  # deep copy
        merged.setdefault("rules", [])
        merged["rules"] = list(bundle_data.get("rules") or []) + merged["rules"]
        return await self.kernel.simulate(merged, requests)

    # -- snapshots -------------------------------------------------------
    async def capture_snapshot(self, *, actor: str, note: str = "") -> dict:
        """Persist the current merged policy + fragment set for rollback."""
        snap_id = await self.kernel.reload() or self.kernel.snapshot_id
        fragments = {}
        for frag_id in await self.configsvc.list("system"):
            if frag_id.startswith(POLICY_FRAGMENT_PREFIX + "/"):
                doc = await self.configsvc.get("system", frag_id)
                if doc:
                    fragments[frag_id] = doc.data
        record = {
            "snapshot_id": snap_id,
            "captured_at_us": now_us(),
            "note": note,
            "fragments": fragments,
            "merged": self.kernel.get_snapshot(snap_id) or {},
        }
        await self.kv.set(SNAPSHOT_PREFIX + snap_id, json.dumps(record).encode())
        await self.kv.zadd("policy:snapshot:index", snap_id, float(record["captured_at_us"]))
        await self._audit("capture_snapshot", actor, snap_id)
        return {"snapshot_id": snap_id, "fragments": len(fragments)}

    async def list_captured(self) -> list[dict]:
        out = []
        for snap_id in await self.kv.zrange("policy:snapshot:index", desc=True):
            b = await self.kv.get(SNAPSHOT_PREFIX + snap_id)
            if b:
                rec = json.loads(b)
                out.append({"snapshot_id": snap_id, "captured_at_us": rec["captured_at_us"],
                            "note": rec.get("note", ""), "fragments": len(rec.get("fragments", {}))})
        return out

    async def get_captured(self, snapshot_id: str) -> Optional[dict]:
        b = await self.kv.get(SNAPSHOT_PREFIX + snapshot_id)
        return json.loads(b) if b else None

    async def rollback(self, snapshot_id: str, *, actor: str) -> dict:
        """Restore the captured fragment set (removing fragments added since)."""
        rec = await self.get_captured(snapshot_id)
        if rec is None:
            raise KeyError(f"unknown snapshot {snapshot_id!r}")
        captured = rec.get("fragments", {})
        current = [
            f for f in await self.configsvc.list("system")
            if f.startswith(POLICY_FRAGMENT_PREFIX + "/")
        ]
        for frag_id in current:
            if frag_id not in captured:
                await self.configsvc.delete("system", frag_id)
        for frag_id, data in captured.items():
            await self.configsvc.set("system", frag_id, data)
        snap = await self.kernel.reload()
        await self._audit("rollback", actor, f"{snapshot_id} → snapshot {snap}")
        return {"rolled_back_to": snapshot_id, "policy_snapshot": snap}
