"""Safety kernel service: Check/Evaluate/Explain/Simulate/ListSnapshots.

Recreates reference ``core/controlplane/safetykernel/kernel.go`` behavior:

  * policy loaded from YAML file and/or config-service fragments stored
    under the ``cfg:system:policy`` namespace (each fragment has an
    ``enabled`` toggle; fragments append rules — kernel.go:590-655)
  * snapshot id = ``<version>:<sha256[:12]>`` of the merged policy
    (+ effective-config hash when present); last 10 snapshots retained
  * decision cache keyed by hash(request minus job_id) + snapshot with TTL
    (kernel.go:259-274)
  * hot reload: ``reload()`` recomputes the snapshot; callers poll
  * ed25519 signature verification for signed policy bundles
"""
from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from ...infra.configsvc import ConfigService
from ...protocol.types import PolicyCheckRequest, PolicyCheckResponse
from .policy import SafetyPolicy, evaluate

POLICY_FRAGMENT_PREFIX = "policy"  # cfg:system:policy/<fragment-id>
DEFAULT_CACHE_TTL_S = 5.0
MAX_SNAPSHOTS = 10


def _read_file(path: str) -> Optional[bytes]:
    """Read a policy artifact; None when absent (callers fail closed)."""
    try:
        with open(path, "rb") as f:  # cordumlint: disable=CL003 -- runs via asyncio.to_thread
            return f.read()
    except FileNotFoundError:
        return None


def _policy_hash(doc: dict) -> str:
    canonical = json.dumps(doc, sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(canonical.encode()).hexdigest()


@dataclass
class Snapshot:
    snapshot_id: str
    policy_doc: dict
    created_at: float = field(default_factory=time.time)


class SafetyKernel:
    def __init__(
        self,
        *,
        policy_doc: Optional[dict] = None,
        policy_path: str = "",
        configsvc: Optional[ConfigService] = None,
        cache_ttl_s: float = DEFAULT_CACHE_TTL_S,
        public_key_path: str = "",
        tracer: Optional[Any] = None,
    ):
        # flight-recorder tracer (obs.Tracer) for embedded deployments; when
        # the kernel runs behind KernelService the SERVICE owns the span so
        # leave this unset there (one "evaluate" span per check either way)
        self._tracer = tracer
        self._file_doc = policy_doc or {}
        self._policy_path = policy_path
        # signed bundles: when a pubkey is configured, the policy file must
        # carry a valid detached ed25519 signature at <path>.sig — fail
        # closed to the previous (or empty) policy otherwise
        import os as _os

        self._public_key_path = public_key_path or _os.environ.get("SAFETY_POLICY_PUBKEY", "")
        self._configsvc = configsvc
        self._cache_ttl_s = cache_ttl_s
        self._cache: dict[str, tuple[float, PolicyCheckResponse]] = {}
        self._version = 0
        self._policy = SafetyPolicy()
        self._snapshot_id = ""
        self._snapshots: list[Snapshot] = []
        self._merged_doc: dict = {}
        # last file-level doc that passed signature verification (signed mode):
        # reused when the file goes missing/tampered so fragments still merge
        self._last_verified_doc: Optional[dict] = None

    # ------------------------------------------------------------------
    async def reload(self) -> str:
        """Re-merge file policy + config-service fragments; returns snapshot id."""
        import copy

        # deep copy: fragment merging must never mutate the base document,
        # or disabled fragments' tenants/rules would persist across reloads
        doc = copy.deepcopy(self._file_doc)
        if self._policy_path:
            import asyncio

            raw = await asyncio.to_thread(_read_file, self._policy_path)
            if self._public_key_path:
                # Signed mode: a missing file fails closed exactly like a bad
                # signature — deleting/mis-pathing the file must not silently
                # disable enforcement. Both paths fall THROUGH to the fragment
                # merge below so configsvc policy updates keep applying.
                verified = False
                if raw is not None:
                    sig = await asyncio.to_thread(_read_file, self._policy_path + ".sig")
                    pub = await asyncio.to_thread(_read_file, self._public_key_path)
                    if sig is not None and pub is not None:
                        verified = verify_signature(raw, sig, pub)
                if verified:
                    doc = yaml.safe_load(raw) or {}
                    self._last_verified_doc = copy.deepcopy(doc)
                else:
                    import logging as _l

                    _l.getLogger("cordum").error(
                        "signed policy %s %s; fail-closed to %s",
                        self._policy_path,
                        "missing" if raw is None else "signature verification FAILED",
                        "previous verified policy" if self._last_verified_doc else "deny-all",
                    )
                    if self._last_verified_doc is not None:
                        doc = copy.deepcopy(self._last_verified_doc)
                    else:
                        # nothing verified has EVER been installed:
                        # deny-all until a signed policy arrives
                        doc = {
                            "rules": [{
                                "id": "unverified-policy-deny-all",
                                "match": {},
                                "decision": "deny",
                                "reason": "policy signature unverified (fail-closed)",
                            }]
                        }
            elif raw is not None:
                doc = yaml.safe_load(raw) or {}
        # Schema-validate the file-level policy before merging fragments: a
        # malformed safety.yaml fails startup with a pointed error; on hot
        # reload the previous good policy is kept (reference validation.go:11).
        from ...infra.configschema import SAFETY_SCHEMA, ConfigError, validate

        try:
            validate(doc, SAFETY_SCHEMA, self._policy_path or "policy_doc")
        except ConfigError as e:
            if self._merged_doc:
                import logging as _l

                _l.getLogger("cordum").error(
                    "invalid policy document on reload (%s); keeping previous", e
                )
                return self._snapshot_id
            raise
        rules = list(doc.get("rules") or [])
        if self._configsvc is not None:
            for frag_id in sorted(await self._configsvc.list("system")):
                if not frag_id.startswith(POLICY_FRAGMENT_PREFIX + "/"):
                    continue
                frag = await self._configsvc.get("system", frag_id)
                if not frag or not frag.data.get("enabled", True):
                    continue
                # fragments get the same schema treatment as the file: a
                # typo'd rule must not load silently — skip + log the
                # offending fragment, keep the rest (hot-path equivalent of
                # keep-previous-on-reload)
                frag_doc = {"rules": frag.data.get("rules") or [],
                            "tenants": frag.data.get("tenants") or {}}
                try:
                    validate(frag_doc, SAFETY_SCHEMA, f"policy fragment {frag_id}")
                except ConfigError as e:
                    import logging as _l

                    _l.getLogger("cordum").error(
                        "skipping invalid policy fragment: %s", e
                    )
                    continue
                rules.extend(frag_doc["rules"])
                for tname, tpol in frag_doc["tenants"].items():
                    doc.setdefault("tenants", {})[tname] = tpol
        doc["rules"] = rules
        h = _policy_hash(doc)
        if self._merged_doc and _policy_hash(self._merged_doc) == h:
            return self._snapshot_id
        self._version += 1
        self._merged_doc = doc
        self._policy = SafetyPolicy.from_dict(doc)
        self._snapshot_id = f"{self._version}:{h[:12]}"
        self._snapshots.append(Snapshot(self._snapshot_id, doc))
        del self._snapshots[:-MAX_SNAPSHOTS]
        self._cache.clear()
        return self._snapshot_id

    @property
    def snapshot_id(self) -> str:
        return self._snapshot_id

    def list_snapshots(self) -> list[dict]:
        return [
            {"snapshot_id": s.snapshot_id, "created_at": s.created_at}
            for s in self._snapshots
        ]

    def get_snapshot(self, snapshot_id: str) -> Optional[dict]:
        for s in self._snapshots:
            if s.snapshot_id == snapshot_id:
                return s.policy_doc
        return None

    # ------------------------------------------------------------------
    def _cache_key(self, req: PolicyCheckRequest) -> str:
        d = req.to_dict()
        d.pop("job_id", None)
        canonical = json.dumps(d, sort_keys=True, separators=(",", ":"), default=str)
        return hashlib.sha256(canonical.encode()).hexdigest() + "|" + self._snapshot_id

    async def check(self, req: PolicyCheckRequest) -> PolicyCheckResponse:
        """Evaluate with decision cache (the hot path the scheduler calls).
        Emits an ``evaluate`` span (service ``safety-kernel``) when a tracer
        is wired and an ambient trace context exists."""
        if self._tracer is None:
            return await self._check_cached(req)
        async with self._tracer.span("evaluate", attrs={"topic": req.topic}) as sp:
            resp = await self._check_cached(req)
            sp.attrs["decision"] = resp.decision
            sp.attrs["snapshot"] = self._snapshot_id
            return resp

    async def _check_cached(self, req: PolicyCheckRequest) -> PolicyCheckResponse:
        if not self._snapshot_id:
            await self.reload()
        key = self._cache_key(req)
        now = time.monotonic()
        hit = self._cache.get(key)
        if hit is not None and now - hit[0] < self._cache_ttl_s:
            return hit[1]
        resp = evaluate(self._policy, req, self._snapshot_id)
        self._apply_effective_overrides(req, resp)
        if len(self._cache) > 8192:
            self._cache = {k: v for k, v in self._cache.items() if now - v[0] < self._cache_ttl_s}
        self._cache[key] = (now, resp)
        return resp

    def _apply_effective_overrides(self, req: PolicyCheckRequest, resp: PolicyCheckResponse) -> None:
        """Effective-config safety overrides: denied/allowed topic lists in the
        job's effective config can deny an otherwise-allowed job
        (reference kernel.go:218-231)."""
        eff = req.effective_config or {}
        safety = eff.get("safety") if isinstance(eff, dict) else None
        if not isinstance(safety, dict) or resp.decision == "DENY":
            return
        from ...utils.globmatch import glob_match

        denied = safety.get("denied_topics") or []
        if any(glob_match(p, req.topic) for p in denied):
            resp.decision = "DENY"
            resp.reason = f"effective config denies topic {req.topic}"
            return
        allowed = safety.get("allowed_topics") or []
        if allowed and not any(glob_match(p, req.topic) for p in allowed):
            resp.decision = "DENY"
            resp.reason = f"topic {req.topic} not in effective-config allowlist"

    async def evaluate_raw(self, req: PolicyCheckRequest) -> PolicyCheckResponse:
        """Uncached evaluation (Evaluate/Simulate RPC equivalent)."""
        if not self._snapshot_id:
            await self.reload()
        resp = evaluate(self._policy, req, self._snapshot_id)
        self._apply_effective_overrides(req, resp)
        return resp

    async def explain(self, req: PolicyCheckRequest) -> dict[str, Any]:
        """Decision plus per-rule match trail (Explain RPC equivalent)."""
        if not self._snapshot_id:
            await self.reload()
        from .policy import _matches  # noqa: internal reuse

        tenant = req.tenant_id or self._policy.default_tenant
        trail = [
            {"rule_id": r.id, "decision": r.decision, "matched": _matches(r.match, req, tenant)}
            for r in self._policy.rules
        ]
        resp = await self.evaluate_raw(req)
        return {"decision": resp.to_dict(), "trail": trail, "snapshot": self._snapshot_id}

    async def simulate(self, policy_doc: dict, reqs: list[PolicyCheckRequest]) -> list[dict]:
        """Evaluate requests against a *draft* policy without installing it."""
        pol = SafetyPolicy.from_dict(policy_doc)
        return [evaluate(pol, r, "draft").to_dict() for r in reqs]


def verify_signature(policy_bytes: bytes, signature: bytes, public_key_bytes: bytes) -> bool:
    """Ed25519 signature check for signed policy bundles
    (reference kernel.go:832-868).  Uses the cryptography backend when
    available, else the pure-Python verifier in ``utils.ed25519`` — a
    missing crypto library must not silently disable signed-policy
    enforcement on minimal worker images.  Any verification failure
    returns False (callers fail closed)."""
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PublicKey
    except ImportError:
        from ...utils.ed25519 import verify as _pure_verify

        return _pure_verify(public_key_bytes, signature, policy_bytes)
    try:
        Ed25519PublicKey.from_public_bytes(public_key_bytes).verify(signature, policy_bytes)
        return True
    except Exception as e:  # noqa: BLE001 - bad sig/key/encoding all deny
        import logging as _l

        _l.getLogger("cordum").debug("policy signature rejected: %s", e)
        return False
