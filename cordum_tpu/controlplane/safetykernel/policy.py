"""Safety policy model + evaluator.

Recreates the reference policy semantics (``core/infra/config/safety_policy.go``):
YAML ``SafetyPolicy{rules[], tenants{}, default_tenant}``; each ``PolicyRule``
has a match block (tenants, topics as globs, capabilities, risk_tags,
requires, pack_ids, actor_ids, actor_types, labels, secrets_present, mcp),
a decision (allow / deny / require_approval / allow_with_constraints /
throttle), optional constraints and remediations.  **First match wins**,
default allow.  Legacy per-tenant allow/deny topic lists are the fallback
when no rule matches (safety_policy.go:225-257).  MCP allow/deny checked
via labels (``mcp.server`` etc., :385-416).

TPU-native extension: rule constraints may bound ``max_chips`` /
``allowed_topologies`` so policy can gate how much of a pod slice a job may
occupy (north-star: the policy gate learns TPU-slice constraints).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from ...protocol.types import (
    Constraints,
    Decision,
    PolicyCheckRequest,
    PolicyCheckResponse,
    Remediation,
)
from ...utils.globmatch import glob_match


@dataclass
class MCPPolicy:
    allow_servers: list[str] = field(default_factory=list)
    deny_servers: list[str] = field(default_factory=list)
    allow_tools: list[str] = field(default_factory=list)
    deny_tools: list[str] = field(default_factory=list)
    allow_resources: list[str] = field(default_factory=list)
    deny_resources: list[str] = field(default_factory=list)
    allow_actions: list[str] = field(default_factory=list)
    deny_actions: list[str] = field(default_factory=list)


@dataclass
class TenantPolicy:
    allow_topics: list[str] = field(default_factory=list)
    deny_topics: list[str] = field(default_factory=list)
    max_concurrent_jobs: int = 0
    mcp: MCPPolicy = field(default_factory=MCPPolicy)


@dataclass
class RuleMatch:
    tenants: list[str] = field(default_factory=list)
    topics: list[str] = field(default_factory=list)
    capabilities: list[str] = field(default_factory=list)
    risk_tags: list[str] = field(default_factory=list)
    requires: list[str] = field(default_factory=list)
    pack_ids: list[str] = field(default_factory=list)
    actor_ids: list[str] = field(default_factory=list)
    actor_types: list[str] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    secrets_present: Optional[bool] = None
    mcp: Optional[bool] = None


@dataclass
class PolicyRule:
    id: str = ""
    description: str = ""
    match: RuleMatch = field(default_factory=RuleMatch)
    decision: str = "allow"
    reason: str = ""
    constraints: Optional[Constraints] = None
    remediations: list[Remediation] = field(default_factory=list)
    throttle_delay_s: float = 0.0


@dataclass
class SafetyPolicy:
    rules: list[PolicyRule] = field(default_factory=list)
    tenants: dict[str, TenantPolicy] = field(default_factory=dict)
    default_tenant: str = "default"

    @classmethod
    def from_yaml(cls, text: str) -> "SafetyPolicy":
        return cls.from_dict(yaml.safe_load(text) or {})

    @classmethod
    def from_dict(cls, doc: dict[str, Any]) -> "SafetyPolicy":
        pol = cls(default_tenant=doc.get("default_tenant", "default"))
        for name, t in (doc.get("tenants") or {}).items():
            t = t or {}
            mcp = t.get("mcp") or {}
            pol.tenants[name] = TenantPolicy(
                allow_topics=list(t.get("allow_topics") or []),
                deny_topics=list(t.get("deny_topics") or []),
                max_concurrent_jobs=int(t.get("max_concurrent_jobs") or 0),
                mcp=MCPPolicy(**{k: list(v or []) for k, v in mcp.items() if k in MCPPolicy.__dataclass_fields__}),
            )
        for i, r in enumerate(doc.get("rules") or []):
            m = r.get("match") or {}
            c = r.get("constraints")
            constraints = Constraints.from_dict(c) if c else None
            rems = [Remediation.from_dict(x) for x in (r.get("remediations") or [])]
            pol.rules.append(
                PolicyRule(
                    id=str(r.get("id") or f"rule-{i}"),
                    description=str(r.get("description") or ""),
                    match=RuleMatch(
                        tenants=list(m.get("tenants") or []),
                        topics=list(m.get("topics") or []),
                        capabilities=list(m.get("capabilities") or []),
                        risk_tags=list(m.get("risk_tags") or []),
                        requires=list(m.get("requires") or []),
                        pack_ids=list(m.get("pack_ids") or []),
                        actor_ids=list(m.get("actor_ids") or []),
                        actor_types=list(m.get("actor_types") or []),
                        labels={str(k): str(v) for k, v in (m.get("labels") or {}).items()},
                        secrets_present=m.get("secrets_present"),
                        mcp=m.get("mcp"),
                    ),
                    decision=str(r.get("decision") or "allow").lower(),
                    reason=str(r.get("reason") or ""),
                    constraints=constraints,
                    remediations=rems,
                    throttle_delay_s=float(r.get("throttle_delay_s") or 0.0),
                )
            )
        return pol


_DECISION_MAP = {
    "allow": Decision.ALLOW,
    "deny": Decision.DENY,
    "require_approval": Decision.REQUIRE_APPROVAL,
    "allow_with_constraints": Decision.ALLOW_WITH_CONSTRAINTS,
    "throttle": Decision.THROTTLE,
}

MCP_LABELS = ("mcp.server", "mcp.tool", "mcp.resource", "mcp.action")


def _has_mcp_labels(labels: dict[str, str]) -> bool:
    return any(k in labels for k in MCP_LABELS)


def _any_glob(patterns: list[str], value: str) -> bool:
    return any(glob_match(p, value) for p in patterns)


def _matches(rule: RuleMatch, req: PolicyCheckRequest, tenant: str) -> bool:
    meta = req.metadata
    if rule.tenants and tenant not in rule.tenants:
        return False
    if rule.topics and not _any_glob(rule.topics, req.topic):
        return False
    if rule.capabilities:
        cap = meta.capability if meta else ""
        if cap not in rule.capabilities:
            return False
    if rule.risk_tags:
        tags = set(meta.risk_tags) if meta else set()
        if not tags & set(rule.risk_tags):
            return False
    if rule.requires:
        reqs = set(meta.requires) if meta else set()
        if not set(rule.requires) <= reqs:
            return False
    if rule.pack_ids:
        pid = meta.pack_id if meta else ""
        if pid not in rule.pack_ids:
            return False
    if rule.actor_ids and req.actor_id not in rule.actor_ids:
        return False
    if rule.actor_types and req.actor_type not in rule.actor_types:
        return False
    for k, v in rule.labels.items():
        if req.labels.get(k) != v:
            return False
    if rule.secrets_present is not None:
        present = req.labels.get("secrets_present") == "true"
        if present != rule.secrets_present:
            return False
    if rule.mcp is not None:
        if _has_mcp_labels(req.labels) != rule.mcp:
            return False
    return True


def _mcp_allowed(mcp: MCPPolicy, labels: dict[str, str]) -> tuple[bool, str]:
    checks = (
        ("mcp.server", mcp.allow_servers, mcp.deny_servers),
        ("mcp.tool", mcp.allow_tools, mcp.deny_tools),
        ("mcp.resource", mcp.allow_resources, mcp.deny_resources),
        ("mcp.action", mcp.allow_actions, mcp.deny_actions),
    )
    for label, allow, deny in checks:
        v = labels.get(label, "")
        if not v:
            continue
        if deny and _any_glob(deny, v):
            return False, f"{label}={v} denied"
        if allow and not _any_glob(allow, v):
            return False, f"{label}={v} not in allowlist"
    return True, ""


def evaluate(policy: SafetyPolicy, req: PolicyCheckRequest, snapshot: str = "") -> PolicyCheckResponse:
    """First-match rule evaluation with legacy tenant fallback."""
    tenant = req.tenant_id or policy.default_tenant

    # MCP gate runs first when MCP labels are present (reference MCPAllowed)
    tp = policy.tenants.get(tenant) or policy.tenants.get(policy.default_tenant)
    if tp and _has_mcp_labels(req.labels):
        ok, why = _mcp_allowed(tp.mcp, req.labels)
        if not ok:
            return PolicyCheckResponse(
                decision=Decision.DENY.value, reason=f"mcp: {why}", policy_snapshot=snapshot
            )

    for rule in policy.rules:
        if not _matches(rule.match, req, tenant):
            continue
        decision = _DECISION_MAP.get(rule.decision, Decision.ALLOW)
        resp = PolicyCheckResponse(
            decision=decision.value,
            reason=rule.reason or rule.description or f"rule {rule.id}",
            rule_id=rule.id,
            policy_snapshot=snapshot,
            constraints=rule.constraints,
            remediations=rule.remediations,
            throttle_delay_s=rule.throttle_delay_s,
        )
        if decision is Decision.REQUIRE_APPROVAL:
            resp.approval_required = True
        return resp

    # legacy tenant allow/deny topic lists
    if tp:
        if tp.deny_topics and _any_glob(tp.deny_topics, req.topic):
            return PolicyCheckResponse(
                decision=Decision.DENY.value,
                reason=f"topic {req.topic} denied for tenant {tenant}",
                policy_snapshot=snapshot,
            )
        if tp.allow_topics and not _any_glob(tp.allow_topics, req.topic):
            return PolicyCheckResponse(
                decision=Decision.DENY.value,
                reason=f"topic {req.topic} not in tenant {tenant} allowlist",
                policy_snapshot=snapshot,
            )
    return PolicyCheckResponse(decision=Decision.ALLOW.value, reason="default allow", policy_snapshot=snapshot)
