"""Safety kernel as a standalone RPC service.

The reference kernel serves gRPC ``Check/Evaluate/Explain/Simulate/
ListSnapshots`` (kernel.go:56-104).  Here the kernel is a library the
scheduler can embed in-process (lowest latency), and this module makes it a
separate process when deployments want isolation: a minimal aiohttp server
exposing the same five operations, plus :func:`remote_check` — an async
check function suitable for wrapping in the scheduler's circuit-breakered
:class:`~cordum_tpu.controlplane.scheduler.safety_client.SafetyClient`.
"""
from __future__ import annotations

import asyncio
from typing import Optional

import aiohttp
from aiohttp import web

from ...infra import logging as logx
from ...obs.tracer import SPAN_HEADER, TRACE_HEADER, Tracer, trace_headers
from ...protocol.types import PolicyCheckRequest, PolicyCheckResponse
from .kernel import SafetyKernel


class KernelService:
    def __init__(
        self,
        kernel: SafetyKernel,
        *,
        reload_interval_s: float = 30.0,
        tracer: Optional[Tracer] = None,
    ):
        self.kernel = kernel
        self.reload_interval_s = reload_interval_s
        # span ownership: the service wraps each RPC check in an `evaluate`
        # span using the caller's X-Cordum-Trace/Span-Id headers; the wrapped
        # kernel should therefore NOT carry its own tracer
        self.tracer = tracer
        self._runner: Optional[web.AppRunner] = None
        self._reload_task: Optional[asyncio.Task] = None
        app = web.Application()
        app.router.add_post("/v1/check", self._check)
        app.router.add_post("/v1/evaluate", self._evaluate)
        app.router.add_post("/v1/explain", self._explain)
        app.router.add_post("/v1/simulate", self._simulate)
        app.router.add_get("/v1/snapshots", self._snapshots)
        app.router.add_get("/healthz", self._health)
        self.app = app

    async def start(self, host: str = "127.0.0.1", port: int = 7430) -> None:
        await self.kernel.reload()
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self._reload_task = asyncio.ensure_future(self._reload_loop())
        logx.info("safety kernel listening", host=host, port=port,
                  snapshot=self.kernel.snapshot_id)

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._reload_task:
            self._reload_task.cancel()
            self._reload_task = None
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    async def _reload_loop(self) -> None:
        while True:
            await asyncio.sleep(self.reload_interval_s)
            try:
                await self.kernel.reload()  # hot reload (kernel.go:485-508)
            except Exception:
                logx.error("policy reload failed")

    async def _check(self, request: web.Request) -> web.Response:
        req = PolicyCheckRequest.from_dict(await request.json())
        trace_id = request.headers.get(TRACE_HEADER, "")
        if self.tracer is not None and trace_id:
            async with self.tracer.span(
                "evaluate",
                trace_id=trace_id,
                parent_span_id=request.headers.get(SPAN_HEADER, ""),
                attrs={"topic": req.topic if req else ""},
            ) as sp:
                resp = await self.kernel.check(req)
                sp.attrs["decision"] = resp.decision
        else:
            resp = await self.kernel.check(req)
        return web.json_response(resp.to_dict())

    async def _evaluate(self, request: web.Request) -> web.Response:
        req = PolicyCheckRequest.from_dict(await request.json())
        return web.json_response((await self.kernel.evaluate_raw(req)).to_dict())

    async def _explain(self, request: web.Request) -> web.Response:
        req = PolicyCheckRequest.from_dict(await request.json())
        return web.json_response(await self.kernel.explain(req))

    async def _simulate(self, request: web.Request) -> web.Response:
        doc = await request.json()
        results = await self.kernel.simulate(
            doc.get("policy") or {},
            [PolicyCheckRequest.from_dict(r) for r in (doc.get("requests") or [])],
        )
        return web.json_response({"results": results})

    async def _snapshots(self, request: web.Request) -> web.Response:
        return web.json_response({"snapshots": self.kernel.list_snapshots(),
                                  "current": self.kernel.snapshot_id})

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True, "snapshot": self.kernel.snapshot_id})


def remote_check(base_url: str, *, timeout_s: float = 2.0):
    """Build an async check fn hitting a remote kernel — wrap it in
    SafetyClient for the breaker + fail-closed semantics."""
    session: dict = {}

    async def check(req: PolicyCheckRequest) -> PolicyCheckResponse:
        if "s" not in session:
            session["s"] = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=timeout_s)
            )
        # span context rides HTTP headers (the RPC analogue of
        # BusPacket.span_id) so the kernel-side evaluate span lands in the
        # caller's trace
        async with session["s"].post(
            f"{base_url}/v1/check", json=req.to_dict(), headers=trace_headers()
        ) as r:
            if r.status != 200:
                raise RuntimeError(f"kernel returned HTTP {r.status}")
            return PolicyCheckResponse.from_dict(await r.json())

    async def close() -> None:
        s = session.pop("s", None)
        if s is not None:
            await s.close()

    check.close = close  # type: ignore[attr-defined]
    return check
