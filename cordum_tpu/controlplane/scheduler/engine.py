"""Scheduler engine: the core job-processing state machine.

Recreates reference ``core/controlplane/scheduler/engine.go`` behavior,
redesigned for asyncio + at-least-once redelivery:

  * consumes ``sys.job.submit`` / ``sys.job.result`` / ``sys.job.cancel``
    (queue group) + ``sys.heartbeat`` (fan-out)
  * per-job KV lock before mutating state; contention → RetryAfter NAK
    (the reference's 25ms lock spin redesigned as bus redelivery,
    SURVEY.md §7 "hard parts")
  * safety gate with approval-hash re-check: a job carrying
    ``approval_granted`` is re-hashed and compared to the stored decision's
    hash before the stored constraints are honored (engine.go:484-522)
  * decision branches: DENY → DLQ; REQUIRE_APPROVAL → APPROVAL_REQUIRED
    park; THROTTLE → delayed redelivery; ALLOW_WITH_CONSTRAINTS → env
    injection + budget clamp (engine.go:298-347, applyConstraints :674-706)
  * max-attempts + tenant-concurrency + deadline registration
  * strategy pick → SCHEDULED → publish job packet → DISPATCHED → RUNNING
  * ``handleJobResult``: terminal state + result_ptr, DLQ on failure,
    terminal-state short-circuit for idempotency under redelivery
  * tick batching (ISSUE 6): submits landing in one event-loop tick drain
    into ONE selection pass + grouped pipelined commits; anything off the
    common path (redelivery, non-ALLOW decisions, tenant limits) falls back
    to the per-job path above, so the batch is a pure fast path
"""
from __future__ import annotations

import asyncio
import contextlib
import random
import time
from typing import AsyncIterator, Optional

from ...infra import codec, logging as logx, syncsan
from ...infra.bus import Bus, MAX_NAK_DELAY_S, RetryAfter
from ...infra.configsvc import ConfigService
from ...infra.jobstore import JobStore, MetaSnapshot, SafetyDecisionRecord, meta_key
from ...infra.metrics import Metrics
from ...infra.registry import WorkerRegistry
from ...obs.tracer import Tracer, current_trace_context
from ...protocol import subjects as subj
from ...protocol.jobhash import job_hash
from ...protocol.partition import partition_of
from ...utils.eager import eager_gather
from ...utils.ids import now_us
from ...protocol.types import (
    BusPacket,
    Constraints,
    Decision,
    ENV_EFFECTIVE_CONFIG,
    ERROR_SESSION_REQUEUE,
    JobPreempt,
    JobRequest,
    JobResult,
    JobState,
    LABEL_APPROVAL_GRANTED,
    LABEL_PARTITION,
    LABEL_RESUME_TOKENS,
    PolicyCheckRequest,
    STATUS_HINT_STREAM,
    TERMINAL_STATES,
    gang_workers,
)
from .safety_client import SafetyClient
from .strategy import Strategy

DEFAULT_MAX_ATTEMPTS = 5
DEFAULT_SUBMIT_CONCURRENCY = 64
ENV_POLICY_CONSTRAINTS = "CORDUM_POLICY_CONSTRAINTS"
ENV_MAX_CHIPS = "CORDUM_MAX_CHIPS"
# tenant-concurrency NAK backoff base: doubles per redelivery (±25% jitter)
# so a tenant burst de-synchronizes instead of NAKing in lockstep
TENANT_NAK_BASE_S = 0.25
# batch preemption under interactive SLO pressure (docs/ADMISSION.md):
# at most this many BATCH jobs preempted per pressure beacon, each held
# off this long (jittered) before its attempts-exempt re-dispatch, and
# never re-preempted within the cooldown
MAX_PREEMPTIONS_PER_PRESSURE = 8
PREEMPT_HOLDOFF_S = 1.0
PREEMPT_COOLDOWN_S = 5.0
PREEMPTED_REASON = "preempted"

_INFLIGHT_STATES = (
    JobState.SCHEDULED.value,
    JobState.DISPATCHED.value,
    JobState.RUNNING.value,
)


def _owns_everything(job_id: str) -> bool:
    """Identity ownership for the unsharded engine — bound at construction
    so the 1×1 hot path never hashes a job id (ISSUE 6)."""
    return True


class _SubmitItem:
    """One submit riding a scheduler tick batch."""

    __slots__ = (
        "req", "trace_id", "parent_span_id", "fut",
        "snap", "pending", "resp", "sched_sp", "target", "redeliveries",
    )

    def __init__(self, req: JobRequest, trace_id: str, parent_span_id: str,
                 fut: "asyncio.Future[None]", redeliveries: int = 0) -> None:
        self.req = req
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.fut = fut
        self.redeliveries = redeliveries
        self.snap: Optional[MetaSnapshot] = None
        self.pending: dict[str, str] = {}
        self.resp = None
        self.sched_sp = None
        self.target = ""

    @property
    def job_id(self) -> str:
        return self.req.job_id


class _ResultItem:
    """One job result riding a scheduler tick batch."""

    __slots__ = ("res", "trace_id", "parent_span_id", "fut", "snap",
                 "sched_sp", "state")

    def __init__(self, res: JobResult, trace_id: str, parent_span_id: str,
                 fut: "asyncio.Future[None]") -> None:
        self.res = res
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.fut = fut
        self.snap: Optional[MetaSnapshot] = None
        self.sched_sp = None  # the per-item "result" span (see _fail_item)
        self.state: Optional[JobState] = None

    @property
    def job_id(self) -> str:
        return self.res.job_id


@syncsan.instrument
class Engine:
    def __init__(
        self,
        *,
        bus: Bus,
        job_store: JobStore,
        safety: SafetyClient,
        strategy: Strategy,
        registry: WorkerRegistry,
        configsvc: Optional[ConfigService] = None,
        metrics: Optional[Metrics] = None,
        instance_id: str = "scheduler-0",
        max_attempts: int = DEFAULT_MAX_ATTEMPTS,
        tenant_concurrency_limit: int = 0,
        tracer: Optional[Tracer] = None,
        submit_concurrency: int = DEFAULT_SUBMIT_CONCURRENCY,
        shard_index: int = 0,
        shard_count: int = 1,
        batch_ticks: bool = True,
    ):
        self.bus = bus
        self.tracer = tracer or Tracer("scheduler", bus)
        self.job_store = job_store
        self.safety = safety
        self.strategy = strategy
        self.registry = registry
        self.configsvc = configsvc
        self.metrics = metrics or Metrics()
        self.instance_id = instance_id
        self.max_attempts = max_attempts
        self.tenant_concurrency_limit = tenant_concurrency_limit
        # jobs are processed concurrently (the per-job KV lock guarantees
        # safety); the semaphore bounds in-flight work so a submit burst
        # can't spawn unbounded tasks all hammering the state bus at once
        self.submit_concurrency = max(1, submit_concurrency)
        self._sem = asyncio.Semaphore(self.submit_concurrency)
        # keyspace sharding (ISSUE 5): shard i of n owns every job with
        # partition_of(job_id, n) == i and consumes its hash-partitioned
        # lifecycle subjects; there is NO cross-shard lock — worker load and
        # batch affinity live in per-shard caches fed by fan-out heartbeats
        # and tolerate bounded staleness (docs/PROTOCOL.md §Partitioning)
        if not (0 <= shard_index < max(1, shard_count)):
            raise ValueError(f"shard_index {shard_index} out of range for {shard_count} shards")
        self.shard_index = shard_index
        self.shard_count = max(1, shard_count)
        self._shard_label = str(shard_index)
        if self.shard_count == 1:
            # 1×1 specialization: ownership and partition stamping collapse
            # to identity at construction — no per-message branch or crc32
            self.owns = _owns_everything  # type: ignore[method-assign]
            self._stamp_partition = self._stamp_noop  # type: ignore[method-assign]
        self._inflight = 0  # submit backlog gauge (cordum_shard_partition_queue_depth)
        # start()/stop() hold this across their subscribe/teardown awaits so
        # a racing start+stop pair cannot interleave at an await and leak a
        # subscription or a half-cancelled drain task (CL008)
        self._lifecycle_lock = asyncio.Lock()
        self._subs = []  # cordum: guarded-by(_lifecycle_lock)
        # tick batching (ISSUE 6): submits arriving in one event-loop tick
        # drain together; grouped commits need co-committable keys, which
        # kv.pipe_group answers per key
        self.batch_ticks = batch_ticks
        self._submit_q: list[_SubmitItem] = []
        self._result_q: list[_ResultItem] = []
        self._submit_wake = asyncio.Event()
        self._drain_task: Optional[asyncio.Task] = None  # cordum: guarded-by(_lifecycle_lock)
        # dispatch-time snapshot cache: the RUNNING commit's post-commit
        # MetaSnapshot, so the result path needs ZERO reads in the common
        # case (a conflict — e.g. a cancel racing the result — re-reads)
        self._snap_cache: dict[str, MetaSnapshot] = {}
        # serving failover (docs/SERVING.md §Migration, drain, and
        # failover): the owner shard shadows each live session's streamed
        # tokens in memory (offset-merged from stream progress packets) so
        # a crash re-dispatch can stamp them as the forced-decode resume
        # prefix.  Deliberately NOT persisted — per-token writes would
        # swamp the job store; after a scheduler restart a failover simply
        # replays from the prompt (same tokens, more decode work).
        self._stream_tokens: dict[str, list[int]] = {}
        # batch preemption under interactive SLO pressure (docs/ADMISSION.md
        # §Preemption): the gateway admission controller's pressure beacons
        # trigger a bounded scan that asks workers to hand back dispatched
        # BATCH jobs; preempted jobs re-dispatch attempts-exempt after a
        # jittered hold-off
        self._preempt_cooldown: dict[str, float] = {}
        self._preempt_tasks: set[asyncio.Task] = set()
        self._preempt_scan: Optional[asyncio.Task] = None
        # gang scheduling (docs/GANG.md): attached by GangScheduler's
        # constructor; submits carrying cordum.gang_workers depart the
        # single-worker dispatch path at _post_decision
        self.gangs = None
        # kv round-trip accounting (cordum_kv_roundtrips_total{op}) for the
        # store this engine drives — the bench's kv_roundtrips_per_job source
        job_store.kv.bind_metrics(self.metrics)

    # ------------------------------------------------------------------
    async def start(self) -> None:
        async with self._lifecycle_lock:
            # plain subjects stay subscribed even when sharded: they are the
            # unstamped-publisher fallback — whichever shard draws the message
            # from the queue group forwards it to the owner's partition subject
            self._subs = [
                await self.bus.subscribe(subj.SUBMIT, self._on_submit, queue=subj.QUEUE_SCHEDULER),
                await self.bus.subscribe(subj.RESULT, self._on_result, queue=subj.QUEUE_SCHEDULER),
                await self.bus.subscribe(subj.CANCEL, self._on_cancel, queue=subj.QUEUE_SCHEDULER),
                await self.bus.subscribe(subj.HEARTBEAT, self._on_heartbeat),
                await self.bus.subscribe(subj.PROGRESS, self._on_progress),
                await self.bus.subscribe(subj.ADMISSION_PRESSURE, self._on_pressure),
            ]
            if self.shard_count > 1:
                # this shard's slice of the keyspace: its own partition subjects
                # (queue groups so replicas of one shard still split the load)
                q = f"{subj.QUEUE_SCHEDULER}-{self.shard_index}"
                self._subs += [
                    await self.bus.subscribe(
                        subj.submit_subject(self.shard_index, self.shard_count),
                        self._on_submit, queue=q),
                    await self.bus.subscribe(
                        subj.result_subject(self.shard_index, self.shard_count),
                        self._on_result, queue=q),
                    await self.bus.subscribe(
                        subj.cancel_subject(self.shard_index, self.shard_count),
                        self._on_cancel, queue=q),
                ]
            if self.batch_ticks and self._drain_task is None:
                self._drain_task = asyncio.ensure_future(self._submit_drain_loop())

    async def stop(self) -> None:
        async with self._lifecycle_lock:
            for s in self._subs:
                s.unsubscribe()
            self._subs = []
            if self._drain_task is not None:
                self._drain_task.cancel()
                try:
                    await self._drain_task
                except asyncio.CancelledError:
                    pass
                self._drain_task = None
            for it in [*self._submit_q, *self._result_q]:
                if not it.fut.done():
                    it.fut.cancel()
            self._submit_q = []
            self._result_q = []
            self._snap_cache.clear()
            self._stream_tokens.clear()
            if self._preempt_scan is not None:
                self._preempt_scan.cancel()
                await logx.join_task(self._preempt_scan, name="preempt-scan")
                self._preempt_scan = None
            for t in list(self._preempt_tasks):
                t.cancel()
                await logx.join_task(t, name="preempt-redispatch")
            self._preempt_tasks.clear()
            self._preempt_cooldown.clear()

        # ------------------------------------------------------------------
    def owns(self, job_id: str) -> bool:
        return partition_of(job_id, self.shard_count) == self.shard_index

    async def _forward_to_owner(
        self, kind: str, job_id: str, subject_fn, pkt: BusPacket
    ) -> None:
        """Route an unstamped message to the owning shard's partition
        subject (one extra bus hop; the stamped fast path skips it)."""
        p = partition_of(job_id, self.shard_count)
        self.metrics.shard_forwarded.inc(kind=kind, shard=self._shard_label)
        await self.bus.publish(subject_fn(p, self.shard_count), pkt)

    # ------------------------------------------------------------------
    async def _on_heartbeat(self, subject: str, pkt: BusPacket) -> None:
        hb = pkt.heartbeat
        if hb is None:
            return
        if hb.draining and hb.worker_id:
            # drain beacon: deregister on sight and drop every affinity
            # entry pointing at the worker — new session/batch jobs must
            # not route to a worker that is migrating its state away
            self.registry.remove(hb.worker_id)
            self._evict_affinity(hb.worker_id)
        else:
            self.registry.update(hb)
        self.metrics.workers_live.set(len(self.registry.snapshot()))
        if hb.worker_id:
            self.metrics.tpu_duty_cycle.set(hb.tpu_duty_cycle, worker=hb.worker_id)

    def _evict_affinity(self, worker_id: str) -> None:
        evict = getattr(self.strategy, "evict_worker", None)
        if evict is not None:
            evict(worker_id)

    async def _on_progress(self, subject: str, pkt: BusPacket) -> None:
        pr = pkt.job_progress
        if pr is None or not pr.job_id:
            return
        if pr.status_hint == STATUS_HINT_STREAM:
            # llm.generate token-stream packets are transport, not state:
            # the gateway WS tap relays them live and the terminal result
            # carries the full token list — persisting one event per decode
            # step would swamp the job store.  The owner shard DOES shadow
            # them in memory: they become the forced-decode resume prefix
            # when the worker dies mid-session (failover_job).
            if pr.tokens and self.owns(pr.job_id):
                self._record_stream(pr.job_id, pr.offset, pr.tokens)
            return
        if not self.owns(pr.job_id):
            return  # progress fans out to every shard; only the owner records
        await self.job_store.append_event(
            pr.job_id, "progress", percent=pr.percent, message=pr.message
        )

    def _record_stream(self, job_id: str, offset: int, tokens: list) -> None:
        buf = self._stream_tokens.get(job_id)
        if buf is None:
            if len(self._stream_tokens) > 8192:
                self._stream_tokens.clear()  # leak guard (entries pop on terminal)
            buf = self._stream_tokens[job_id] = []
        off = offset if isinstance(offset, int) and offset >= 0 else len(buf)
        for i, t in enumerate(tokens):
            idx = off + i
            if idx == len(buf):
                buf.append(int(t))
            elif idx < len(buf):
                buf[idx] = int(t)
            # idx > len(buf): a gap (lost packet) — the worker's resume
            # replay at offset 0 backfills it on the next failover

    async def _on_cancel(self, subject: str, pkt: BusPacket) -> None:
        c = pkt.job_cancel
        if c is None or not c.job_id:
            return
        if not self.owns(c.job_id):
            await self._forward_to_owner("cancel", c.job_id, subj.cancel_subject, pkt)
            return
        if await self.job_store.cancel_job(c.job_id):
            await self.job_store.append_event(c.job_id, "cancelled", reason=c.reason)
            if self.gangs is not None:
                # a cancelled gang job aborts its whole gang (members stop,
                # devices release) without a requeue
                await self.gangs.on_cancel(c.job_id)

    # ------------------------------------------------------------------
    # batch preemption (docs/ADMISSION.md §Preemption): the telemetry
    # plane changing the data plane — interactive SLO pressure requeues
    # dispatched BATCH work instead of letting interactive p99 collapse
    # ------------------------------------------------------------------
    async def _on_pressure(self, subject: str, pkt: BusPacket) -> None:
        ap = pkt.admission_pressure
        if ap is None or not ap.preempt_batch:
            return
        if self._preempt_scan is not None and not self._preempt_scan.done():
            return  # single-flight: one scan per beacon at most
        self._preempt_scan = asyncio.ensure_future(self._preempt_batch_jobs())

    async def _preempt_batch_jobs(self) -> int:
        """Scan owned DISPATCHED/RUNNING BATCH jobs and ask their workers to
        hand them back (bounded per beacon, per-job cooldown).  Workers
        requeue where that is safe (queued intake slots, serving sessions);
        a handler already executing simply ignores the request."""
        now = time.monotonic()
        self._preempt_cooldown = {
            jid: t for jid, t in self._preempt_cooldown.items()
            if now - t < PREEMPT_COOLDOWN_S
        }
        n = 0
        for state in (JobState.RUNNING.value, JobState.DISPATCHED.value):
            if n >= MAX_PREEMPTIONS_PER_PRESSURE:
                break
            for jid in await self.job_store.list_by_state(state, 128):
                if n >= MAX_PREEMPTIONS_PER_PRESSURE:
                    break
                if not self.owns(jid) or jid in self._preempt_cooldown:
                    continue
                meta = await self.job_store.get_meta(jid)
                if (meta.get("priority") or "BATCH") != "BATCH":
                    continue  # only BATCH yields to interactive pressure
                if meta.get("state") != state:
                    continue  # moved on concurrently
                await self.preempt_job(jid)
                n += 1
        return n

    async def preempt_job(self, job_id: str, *, reason: str = "slo_pressure") -> None:
        """Fan out a :class:`JobPreempt` for one BATCH job.  Fire-and-forget:
        the holding worker answers with a non-terminal ``SESSION_REQUEUE``
        result (reason ``preempted``) when it can yield the job."""
        self._preempt_cooldown[job_id] = time.monotonic()
        self.metrics.preemptions.inc(reason="requested")
        await self.bus.publish(
            subj.PREEMPT,
            BusPacket.wrap(
                JobPreempt(job_id=job_id, reason=reason,
                           requested_by=self.instance_id),
                sender_id=self.instance_id,
            ),
        )

    def _schedule_preempt_redispatch(self, job_id: str) -> None:
        """Attempts-exempt re-dispatch of a preempted job after a jittered
        hold-off — long enough for the interactive burst to drain ahead of
        it, short enough that preemption never strands work (the replayer's
        result-replay nudge backstops it regardless)."""
        async def _redispatch() -> None:
            await asyncio.sleep(
                PREEMPT_HOLDOFF_S * (1.0 + random.uniform(-0.5, 0.5))
            )
            moved = await self.failover_job(
                job_id, reason=PREEMPTED_REASON, count_attempt=False
            )
            if moved:
                self.metrics.preemptions.inc(reason="redispatched")

        t = asyncio.ensure_future(_redispatch())
        self._preempt_tasks.add(t)
        t.add_done_callback(self._preempt_tasks.discard)

    # ------------------------------------------------------------------
    async def _on_submit(self, subject: str, pkt: BusPacket) -> None:
        req = pkt.job_request
        if req is None or not req.job_id or not req.topic:
            return
        if not self.owns(req.job_id):
            await self._forward_to_owner("submit", req.job_id, subj.submit_subject, pkt)
            return
        self._inflight += 1
        self.metrics.shard_queue_depth.set(float(self._inflight), shard=self._shard_label)
        try:
            if self.batch_ticks and self._drain_task is not None:
                # enqueue for the tick batch; the await preserves per-message
                # semantics exactly (a RetryAfter raised while processing the
                # batch propagates to THIS delivery and drives redelivery)
                fut: asyncio.Future[None] = asyncio.get_running_loop().create_future()
                self._submit_q.append(
                    _SubmitItem(req, pkt.trace_id, pkt.span_id, fut,
                                pkt.redelivery_count)
                )
                self._submit_wake.set()
                await fut
            else:
                async with self._sem:
                    await self.handle_job_request(
                        req, trace_id=pkt.trace_id, parent_span_id=pkt.span_id,
                        redeliveries=pkt.redelivery_count,
                    )
        finally:
            self._inflight -= 1
            self.metrics.shard_queue_depth.set(float(self._inflight), shard=self._shard_label)

    # ------------------------------------------------------------------
    # tick batching (ISSUE 6): the submit fast path
    # ------------------------------------------------------------------
    async def _submit_drain_loop(self) -> None:
        """Drain every submit that accumulated during the last event-loop
        tick and process them as ONE batch (mirror of the statebus write
        coalescer).  The loop is single-flight: submits arriving while a
        batch is in progress form the next batch."""
        while True:
            await self._submit_wake.wait()
            self._submit_wake.clear()
            batch = self._submit_q[: self.submit_concurrency]
            del self._submit_q[: self.submit_concurrency]
            rbatch = self._result_q[: self.submit_concurrency]
            del self._result_q[: self.submit_concurrency]
            if self._submit_q or self._result_q:
                self._submit_wake.set()
            for items, process in (
                (batch, self._process_submit_batch),
                (rbatch, self._process_result_batch),
            ):
                if not items:
                    continue
                try:
                    await process(items)
                except asyncio.CancelledError:
                    for it in items:
                        if not it.fut.done():
                            it.fut.cancel()
                    raise
                except Exception as e:  # noqa: BLE001 - a batch bug must not wedge the queue
                    logx.error("tick batch failed", err=str(e))
                    for it in items:
                        if not it.fut.done():
                            it.fut.set_exception(e)

    @contextlib.asynccontextmanager
    async def _spanctx(
        self, name: str, trace_id: str, parent_span_id: str, attrs: dict
    ) -> AsyncIterator:
        """Explicit-parent span (no ambient contextvar): the batched path
        runs several jobs' spans interleaved in one task, so parenting must
        not ride the task-local context."""
        sp = self.tracer.begin(
            name, trace_id=trace_id, parent_span_id=parent_span_id, attrs=attrs
        )
        try:
            yield sp
        except BaseException as e:
            sp.attrs.setdefault("error", type(e).__name__)
            await self.tracer.finish(sp, status="ERROR")
            raise
        else:
            await self.tracer.finish(sp)

    def _submit_fields(self, req: JobRequest, trace_id: str) -> dict[str, str]:
        return {
            "topic": req.topic,
            "tenant_id": req.tenant_id,
            "principal_id": req.principal_id,
            "context_ptr": req.context_ptr,
            "workflow_id": req.workflow_id,
            "run_id": req.run_id,
            "trace_id": trace_id,
            "priority": req.priority,
            "submitted_at_us": str(time.time_ns() // 1000),
        }

    async def _process_submit_batch(self, batch: list[_SubmitItem]) -> None:
        self.metrics.sched_tick_batch.observe(float(len(batch)))
        # duplicate job ids inside one tick cannot share a grouped create
        # (their watches would collapse); dupes take the per-job path, whose
        # lock + short-circuits already model redelivery
        seen: set[str] = set()
        grouped: list[_SubmitItem] = []
        fallback: list[_SubmitItem] = []
        for it in batch:
            if it.req.job_id in seen:
                fallback.append(it)
            else:
                seen.add(it.req.job_id)
                grouped.append(it)

        # stage 1 — grouped optimistic create: every job assumed fresh
        # (version 0), →PENDING + request blob + trace membership folded
        # into ONE pipe per co-committable key group.  A conflicted group
        # means at least one job already exists → that whole group falls
        # back to the per-job path (which re-reads and short-circuits).
        created: list[_SubmitItem] = []
        kv = self.job_store.kv
        groups: dict[int, list[_SubmitItem]] = {}
        for it in grouped:
            groups.setdefault(kv.pipe_group(meta_key(it.req.job_id)), []).append(it)
        for members in groups.values():
            watches: dict[str, int] = {}
            ops: list[tuple] = []
            overlays: dict[str, dict[str, bytes]] = {}
            for it in members:
                jid = it.req.job_id
                c_ops, overlay, _ = self.job_store.build_chain_ops(
                    jid, MetaSnapshot(),
                    [(JobState.PENDING, self._submit_fields(it.req, it.trace_id),
                      "submit")],
                )
                watches[meta_key(jid)] = 0
                ops.extend(c_ops)
                ops.extend(self.job_store.put_request_ops(it.req))
                ops.extend(self.job_store.add_to_trace_ops(it.trace_id, jid))
                overlays[jid] = overlay
            ok, versions = await kv.pipe_execute(watches, ops)
            if ok:
                for it in members:
                    jid = it.req.job_id
                    it.snap = MetaSnapshot(
                        versions.get(meta_key(jid), 0), dict(overlays[jid])
                    )
                    self.metrics.jobs_received.inc(topic=it.req.topic)
                created.extend(members)
            else:
                fallback.extend(members)

        if fallback:
            self.metrics.sched_tick_fallbacks.inc(amount=float(len(fallback)))
            await eager_gather([self._fallback_submit(it) for it in fallback])
        if not created:
            return

        # stage 2 — per-job config attach + policy check, gathered so the
        # checks overlap; each runs inside its own task, so the policy-check
        # span context stays per-job (remote kernels see correct parents)
        await eager_gather([self._batch_pre(it) for it in created])

        # stage 3 — classify: the grouped tail handles only the plain-ALLOW
        # shape (no constraints, no tenant concurrency gate); everything
        # else continues on the per-job decision tail
        simple: list[_SubmitItem] = []
        complex_: list[_SubmitItem] = []
        for it in created:
            if it.fut.done():
                continue  # pre-stage already failed this item
            resp = it.resp
            gated = bool(self._tenant_limit(it.req) and it.req.tenant_id)
            is_gang = self.gangs is not None and gang_workers(it.req.labels) > 0
            if resp.decision == Decision.ALLOW.value and not gated and not is_gang:
                simple.append(it)
            else:
                complex_.append(it)
        if complex_:
            self.metrics.sched_tick_fallbacks.inc(amount=float(len(complex_)))
            await eager_gather([self._complex_tail(it) for it in complex_])
        if simple:
            await self._batch_dispatch(simple)

    async def _fallback_submit(self, it: _SubmitItem) -> None:
        """Per-job slow path for batch members that aren't fresh creates;
        funnels the outcome (including RetryAfter) into the item's future."""
        try:
            async with self._sem:
                await self.handle_job_request(
                    it.req, trace_id=it.trace_id, parent_span_id=it.parent_span_id,
                    redeliveries=it.redeliveries,
                )
        except BaseException as e:
            if not it.fut.done():
                it.fut.set_exception(e)
        else:
            if not it.fut.done():
                it.fut.set_result(None)

    async def _batch_pre(self, it: _SubmitItem) -> None:
        req = it.req
        it.sched_sp = self.tracer.begin(
            "schedule", trace_id=it.trace_id, parent_span_id=it.parent_span_id,
            attrs={"job_id": req.job_id, "topic": req.topic},
        )
        try:
            cfg_hash = await self._attach_effective_config(req)
            if cfg_hash:
                it.pending["config_hash"] = cfg_hash
            async with self.tracer.span(
                "policy-check", trace_id=it.sched_sp.trace_id,
                parent_span_id=it.sched_sp.span_id, attrs={"job_id": req.job_id},
            ) as polsp:
                it.resp = await self._check_safety(req)
                polsp.attrs["decision"] = it.resp.decision
        except BaseException as e:
            await self._fail_item(it, e)

    async def _complex_tail(self, it: _SubmitItem) -> None:
        """Non-ALLOW / gated decisions: reuse the per-job decision tail."""
        try:
            await self._post_decision(
                it.req, it.resp, snap=it.snap, pending_fields=it.pending,
                trace_id=it.sched_sp.trace_id, parent_span_id=it.sched_sp.span_id,
                redeliveries=it.redeliveries,
            )
        except BaseException as e:
            await self._fail_item(it, e)
        else:
            await self._finish_item(it)

    async def _fail_item(self, it: _SubmitItem, e: BaseException) -> None:
        if it.sched_sp is not None:
            it.sched_sp.attrs.setdefault("error", type(e).__name__)
            await self.tracer.finish(it.sched_sp, status="ERROR")
            it.sched_sp = None
        if not it.fut.done():
            it.fut.set_exception(e)

    async def _finish_item(self, it: _SubmitItem) -> None:
        if it.sched_sp is not None:
            await self.tracer.finish(it.sched_sp)
            it.sched_sp = None
        if not it.fut.done():
            it.fut.set_result(None)

    async def _group_chain(self, items: list, steps_for, extra_for=None) -> None:
        """Commit one transition chain per item, folding co-committable items
        into ONE grouped pipe; a conflicted group degrades to per-job
        ``apply_chain`` (which re-reads and retries).  Per-item failures
        (e.g. a cancel racing the batch → IllegalTransition) fail only that
        item via its future."""
        kv = self.job_store.kv
        groups: dict[int, list] = {}
        for it in items:
            groups.setdefault(kv.pipe_group(meta_key(it.job_id)), []).append(it)
        for members in groups.values():
            watches: dict[str, int] = {}
            ops: list[tuple] = []
            overlays: dict[str, dict[str, bytes]] = {}
            try:
                for it in members:
                    jid = it.job_id
                    c_ops, overlay, _ = self.job_store.build_chain_ops(
                        jid, it.snap, steps_for(it)
                    )
                    if extra_for is not None:
                        c_ops = [*c_ops, *extra_for(it)]
                    watches[meta_key(jid)] = it.snap.version
                    ops.extend(c_ops)
                    overlays[jid] = overlay
                ok, versions = await kv.pipe_execute(watches, ops)
            except BaseException:
                ok = False
            if ok:
                for it in members:
                    jid = it.job_id
                    merged = dict(it.snap.fields)
                    merged.update(overlays[jid])
                    it.snap = MetaSnapshot(versions.get(meta_key(jid), 0), merged)
                continue
            # group lost a race (or a chain build failed): per-job commits
            for it in members:
                try:
                    _, it.snap = await self.job_store.apply_chain(
                        it.job_id, steps_for(it), snap=it.snap,
                        extra_ops=list(extra_for(it)) if extra_for else None,
                    )
                except BaseException as e:
                    await self._fail_item(it, e)

    async def _batch_dispatch(self, items: list[_SubmitItem]) -> None:
        """The grouped plain-ALLOW tail: one selection pass, one grouped
        SCHEDULED commit, overlapped publishes + one grouped
        DISPATCHED→RUNNING commit."""
        # selection: one batched strategy pass (registry snapshot amortized)
        st_spans = [
            self.tracer.begin(
                "strategy", trace_id=it.sched_sp.trace_id,
                parent_span_id=it.sched_sp.span_id,
                attrs={"job_id": it.req.job_id},
            )
            for it in items
        ]
        targets = self.strategy.pick_subjects([it.req for it in items])
        for it, sp, target in zip(items, st_spans, targets):
            it.target = target
            sp.attrs["target"] = target
            await self.tracer.finish(sp)
        for it in items:
            # fresh create → this is attempt 1 (mirrors the per-job tail)
            it.pending["attempts"] = "1"

        def sched_steps(it: _SubmitItem):
            return [(JobState.SCHEDULED,
                     {"dispatch_subject": it.target, **it.pending}, "scheduled")]

        def sched_extra(it: _SubmitItem):
            extra = self.job_store.put_safety_decision_ops(
                self._decision_record(it.req, it.resp)
            )
            if it.req.tenant_id:
                extra += self.job_store.tenant_active_add_ops(
                    it.req.tenant_id, it.req.job_id
                )
            if it.req.budget and it.req.budget.deadline_unix_ms:
                extra += self.job_store.register_deadline_ops(
                    it.req.job_id, it.req.budget.deadline_unix_ms
                )
            return extra

        await self._group_chain(items, sched_steps, sched_extra)
        live = [it for it in items if not it.fut.done()]
        if not live:
            return

        # dispatch: publishes overlap each other AND the grouped
        # DISPATCHED→RUNNING bookkeeping commit (same contract as the
        # per-job path: an undelivered publish leaves the job RUNNING for
        # the replayer's result-replay nudge to recover)
        d_spans = []
        pubs = []
        for it in live:
            dsp = self.tracer.begin(
                "dispatch", trace_id=it.sched_sp.trace_id,
                parent_span_id=it.sched_sp.span_id,
                attrs={"job_id": it.req.job_id, "target": it.target},
            )
            d_spans.append(dsp)
            self._stamp_partition(it.req)
            out = BusPacket.wrap(
                it.req, trace_id=it.trace_id, sender_id=self.instance_id,
                span_id=dsp.span_id, parent_span_id=dsp.parent_span_id,
            )
            pubs.append(self.bus.publish(it.target, out))

        def run_steps(it: _SubmitItem):
            return [(JobState.DISPATCHED, None, "dispatched"),
                    (JobState.RUNNING, None, "running")]

        results = await asyncio.gather(
            self._group_chain(live, run_steps), *pubs, return_exceptions=True
        )
        if isinstance(results[0], BaseException):
            logx.error("batched DISPATCHED/RUNNING commit failed",
                       err=str(results[0]))
        for it, dsp, pub_res in zip(live, d_spans, results[1:]):
            if isinstance(pub_res, BaseException):
                dsp.attrs.setdefault("error", type(pub_res).__name__)
                await self.tracer.finish(dsp, status="ERROR")
                await self._fail_item(it, pub_res)
                continue
            await self.tracer.finish(dsp)
            if it.fut.done():
                continue  # run_steps commit failed this item
            self._cache_snap(it.req.job_id, it.snap)
            self.metrics.jobs_dispatched.inc(topic=it.req.topic)
            self.metrics.shard_scheduled.inc(shard=self._shard_label)
            sub_us = int(it.snap.get("submitted_at_us", "0") or 0)
            if sub_us:
                self.metrics.dispatch_latency.observe(
                    max(0.0, (now_us() - sub_us) / 1e6)
                )
            await self._finish_item(it)

    def _cache_snap(self, job_id: str, snap: MetaSnapshot) -> None:
        """Remember the post-RUNNING snapshot so the result path commits
        read-free; the cache is advisory (a conflict re-reads)."""
        if len(self._snap_cache) > 65536:
            self._snap_cache.clear()
        self._snap_cache[job_id] = snap

    async def _process_result_batch(self, items: list[_ResultItem]) -> None:
        self.metrics.sched_tick_batch.observe(float(len(items)))
        fast: list[_ResultItem] = []
        fallback: list[_ResultItem] = []
        seen: set[str] = set()
        for it in items:
            res = it.res
            snap = self._snap_cache.pop(res.job_id, None)
            try:
                it.state = JobState(res.status)
            except ValueError:
                it.state = JobState.FAILED
            if (
                snap is None or snap.is_terminal
                or it.state not in TERMINAL_STATES
                or res.job_id in seen
            ):
                fallback.append(it)  # no cached snap / hint / dup-in-tick
                continue
            seen.add(res.job_id)
            it.snap = snap
            fast.append(it)
        if fallback:
            await eager_gather([self._fallback_result(it) for it in fallback])
        if not fast:
            return
        for it in fast:
            it.sched_sp = self.tracer.begin(
                "result", trace_id=it.trace_id, parent_span_id=it.parent_span_id,
                attrs={"job_id": it.res.job_id, "status": it.state.value},
            )

        def result_steps(it: _ResultItem):
            return [(it.state, self._result_fields(it.res), "result")]

        await self._group_chain(fast, result_steps)
        for it in fast:
            if it.fut.done():
                # commit failed this item (e.g. a cancel won the race and the
                # re-read raised IllegalTransition — the per-job path raises
                # the same way); its future already carries the error
                continue
            self._stream_tokens.pop(it.res.job_id, None)
            self.metrics.jobs_completed.inc(status=it.state.value)
            klass = it.snap.get("priority", "") or "BATCH"
            self.metrics.jobs_by_class.inc(job_class=klass, status=it.state.value)
            sub_us = int(it.snap.get("submitted_at_us", "0") or 0)
            if sub_us:
                # the job's trace id rides as an exemplar so an e2e bucket
                # spike resolves straight to a stored trace (ISSUE 10)
                self.metrics.e2e_latency.observe(
                    max(0.0, (now_us() - sub_us) / 1e6),
                    exemplar=it.snap.get("trace_id", ""), job_class=klass,
                )
            if it.state in (JobState.FAILED, JobState.TIMEOUT):
                req = await self.job_store.get_request(it.res.job_id)
                if req is not None:
                    await self._emit_dlq(
                        req, it.res.error_message or it.state.value,
                        it.res.error_code or it.state.value, status=it.state.value,
                    )
            await self._finish_item(it)

    async def _fallback_result(self, it: _ResultItem) -> None:
        try:
            async with self._sem:
                await self.handle_job_result(
                    it.res, trace_id=it.trace_id, parent_span_id=it.parent_span_id
                )
        except BaseException as e:
            if not it.fut.done():
                it.fut.set_exception(e)
        else:
            if not it.fut.done():
                it.fut.set_result(None)

    @staticmethod
    def _result_fields(res: JobResult) -> dict[str, str]:
        fields = {
            "result_ptr": res.result_ptr,
            "worker_id": res.worker_id,
            "execution_ms": str(res.execution_ms),
        }
        if res.error_message:
            fields["error_message"] = res.error_message
            fields["error_code"] = res.error_code
        return fields

    async def handle_job_request(
        self, req: JobRequest, *, trace_id: str = "", parent_span_id: str = "",
        redeliveries: int = 0,
    ) -> None:
        if not await self.job_store.acquire_job_lock(req.job_id, self.instance_id, ttl_s=30.0):
            raise RetryAfter(0.05, f"job {req.job_id} locked")
        try:
            submit_fields = self._submit_fields(req, trace_id)
            create_extra = self.job_store.put_request_ops(req)
            create_extra += self.job_store.add_to_trace_ops(trace_id, req.job_id)
            # Optimistic fresh-job fast path: assume job:meta does not exist
            # yet (version 0) and fold →PENDING + the request blob + trace
            # membership into ONE pipelined commit — zero read round trips
            # for the common case.  A conflict means the job already exists:
            # apply_chain hands back a fresh snapshot to short-circuit on.
            changed, snap = await self.job_store.apply_chain(
                req.job_id,
                [(JobState.PENDING, submit_fields, "submit")],
                snap=MetaSnapshot(), extra_ops=create_extra, max_retries=1,
            )
            if changed is None:
                st = snap.state
                if snap.is_terminal:
                    return  # idempotency short-circuit under redelivery
                self.metrics.jobs_received.inc(topic=req.topic)
                if st in _INFLIGHT_STATES:
                    # In-flight short-circuit: a redelivered submit for a job
                    # already dispatched must not re-run the safety check,
                    # burn an attempt, or attempt an illegal →SCHEDULED
                    # transition (enough duplicates could otherwise DLQ a job
                    # that is still running).
                    return
                if st == JobState.APPROVAL_REQUIRED.value:
                    # Parked jobs only move via a valid approval: the
                    # republish must carry the approval label AND hash-match
                    # the stored decision record; anything else must not
                    # clobber the parked request/record (attempted approval
                    # bypass otherwise).
                    stored = await self.job_store.get_safety_decision(req.job_id)
                    granted = (req.labels or {}).get(LABEL_APPROVAL_GRANTED) == "true"
                    if not (granted and stored and stored.job_hash == job_hash(req)):
                        logx.warn(
                            "ignoring republish of parked job without valid approval",
                            job_id=req.job_id,
                        )
                        return
                    await self.job_store.put_request(req)
                elif not st:
                    # rare: meta expired between the failed create and the
                    # re-read — walk the normal validated create with retries
                    changed, snap = await self.job_store.apply_chain(
                        req.job_id,
                        [(JobState.PENDING, submit_fields, "submit")],
                        snap=snap, extra_ops=create_extra,
                    )
                else:
                    # PENDING redelivery: refresh the persisted request blob
                    # only (the original submit fields stay authoritative)
                    await self.job_store.put_request(req)
            else:
                self.metrics.jobs_received.inc(topic=req.topic)
            # schedule span: covers safety gate + strategy + dispatch; a
            # RetryAfter (throttle / tenant limit) surfaces as an ERROR span
            # with the exception type, then still drives redelivery
            async with self.tracer.span(
                "schedule",
                trace_id=trace_id,
                parent_span_id=parent_span_id,
                attrs={"job_id": req.job_id, "topic": req.topic},
            ):
                await self.process_job(req, trace_id=trace_id, snap=snap,
                                       redeliveries=redeliveries)
        finally:
            await self.job_store.release_job_lock(req.job_id, self.instance_id)

    # ------------------------------------------------------------------
    async def process_job(
        self, req: JobRequest, *, trace_id: str = "",
        snap: Optional[MetaSnapshot] = None, redeliveries: int = 0,
    ) -> None:
        if snap is None:
            snap = await self.job_store.watch_meta(req.job_id)
        # fields produced along the way (config hash, attempts) ride the next
        # state-transition commit instead of costing their own round trips
        pending_fields: dict[str, str] = {}
        cfg_hash = await self._attach_effective_config(req)
        if cfg_hash:
            pending_fields["config_hash"] = cfg_hash

        async with self.tracer.span(
            "policy-check", attrs={"job_id": req.job_id}
        ) as polsp:
            resp = await self._check_safety(req)
            polsp.attrs["decision"] = resp.decision
        # nested spans in the shared tail take explicit parents (the batched
        # path has no per-job ambient context); here the ambient context IS
        # the enclosing schedule span, so behavior is unchanged
        ptrace, pspan = current_trace_context()
        await self._post_decision(
            req, resp, snap=snap, pending_fields=pending_fields,
            trace_id=trace_id or ptrace, parent_span_id=pspan,
            redeliveries=redeliveries,
        )

    def _tenant_limit(self, req: JobRequest) -> int:
        """Per-tenant concurrency limit: org-scoped effective config
        (rate_limits.concurrent_jobs), else the global default."""
        limit = self.tenant_concurrency_limit
        eff_raw = (req.env or {}).get(ENV_EFFECTIVE_CONFIG)
        if eff_raw and req.tenant_id:
            eff = codec.loads_env_json(eff_raw)
            if isinstance(eff, dict):
                try:
                    rate = eff.get("rate_limits") or {}
                    limit = int(rate.get("concurrent_jobs", limit) or limit)
                except (ValueError, TypeError, AttributeError):
                    pass
        return limit

    async def _post_decision(
        self, req: JobRequest, resp, *,
        snap: MetaSnapshot, pending_fields: dict[str, str],
        trace_id: str = "", parent_span_id: str = "",
        redeliveries: int = 0,
    ) -> None:
        """Everything after the safety check: decision branches, tenant
        gate, deadline, attempts guard, strategy pick, dispatch.  Shared by
        the per-job path and the batched tick path's non-simple items."""
        decision = resp.decision
        decision_ops = self.job_store.put_safety_decision_ops(
            self._decision_record(req, resp)
        )

        if decision == Decision.DENY.value:
            self.metrics.jobs_denied.inc(topic=req.topic)
            await self.job_store.apply_chain(
                req.job_id,
                [(JobState.DENIED,
                  {"deny_reason": resp.reason, **pending_fields}, "safety_deny")],
                snap=snap, extra_ops=decision_ops,
            )
            await self._emit_dlq(req, resp.reason, "SAFETY_DENY", status=JobState.DENIED.value)
            return

        if decision == Decision.REQUIRE_APPROVAL.value:
            await self.job_store.apply_chain(
                req.job_id,
                [(JobState.APPROVAL_REQUIRED,
                  {"approval_reason": resp.reason,
                   "policy_snapshot": resp.policy_snapshot, **pending_fields},
                  "approval_required")],
                snap=snap, extra_ops=decision_ops,
            )
            return  # parked until an admin approves

        if decision == Decision.THROTTLE.value:
            delay = resp.throttle_delay_s or 1.0
            raise RetryAfter(delay, f"throttled: {resp.reason}")

        # The decision record carries the hash of the request *as
        # approved/checked*, before constraint injection mutates env
        # (otherwise the stored hash would never match a faithful
        # republish); the write itself rides the SCHEDULED commit.
        extra_ops = list(decision_ops)
        if decision == Decision.ALLOW_WITH_CONSTRAINTS.value and resp.constraints:
            self._apply_constraints(req, resp.constraints)

        limit = self._tenant_limit(req)
        if limit and req.tenant_id:
            active = await self.job_store.tenant_active_count(req.tenant_id)
            if active >= limit:
                # exponential NAK backoff with ±25% jitter per redelivery:
                # a tenant burst spreads out instead of resonating as a
                # synchronized retry storm (capped by MAX_NAK_DELAY_S)
                delay = min(MAX_NAK_DELAY_S,
                            TENANT_NAK_BASE_S * (2 ** max(0, redeliveries)))
                delay *= 1.0 + random.uniform(-0.25, 0.25)
                raise RetryAfter(
                    delay, f"tenant {req.tenant_id} at concurrency limit {limit}"
                )
        if req.tenant_id:
            extra_ops += self.job_store.tenant_active_add_ops(req.tenant_id, req.job_id)

        # deadline registration
        if req.budget and req.budget.deadline_unix_ms:
            extra_ops += self.job_store.register_deadline_ops(
                req.job_id, req.budget.deadline_unix_ms
            )

        # gang jobs depart here (docs/GANG.md): the gang scheduler owns
        # reservation, fan-out dispatch, and attempts accounting — a queued
        # gang leaves the job PENDING so the replayer keeps it alive
        if self.gangs is not None and gang_workers(req.labels) > 0:
            await self.gangs.on_submit(
                req, extra_ops=extra_ops, pending_fields=pending_fields,
                trace_id=trace_id, parent_span_id=parent_span_id,
            )
            return

        # dispatch-attempts guard: counted only for real dispatch attempts so
        # backpressure redeliveries (throttle / tenant concurrency) don't burn
        # the budget of a job that merely waited
        attempts = int(snap.get("attempts", "0") or "0") + 1
        pending_fields["attempts"] = str(attempts)
        if attempts > self.max_attempts:
            await self._fail_to_dlq(
                req, "max attempts exceeded", "MAX_RETRIES",
                fields=pending_fields, snap=snap,
            )
            return

        # pick subject and dispatch
        async with self._spanctx(
            "strategy", trace_id, parent_span_id, {"job_id": req.job_id}
        ) as stsp:
            target = self.strategy.pick_subject(req)
            stsp.attrs["target"] = target
        async with self._spanctx(
            "dispatch", trace_id, parent_span_id,
            {"job_id": req.job_id, "target": target},
        ) as dsp:
            # ONE pipelined commit: →SCHEDULED + decision record + tenant
            # membership + deadline + attempts/config fields (was 6-9
            # round trips of separate writes)
            _, snap = await self.job_store.apply_chain(
                req.job_id,
                [(JobState.SCHEDULED,
                  {"dispatch_subject": target, **pending_fields}, "scheduled")],
                snap=snap, extra_ops=extra_ops,
            )
            self._stamp_partition(req)
            out = BusPacket.wrap(
                req, trace_id=trace_id, sender_id=self.instance_id,
                span_id=dsp.span_id, parent_span_id=dsp.parent_span_id,
            )
            # Overlap the load-bearing dispatch publish with the
            # non-load-bearing DISPATCHED→RUNNING bookkeeping commit (one
            # pipelined chain).  If the publish fails the chain may still
            # land, leaving the job RUNNING-but-undelivered; the replayer's
            # result-replay nudge recovers it, and the publish error still
            # propagates for bus-level redelivery.
            results = await asyncio.gather(
                self.bus.publish(target, out),
                self.job_store.apply_chain(
                    req.job_id,
                    [(JobState.DISPATCHED, None, "dispatched"),
                     (JobState.RUNNING, None, "running")],
                    snap=snap,
                ),
                return_exceptions=True,
            )
            for r in results:
                if isinstance(r, BaseException):
                    raise r
            self._cache_snap(req.job_id, results[1][1])
        self.metrics.jobs_dispatched.inc(topic=req.topic)
        self.metrics.shard_scheduled.inc(shard=self._shard_label)
        sub_us = int(snap.get("submitted_at_us", "0") or 0)
        if sub_us:
            self.metrics.dispatch_latency.observe(max(0.0, (now_us() - sub_us) / 1e6))

    def _stamp_partition(self, req: JobRequest) -> None:
        """Stamp this shard's partition on the outbound request so the
        worker can publish the result straight to ``sys.job.result.<p>``
        (skipping the unstamped-result forwarding hop).  Rebound to
        :meth:`_stamp_noop` at construction when ``shard_count == 1``."""
        req.labels = dict(req.labels or {})
        req.labels[LABEL_PARTITION] = self._shard_label

    @staticmethod
    def _stamp_noop(req: JobRequest) -> None:
        return None

    # ------------------------------------------------------------------
    async def redispatch_scheduled(self, job_id: str) -> bool:
        """Re-publish a job wedged in SCHEDULED (crash/bus blip between
        set_state(SCHEDULED) and the dispatch publish).  Safety was already
        checked on the original pass; this only repeats the dispatch leg —
        with the attempts guard, so a persistently failing publish still
        lands in the DLQ instead of looping forever.  Driven by the
        PendingReplayer; returns True if the job moved."""
        if not await self.job_store.acquire_job_lock(job_id, self.instance_id, ttl_s=30.0):
            return False
        try:
            snap = await self.job_store.watch_meta(job_id)
            if snap.state != JobState.SCHEDULED.value:
                return False  # moved on concurrently
            req = await self.job_store.get_request(job_id)
            if req is None:
                return False
            attempts = int(snap.get("attempts", "0") or "0") + 1
            if attempts > self.max_attempts:
                await self._fail_to_dlq(
                    req, "max attempts exceeded", "MAX_RETRIES",
                    fields={"attempts": str(attempts)}, snap=snap,
                )
                return True
            target = self.strategy.pick_subject(req)
            # attempts must land BEFORE the publish: a persistently failing
            # publish still burns its budget and reaches the DLQ instead of
            # looping forever (idempotent fields-only commit keeps the
            # snapshot current for the chain below)
            _, snap = await self.job_store.apply_chain(
                job_id,
                [(JobState.SCHEDULED, {"attempts": str(attempts)}, "")],
                snap=snap,
            )
            # fresh bus msg-id label: the redispatch must survive the dedupe
            # window even if the original publish reached the bus
            req.labels = dict(req.labels or {})
            req.labels["cordum.bus_msg_id"] = f"redispatch-{job_id}-{attempts}"
            self._stamp_partition(req)
            out = BusPacket.wrap(req, trace_id=snap.get("trace_id", ""),
                                 sender_id=self.instance_id)
            await self.bus.publish(target, out)
            await self.job_store.apply_chain(
                job_id,
                [(JobState.DISPATCHED,
                  {"dispatch_subject": target}, "redispatched"),
                 (JobState.RUNNING, None, "running")],
                snap=snap,
            )
            self.metrics.jobs_dispatched.inc(topic=req.topic)
            return True
        finally:
            await self.job_store.release_job_lock(job_id, self.instance_id)

    async def nudge_inflight(self, job_id: str) -> bool:
        """Re-deliver a job wedged in DISPATCHED/RUNNING to its recorded
        dispatch subject.  The worker side is idempotent — an in-flight
        redelivery is dropped, a completed job republishes its cached
        result — so this acts as a result-replay request: it recovers jobs
        whose dispatch packet or terminal result was lost to a statebus
        failover window (pub/sub pushes are not replicated), without
        re-running work or transitioning state.  Driven by the
        PendingReplayer past ``Timeouts.result_replay_s``."""
        snap = await self.job_store.watch_meta(job_id)
        if snap.state not in (JobState.DISPATCHED.value, JobState.RUNNING.value):
            return False
        req = await self.job_store.get_request(job_id)
        if req is None:
            return False
        target = snap.get("dispatch_subject", "") or self.strategy.pick_subject(req)
        # fresh bus msg-id: the redelivery must survive the dedupe window
        req.labels = dict(req.labels or {})
        req.labels["cordum.bus_msg_id"] = f"nudge-{job_id}-{now_us()}"
        self._stamp_partition(req)
        await self.bus.publish(
            target,
            BusPacket.wrap(req, trace_id=snap.get("trace_id", ""),
                           sender_id=self.instance_id),
        )
        self.metrics.inflight_nudges.inc()
        return True

    async def failover_job(
        self, job_id: str, *, reason: str = "worker_dead",
        count_attempt: bool = True,
    ) -> bool:
        """Re-dispatch an in-flight job to a NEW worker after its old one
        died or handed it back (``SESSION_REQUEUE``) — the serving-session
        crash-failover leg (docs/SERVING.md §Migration, drain, and
        failover).  Differences from :meth:`nudge_inflight`: the strategy
        picks a FRESH target (the dead worker's affinity entries are
        evicted first), the attempt counts against the job's budget (past
        the cap it fails to the DLQ), and any tokens the dead worker
        already streamed ride along as the forced-decode resume prefix so
        the client's stream resumes with no duplicated or missing tokens.
        State stays DISPATCHED/RUNNING throughout — legal, since the job
        really is still in flight."""
        if not await self.job_store.acquire_job_lock(job_id, self.instance_id, ttl_s=30.0):
            return False
        try:
            snap = await self.job_store.watch_meta(job_id)
            if snap.state not in (JobState.DISPATCHED.value, JobState.RUNNING.value):
                return False  # finished (or was cancelled) concurrently
            req = await self.job_store.get_request(job_id)
            if req is None:
                return False
            # preemption re-dispatches are attempts-exempt: yielding to
            # interactive pressure is the control plane's choice, not the
            # job's failure, so it must never burn the job toward the DLQ
            attempts = int(snap.get("attempts", "0") or "0") + (
                1 if count_attempt else 0
            )
            if attempts > self.max_attempts:
                self._stream_tokens.pop(job_id, None)
                await self._fail_to_dlq(
                    req, f"failover attempts exhausted ({reason})",
                    "MAX_RETRIES", fields={"attempts": str(attempts)},
                    snap=snap,
                )
                return True
            req.labels = dict(req.labels or {})
            streamed = self._stream_tokens.get(job_id)
            if streamed:
                # the forced-decode prefix: the new worker prefills
                # prompt + prefix, replays it at offset 0 (consumers
                # dedupe), and generates only the remainder.  NOT persisted
                # onto the stored request — the prefix is routing state,
                # and mutating the blob would break approval hash checks.
                req.labels[LABEL_RESUME_TOKENS] = ",".join(
                    str(t) for t in streamed
                )
            target = self.strategy.pick_subject(req)
            # attempts + the new dispatch subject land BEFORE the publish
            # (idempotent same-state fields commit), so a crash loop still
            # burns its budget and the replayer nudges the right worker
            _, snap = await self.job_store.apply_chain(
                job_id,
                [(JobState(snap.state),
                  {"attempts": str(attempts), "dispatch_subject": target},
                  "")],
                snap=snap,
            )
            req.labels["cordum.bus_msg_id"] = f"failover-{job_id}-{attempts}"
            self._stamp_partition(req)
            await self.bus.publish(
                target,
                BusPacket.wrap(req, trace_id=snap.get("trace_id", ""),
                               sender_id=self.instance_id),
            )
            await self.job_store.append_event(
                job_id, "failover", reason=reason, target=target,
                attempts=attempts, resumed_tokens=len(streamed or ()),
            )
            self.metrics.session_failovers.inc(reason=reason)
            self.metrics.jobs_dispatched.inc(topic=req.topic)
            logx.info("job failed over", job_id=job_id, reason=reason,
                      target=target, attempts=attempts,
                      resumed_tokens=len(streamed or ()))
            return True
        finally:
            await self.job_store.release_job_lock(job_id, self.instance_id)

    # ------------------------------------------------------------------
    async def _check_safety(self, req: JobRequest):
        """Approval-granted fast path with hash binding, else kernel check."""
        from ...protocol.types import PolicyCheckResponse

        labels = req.labels or {}
        if labels.get(LABEL_APPROVAL_GRANTED) == "true":
            stored = await self.job_store.get_safety_decision(req.job_id)
            if stored is not None and stored.job_hash and stored.job_hash == job_hash(req):
                constraints = (
                    Constraints.from_dict(stored.constraints) if stored.constraints else None
                )
                return PolicyCheckResponse(
                    decision=(
                        Decision.ALLOW_WITH_CONSTRAINTS.value
                        if constraints
                        else Decision.ALLOW.value
                    ),
                    reason="approval granted (hash verified)",
                    policy_snapshot=stored.policy_snapshot,
                    constraints=constraints,
                )
            # hash mismatch: the job content changed since approval → re-check
            logx.warn("approval hash mismatch; re-checking", job_id=req.job_id)

        check = PolicyCheckRequest(
            job_id=req.job_id,
            tenant_id=req.tenant_id,
            principal_id=req.principal_id,
            topic=req.topic,
            labels=dict(labels),
            metadata=req.metadata,
            actor_id=req.principal_id,
        )
        eff = (req.env or {}).get(ENV_EFFECTIVE_CONFIG)
        if eff:
            parsed = codec.loads_env_json(eff)
            if isinstance(parsed, dict):
                check.effective_config = parsed
        self.metrics.policy_evals.inc()
        return await self.safety.check(check)

    def _decision_record(self, req: JobRequest, resp) -> SafetyDecisionRecord:
        return SafetyDecisionRecord(
            job_id=req.job_id,
            decision=resp.decision,
            reason=resp.reason,
            rule_id=resp.rule_id,
            policy_snapshot=resp.policy_snapshot,
            job_hash=job_hash(req),
            constraints=resp.constraints.to_dict() if resp.constraints else None,
            remediations=[r.to_dict() for r in resp.remediations],
        )

    def _apply_constraints(self, req: JobRequest, c: Constraints) -> None:
        req.env = dict(req.env or {})
        # the env contract stays JSON (non-Python workers parse it); the
        # codec module owns contract-JSON under CL007
        req.env[ENV_POLICY_CONSTRAINTS] = codec.dumps_env_json(
            c.to_dict(), sort_keys=True
        )
        if c.max_chips:
            req.env[ENV_MAX_CHIPS] = str(c.max_chips)
        for k, v in (c.env or {}).items():
            req.env[k] = v
        if c.max_tokens and req.budget is not None and (
            req.budget.max_tokens == 0 or req.budget.max_tokens > c.max_tokens
        ):
            req.budget.max_tokens = c.max_tokens
        if c.max_cost_usd and req.budget is not None and (
            req.budget.max_cost_usd == 0 or req.budget.max_cost_usd > c.max_cost_usd
        ):
            req.budget.max_cost_usd = c.max_cost_usd

    async def _attach_effective_config(self, req: JobRequest) -> str:
        """Injects the effective config into the request env and returns its
        hash; the caller folds the ``config_hash`` meta field into the next
        state-transition commit (no separate write round trip)."""
        if self.configsvc is None:
            return ""
        snap = await self.configsvc.effective_snapshot(
            org=req.tenant_id, workflow=req.workflow_id
        )
        req.env = dict(req.env or {})
        req.env[ENV_EFFECTIVE_CONFIG] = snap["config"]
        return str(snap["hash"])

    # ------------------------------------------------------------------
    async def _on_result(self, subject: str, pkt: BusPacket) -> None:
        res = pkt.job_result
        if res is None or not res.job_id:
            return
        if not self.owns(res.job_id):
            await self._forward_to_owner("result", res.job_id, subj.result_subject, pkt)
            return
        if self.batch_ticks and self._drain_task is not None:
            fut: asyncio.Future[None] = asyncio.get_running_loop().create_future()
            self._result_q.append(_ResultItem(res, pkt.trace_id, pkt.span_id, fut))
            self._submit_wake.set()
            await fut
            return
        async with self._sem:
            await self.handle_job_result(
                res, trace_id=pkt.trace_id, parent_span_id=pkt.span_id
            )

    async def handle_job_result(
        self, res: JobResult, *, trace_id: str = "", parent_span_id: str = ""
    ) -> None:
        # one snapshot read serves the terminal short-circuit, the
        # transition's optimistic first attempt, AND the e2e-latency meta
        snap = await self.job_store.watch_meta(res.job_id)
        if snap.state and snap.is_terminal:
            return  # already terminal: redelivery no-op
        try:
            state = JobState(res.status)
        except ValueError:
            state = JobState.FAILED
        if state not in TERMINAL_STATES:
            if res.error_code == ERROR_SESSION_REQUEUE:
                if res.error_message.startswith(PREEMPTED_REASON):
                    # preemption: the worker yielded the job to interactive
                    # pressure — count it, hold it off briefly, then
                    # re-dispatch attempts-exempt (never FAILED/CANCELLED)
                    self.metrics.preemptions.inc(reason="requeued")
                    self._schedule_preempt_redispatch(res.job_id)
                    return
                # a worker handed the job back (drain without a migration
                # target, crashed decode loop): re-dispatch it instead of
                # recording anything terminal — bounded by the attempts cap
                await self.failover_job(res.job_id, reason="requeue_requested")
                return
            # workers may send RUNNING status hints; record as event only
            await self.job_store.append_event(res.job_id, "result_hint", status=res.status)
            return
        async with self.tracer.span(
            "result",
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            attrs={"job_id": res.job_id, "status": state.value},
        ):
            await self._apply_terminal_result(res, state, snap)

    async def _apply_terminal_result(
        self, res: JobResult, state: JobState, snap: Optional[MetaSnapshot] = None
    ) -> None:
        fields = {
            "result_ptr": res.result_ptr,
            "worker_id": res.worker_id,
            "execution_ms": str(res.execution_ms),
        }
        if res.error_message:
            fields["error_message"] = res.error_message
            fields["error_code"] = res.error_code
        # one pipelined commit: terminal transition + result fields + event
        # (+ deadline clear + tenant-active removal, folded in by the
        # transition builder for terminal states)
        _, snap = await self.job_store.apply_chain(
            res.job_id, [(state, fields, "result")], snap=snap
        )
        self._stream_tokens.pop(res.job_id, None)
        self.metrics.jobs_completed.inc(status=state.value)
        # SLO class = the persisted submit-time priority (obs/slo.py reads
        # the class-labeled series fleet-wide)
        klass = snap.get("priority", "") or "BATCH"
        self.metrics.jobs_by_class.inc(job_class=klass, status=state.value)
        sub_us = int(snap.get("submitted_at_us", "0") or 0)
        if sub_us:
            self.metrics.e2e_latency.observe(
                max(0.0, (now_us() - sub_us) / 1e6),
                exemplar=snap.get("trace_id", ""), job_class=klass,
            )
        if state in (JobState.FAILED, JobState.TIMEOUT):
            req = await self.job_store.get_request(res.job_id)
            if req is not None:
                await self._emit_dlq(
                    req,
                    res.error_message or state.value,
                    res.error_code or state.value,
                    status=state.value,
                )

    # ------------------------------------------------------------------
    async def _fail_to_dlq(
        self, req: JobRequest, reason: str, code: str, *,
        fields: Optional[dict[str, str]] = None,
        snap: Optional[MetaSnapshot] = None,
    ) -> None:
        try:
            f = {"error_message": reason, **(fields or {})}
            await self.job_store.set_state(
                req.job_id, JobState.FAILED, fields=f, event="dlq", snap=snap
            )
        except Exception as e:  # noqa: BLE001 - job may already be terminal
            logx.warn("could not mark job FAILED before DLQ", job_id=req.job_id, err=str(e))
        await self._emit_dlq(req, reason, code, status=JobState.FAILED.value)

    async def _emit_dlq(self, req: JobRequest, reason: str, code: str, *, status: str) -> None:
        self.metrics.jobs_dlq.inc(topic=req.topic)
        res = JobResult(
            job_id=req.job_id,
            status=status,
            error_code=code,
            error_message=reason,
            labels={"topic": req.topic, "tenant_id": req.tenant_id},
        )
        await self.bus.publish(subj.DLQ, BusPacket.wrap(res, sender_id=self.instance_id))
