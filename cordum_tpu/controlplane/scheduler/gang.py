"""Gang scheduling: mesh-aware all-or-nothing placement for multi-chip
SPMD/MPMD jobs (docs/GANG.md, ROADMAP item 4).

Two pieces live here:

* :class:`DeviceLedger` — the scheduler-side chip/slice inventory.  It
  reads per-worker device telemetry straight from heartbeats (chip count,
  topology, device kind, pool — the keys ``config/pools.yaml`` declares)
  and performs **all-or-nothing reservation** of N co-located workers for
  a gang: either every member is reserved in one synchronous pass or
  nothing is touched — the PageAllocator's worst-case-admission pattern
  lifted from KV pages to devices.  Exhaustion parks the gang in a FIFO
  (no queue-jumping), so concurrent gangs queue instead of deadlocking
  half-reserved.

* :class:`GangScheduler` — the gang lifecycle driver next to the engine.
  A submit carrying ``cordum.gang_workers`` departs the single-worker
  dispatch path: the gang scheduler reserves members, fans the request out
  to each member's direct subject with rank/size/member labels, and
  listens on the gang's ``sys.job.gang.<gang_id>`` subject for rendezvous
  beacons, per-member completion reports (aggregated into ONE terminal
  job result), and aborts.  Failure semantics are first-class: any member
  failing, crashing (heartbeat expiry), or timing out at the rendezvous
  aborts the WHOLE gang — peers see the ``GangMsg(kind="abort")`` fan-out,
  every reserved device is released, and the job requeues attempts-bounded
  through the same FIFO.  A ``JobPreempt`` for a BATCH gang (the PR 13
  preemption governor) aborts-and-requeues the gang **as a unit**,
  attempts-exempt, after the standard jittered hold-off.
"""
from __future__ import annotations

import asyncio
import contextlib
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ...infra import logging as logx
from ...infra.config import Pool, PoolConfig
from ...infra.memstore import MemoryStore
from ...protocol import subjects as subj
from ...protocol.partition import partition_of
from ...protocol.types import (
    BusPacket,
    GangMsg,
    JobRequest,
    JobResult,
    JobState,
    LABEL_GANG_CHIPS,
    LABEL_GANG_ID,
    LABEL_GANG_MEMBERS,
    LABEL_GANG_RANK,
    LABEL_GANG_SIZE,
    TERMINAL_STATES,
    gang_chips,
    gang_kind,
    gang_workers,
)
from ...utils.ids import new_id, now_us
from .strategy import worker_satisfies

# default worker-side barrier timeout; the scheduler watchdog backstops at
# 2x so the member-side abort (which names the missing rank) usually wins
DEFAULT_RENDEZVOUS_TIMEOUT_S = 10.0
WATCH_INTERVAL_S = 0.25
# jittered hold-off before a preempted gang re-enters the FIFO (mirrors the
# engine's single-job PREEMPT_HOLDOFF_S)
PREEMPT_HOLDOFF_S = 1.0
RECENT_GANGS_KEPT = 32

GANG_QUEUED = "QUEUED"
GANG_RUNNING = "RUNNING"
GANG_DONE = "DONE"
GANG_ABORTED = "ABORTED"
GANG_FAILED = "FAILED"


def slice_key(hb) -> str:
    """The co-location group a worker belongs to: an explicit
    ``cordum.slice_id`` label when the deployment pins slices, else the
    (pool, region) pair — workers on one slice share ICI and can run one
    mesh."""
    explicit = (hb.labels or {}).get("cordum.slice_id", "")
    if explicit:
        return explicit
    return f"{hb.pool}|{hb.region}"


class DeviceLedger:
    """Per-worker device inventory + all-or-nothing gang reservations.

    Event-loop-confined (no internal locking): ``try_reserve`` finds the
    full member set *before* mutating any state, so a failed reservation
    touches nothing — the invariant :meth:`verify` (and the property test)
    asserts is that no gang ever holds a partial member set.
    """

    def __init__(self, registry, *, metrics=None) -> None:
        self.registry = registry
        self.metrics = metrics
        # worker_id -> gang_id holding it
        self._reserved: dict[str, str] = {}
        # gang_id -> (members, n_requested)
        self._gangs: dict[str, tuple[list[str], int]] = {}

    # ------------------------------------------------------------------
    @property
    def reserved_workers(self) -> dict[str, str]:
        return dict(self._reserved)

    def gang_members(self, gang_id: str) -> list[str]:
        ent = self._gangs.get(gang_id)
        return list(ent[0]) if ent else []

    def eligible_workers(
        self,
        *,
        pools: list[Pool],
        job_requires: list[str],
        chips: int = 0,
        exclude: tuple = (),
        include_reserved: bool = False,
    ) -> dict[str, list]:
        """Live candidate workers grouped by slice key.  A worker is a
        candidate when it serves one of the topic's pools, satisfies the
        pool's slice requirements AND the job's own ``requires``, owns at
        least ``chips`` chips, is healthy/not draining, and is not already
        reserved by another gang (``include_reserved=True`` ignores current
        reservations — the satisfiability probe: could this gang EVER fit
        on the live fleet?)."""
        groups: dict[str, list] = {}
        for hb in self.registry.snapshot().values():
            if hb.worker_id in exclude:
                continue
            if not include_reserved and hb.worker_id in self._reserved:
                continue
            if hb.draining or not hb.devices_healthy:
                continue
            pool = next((p for p in pools if p.name == hb.pool), None)
            if pools and pool is None:
                continue
            if not worker_satisfies(hb, pool, job_requires):
                continue
            if chips and hb.chip_count < chips:
                continue
            groups.setdefault(slice_key(hb), []).append(hb)
        return groups

    def try_reserve(
        self,
        gang_id: str,
        n_workers: int,
        *,
        pools: list[Pool],
        job_requires: list[str],
        chips: int = 0,
        exclude: tuple = (),
    ) -> Optional[list[str]]:
        """Reserve ``n_workers`` co-located workers for ``gang_id`` — all
        in one pass or none at all.  Returns the member list in rank order
        (least-loaded first) or None when no slice group can cover the
        gang."""
        if gang_id in self._gangs:
            return self.gang_members(gang_id)  # idempotent re-reserve
        groups = self.eligible_workers(
            pools=pools, job_requires=job_requires, chips=chips, exclude=exclude
        )
        best: Optional[list] = None
        for members in groups.values():
            if len(members) < n_workers:
                continue
            # best fit: the group with the least slack keeps big slices
            # free for bigger gangs; ties by name for determinism
            if best is None or len(members) < len(best):
                best = members
        if best is None:
            return None
        best.sort(key=lambda hb: (hb.active_jobs, hb.worker_id))
        chosen = [hb.worker_id for hb in best[:n_workers]]
        # the mutation happens only here, after the full set is known —
        # all-or-nothing by construction
        for wid in chosen:
            self._reserved[wid] = gang_id
        self._gangs[gang_id] = (chosen, n_workers)
        self._gauge()
        return chosen

    def release(self, gang_id: str) -> int:
        """Return every worker reserved by ``gang_id``; 0 for unknown gangs
        (release and abort can race benignly, like the page allocator)."""
        ent = self._gangs.pop(gang_id, None)
        if ent is None:
            return 0
        n = 0
        for wid in ent[0]:
            if self._reserved.get(wid) == gang_id:
                del self._reserved[wid]
                n += 1
        self._gauge()
        return n

    def verify(self) -> int:
        """Invariant check: every held gang owns exactly its full member
        set and every reservation back-links to its gang.  Returns the
        number of violations (MUST be 0) and counts them in
        ``cordum_gang_partial_reservations_total``."""
        bad = 0
        for gid, (members, n) in self._gangs.items():
            held = [w for w in members if self._reserved.get(w) == gid]
            if len(held) != n or len(members) != n:
                bad += 1
        for wid, gid in self._reserved.items():
            if wid not in (self._gangs.get(gid) or ((), 0))[0]:
                bad += 1
        if bad and self.metrics is not None:
            self.metrics.gang_partial_reservations.inc(amount=float(bad))
        return bad

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.gang_reserved_workers.set(float(len(self._reserved)))


@dataclass
class GangRecord:
    """One gang attempt (a requeue creates a fresh record, same job)."""

    gang_id: str
    job_id: str
    req: JobRequest
    trace_id: str = ""
    parent_span_id: str = ""
    n_workers: int = 1
    chips: int = 0
    kind: str = ""  # "" = training/SPMD default; "serving" = TP serving gang
    state: str = GANG_QUEUED
    members: list[str] = field(default_factory=list)
    ready: set = field(default_factory=set)
    done: dict[int, dict] = field(default_factory=dict)
    exclude: set = field(default_factory=set)
    count_attempt: bool = True
    created_at: float = field(default_factory=time.monotonic)
    dispatched_at: float = 0.0
    extra_ops: list = field(default_factory=list)
    pending_fields: dict[str, str] = field(default_factory=dict)
    reserve_span: Any = None
    abort_reason: str = ""

    @property
    def age_s(self) -> float:
        return time.monotonic() - self.created_at


class GangScheduler:
    """Drives gang jobs end-to-end next to the engine (docs/GANG.md):
    reserve → fan-out dispatch → collect rendezvous/done/abort → one
    terminal job result, with abort + attempts-bounded requeue on any
    member failure and unit-preemption under interactive pressure."""

    def __init__(
        self,
        engine,
        pool_config: PoolConfig,
        *,
        rendezvous_timeout_s: float = DEFAULT_RENDEZVOUS_TIMEOUT_S,
        watch_interval_s: float = WATCH_INTERVAL_S,
        queued_timeout_s: float = 300.0,
    ) -> None:
        self.engine = engine
        self.bus = engine.bus
        self.job_store = engine.job_store
        self.registry = engine.registry
        self.metrics = engine.metrics
        self.tracer = engine.tracer
        self.pool_config = pool_config
        self.rendezvous_timeout_s = rendezvous_timeout_s
        self.watch_interval_s = watch_interval_s
        self.queued_timeout_s = queued_timeout_s
        self.ledger = DeviceLedger(engine.registry, metrics=engine.metrics)
        self._mem = MemoryStore(engine.job_store.kv)
        self._fifo: deque[GangRecord] = deque()
        self._by_job: dict[str, GangRecord] = {}
        self._by_gang: dict[str, GangRecord] = {}
        self._recent: deque[GangRecord] = deque(maxlen=RECENT_GANGS_KEPT)
        self._holdoffs: set[asyncio.Task] = set()
        self._watch_task: Optional[asyncio.Task] = None
        self._subs: list = []
        # _pump single-flight: the watchdog, releases, and submits all
        # pump; overlapping passes could otherwise re-dispatch the same
        # head record around an await
        self._pumping = False
        self._pump_again = False
        engine.gangs = self

    # ------------------------------------------------------------------
    async def start(self) -> None:
        self._subs = [
            await self.bus.subscribe(subj.GANG_WILDCARD, self._on_gang_msg),
            await self.bus.subscribe(subj.PREEMPT, self._on_preempt),
        ]
        if self._watch_task is None:
            self._watch_task = asyncio.ensure_future(self._watch_loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        if self._watch_task is not None:
            self._watch_task.cancel()
            await logx.join_task(self._watch_task, name="gang-watchdog")
            self._watch_task = None
        for t in list(self._holdoffs):
            t.cancel()
            await logx.join_task(t, name="gang-holdoff")
        self._holdoffs.clear()

    def update_routing(self, pool_config: PoolConfig) -> None:
        self.pool_config = pool_config

    # ------------------------------------------------------------------
    # submit path (called from Engine._post_decision for gang-labeled jobs)
    # ------------------------------------------------------------------
    async def on_submit(
        self,
        req: JobRequest,
        *,
        extra_ops: Optional[list] = None,
        pending_fields: Optional[dict[str, str]] = None,
        trace_id: str = "",
        parent_span_id: str = "",
    ) -> None:
        """Admit a gang job: reserve-and-dispatch immediately when the FIFO
        is empty and devices cover it, else queue.  Idempotent under
        redelivery — a job with a live gang record is a no-op, so PENDING
        replays of a queued gang just keep it alive."""
        live = self._by_job.get(req.job_id)
        if live is not None and live.state in (GANG_QUEUED, GANG_RUNNING):
            return
        n = gang_workers(req.labels)
        if n < 1:
            raise ValueError(f"job {req.job_id} is not gang-labeled")
        rec = GangRecord(
            gang_id=new_id(),
            job_id=req.job_id,
            req=req,
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            n_workers=n,
            chips=gang_chips(req.labels),
            kind=gang_kind(req.labels),
            extra_ops=list(extra_ops or []),
            pending_fields=dict(pending_fields or {}),
        )
        rec.reserve_span = self.tracer.begin(
            "gang-reserve", trace_id=trace_id, parent_span_id=parent_span_id,
            attrs={"job_id": req.job_id, "gang_id": rec.gang_id,
                   "workers": str(n)},
        )
        self._enqueue(rec)
        await self._pump()
        if rec.state == GANG_QUEUED:
            self.metrics.gang_admissions.inc(outcome="queued")

    def _enqueue(self, rec: GangRecord) -> None:
        self._by_job[rec.job_id] = rec
        self._by_gang[rec.gang_id] = rec
        self._fifo.append(rec)
        self.metrics.gang_queue_depth.set(float(len(self._fifo)))

    def _pools_for(self, rec: GangRecord) -> list[Pool]:
        # follow the strategy's hot-reloaded pool config when present (the
        # ConfigOverlay swaps it atomically via update_routing)
        pc = getattr(self.engine.strategy, "_pool_config", None) or self.pool_config
        return pc.pools_for_topic(rec.req.topic)

    def _requires_for(self, rec: GangRecord) -> list[str]:
        return list(rec.req.metadata.requires) if rec.req.metadata else []

    def _satisfiable(self, rec: GangRecord) -> bool:
        """Could this gang EVER fit on the live fleet (ignoring transient
        reservations, honoring its exclusions)?"""
        groups = self.ledger.eligible_workers(
            pools=self._pools_for(rec), job_requires=self._requires_for(rec),
            chips=rec.chips, exclude=tuple(rec.exclude),
            include_reserved=True,
        )
        return any(len(g) >= rec.n_workers for g in groups.values())

    async def _pump(self) -> None:
        """Admit queued gangs in FIFO order.  A *satisfiable* head that
        cannot reserve yet blocks the line (no overtake — a stream of small
        gangs must not starve a big one); an UNsatisfiable gang first drops
        its exclusions (a transiently-failed worker must not wedge a small
        fleet), then — still unplaceable — is skipped so it cannot block
        the line, and fails to the DLQ past ``queued_timeout_s``.

        Single-flight: concurrent pump requests (watchdog tick, a release,
        a submit) coalesce into one pass + one re-run — overlapping passes
        could otherwise double-dispatch the record they both saw queued."""
        if self._pumping:
            self._pump_again = True
            return
        self._pumping = True
        try:
            await self._pump_locked()
            while self._pump_again:
                self._pump_again = False
                await self._pump_locked()
        finally:
            self._pumping = False

    async def _pump_locked(self) -> None:
        progressed = True
        while progressed:
            progressed = False
            for rec in list(self._fifo):
                if rec.state != GANG_QUEUED:
                    with contextlib.suppress(ValueError):
                        self._fifo.remove(rec)
                    continue
                members = self.ledger.try_reserve(
                    rec.gang_id, rec.n_workers,
                    pools=self._pools_for(rec),
                    job_requires=self._requires_for(rec),
                    chips=rec.chips,
                    exclude=tuple(rec.exclude),
                )
                if members is not None:
                    with contextlib.suppress(ValueError):
                        self._fifo.remove(rec)
                    try:
                        await self._dispatch(rec, members)
                    except Exception as e:  # noqa: BLE001 - one gang must not wedge the queue
                        logx.error("gang dispatch failed", gang_id=rec.gang_id,
                                   job_id=rec.job_id, err=str(e))
                        self.ledger.release(rec.gang_id)
                        self._finish_record(rec, GANG_ABORTED,
                                            reason="dispatch_error")
                    progressed = True
                    break
                if self._satisfiable(rec):
                    break  # head-of-line: wait for devices, no overtake
                if rec.exclude:
                    # the exclusions made it unplaceable on this fleet:
                    # forgive them (the excluded workers may be fine) and
                    # retry — the attempts budget still bounds the cycle
                    logx.warn("gang unplaceable with exclusions; clearing",
                              gang_id=rec.gang_id, job_id=rec.job_id,
                              excluded=",".join(sorted(rec.exclude)))
                    rec.exclude.clear()
                    progressed = True
                    break
                if rec.age_s > self.queued_timeout_s:
                    await self._fail_unplaceable(rec)
                    progressed = True
                    break
                # unsatisfiable but young: let later gangs overtake it
                continue
        self.metrics.gang_queue_depth.set(float(len(self._fifo)))

    async def _fail_unplaceable(self, rec: GangRecord) -> None:
        snap = await self.job_store.watch_meta(rec.job_id)
        self._finish_record(rec, GANG_FAILED, reason="unplaceable")
        self.metrics.gang_completed.inc(status="failed")
        await self.engine._fail_to_dlq(
            rec.req,
            f"gang unplaceable: no slice with {rec.n_workers} eligible "
            f"workers within {self.queued_timeout_s:.0f}s",
            "GANG_UNPLACEABLE", snap=snap,
        )

    async def _dispatch(self, rec: GangRecord, members: list[str]) -> None:
        """Fan the job out to every reserved member with rank/size labels;
        one SCHEDULED→DISPATCHED→RUNNING chain covers the whole gang."""
        snap = await self.job_store.watch_meta(rec.job_id)
        st = snap.state
        if snap.is_terminal:
            # cancelled/finished while queued: nothing to run
            self.ledger.release(rec.gang_id)
            self._finish_record(rec, GANG_ABORTED, reason="terminal_before_dispatch")
            return
        attempts = int(snap.get("attempts", "0") or "0") + (
            1 if rec.count_attempt else 0
        )
        if attempts > self.engine.max_attempts:
            self.ledger.release(rec.gang_id)
            self._finish_record(rec, GANG_FAILED, reason="max_attempts")
            self.metrics.gang_completed.inc(status="failed")
            await self.engine._fail_to_dlq(
                rec.req, "gang failover attempts exhausted", "MAX_RETRIES",
                fields={"attempts": str(attempts)}, snap=snap,
            )
            return
        fields = {
            "dispatch_subject": subj.gang_subject(rec.gang_id),
            "gang_id": rec.gang_id,
            "gang_members": ",".join(members),
            "attempts": str(attempts),
            **rec.pending_fields,
        }
        if st in (JobState.DISPATCHED.value, JobState.RUNNING.value):
            # requeued gang: the job is legally still in flight — a
            # same-state fields commit retargets it (failover_job's shape;
            # same-state steps don't auto-append, so the audit event is
            # explicit)
            await self.job_store.apply_chain(
                rec.job_id, [(JobState(st), fields, "")], snap=snap,
            )
            await self.job_store.append_event(
                rec.job_id, "gang_redispatched", gang_id=rec.gang_id,
                members=",".join(members), attempts=attempts,
            )
        else:
            await self.job_store.apply_chain(
                rec.job_id,
                [(JobState.SCHEDULED, fields, "gang_scheduled"),
                 (JobState.DISPATCHED, None, "dispatched"),
                 (JobState.RUNNING, None, "running")],
                snap=snap, extra_ops=list(rec.extra_ops),
            )
            rec.extra_ops = []  # committed once; requeues must not re-add
        rec.members = members
        rec.state = GANG_RUNNING
        rec.dispatched_at = time.monotonic()
        self.metrics.gang_admissions.inc(outcome="reserved")
        self.metrics.gang_size.observe(float(len(members)))
        if rec.reserve_span is not None:
            rec.reserve_span.attrs["members"] = ",".join(members)
            rec.reserve_span.attrs["queued_ms"] = str(
                round(1000 * rec.age_s, 1))
            await self.tracer.finish(rec.reserve_span)
            rec.reserve_span = None
        dsp = self.tracer.begin(
            "gang-dispatch", trace_id=rec.trace_id,
            parent_span_id=rec.parent_span_id,
            attrs={"job_id": rec.job_id, "gang_id": rec.gang_id,
                   "members": ",".join(members)},
        )
        pubs = []
        for rank, wid in enumerate(members):
            member_req = JobRequest.from_dict(rec.req.to_dict())
            member_req.labels = dict(member_req.labels or {})
            member_req.labels[LABEL_GANG_ID] = rec.gang_id
            member_req.labels[LABEL_GANG_RANK] = str(rank)
            member_req.labels[LABEL_GANG_SIZE] = str(len(members))
            member_req.labels[LABEL_GANG_MEMBERS] = ",".join(members)
            # each member packet must survive the dedupe window on its own
            member_req.labels["cordum.bus_msg_id"] = (
                f"gang-{rec.gang_id}-{rank}-{attempts}"
            )
            self.engine._stamp_partition(member_req)
            pubs.append(self.bus.publish(
                subj.direct_subject(wid),
                BusPacket.wrap(
                    member_req, trace_id=rec.trace_id,
                    sender_id=self.engine.instance_id,
                    span_id=dsp.span_id, parent_span_id=dsp.parent_span_id,
                ),
            ))
        results = await asyncio.gather(*pubs, return_exceptions=True)
        await self.tracer.finish(dsp)
        failed = [members[i] for i, r in enumerate(results)
                  if isinstance(r, BaseException)]
        if failed:
            # an undeliverable member is a failed gang start: abort now so
            # peers don't burn the rendezvous timeout
            await self.abort_gang(rec, reason="dispatch_publish_failed",
                                  exclude=set(failed))
            return
        self.metrics.jobs_dispatched.inc(topic=rec.req.topic)
        logx.info("gang dispatched", gang_id=rec.gang_id, job_id=rec.job_id,
                  members=",".join(members), attempts=attempts)

    # ------------------------------------------------------------------
    # gang subject traffic
    # ------------------------------------------------------------------
    async def _on_gang_msg(self, subject: str, pkt: BusPacket) -> None:
        msg = pkt.gang_msg
        if msg is None or not msg.gang_id:
            return
        rec = self._by_gang.get(msg.gang_id)
        if rec is None or rec.state != GANG_RUNNING:
            return
        if not self.engine.owns(rec.job_id):
            return
        if msg.kind == "ready":
            rec.ready.add(msg.rank)
        elif msg.kind == "abort" and msg.worker_id:
            # member-originated abort (scheduler-originated aborts carry no
            # worker_id and were already handled locally).  Exclusions for
            # the requeue depend on who is actually at fault:
            #   member_failed:* — the REPORTER failed; exclude it
            #   rendezvous_timeout:* — the reporter is healthy; exclude the
            #     members that never beaconed ready
            #   peer_timeout:* / other — unknown culprit; the watchdog's
            #     dead-worker pass names it if it is really gone
            reason = msg.reason or "member_failed"
            exclude: set = set()
            if reason.startswith("member_failed"):
                exclude = {msg.worker_id}
            elif reason.startswith("rendezvous_timeout"):
                exclude = {
                    w for r, w in enumerate(rec.members) if r not in rec.ready
                }
            await self.abort_gang(rec, reason=reason, exclude=exclude)
        elif msg.kind == "done":
            rec.done[msg.rank] = dict(msg.stats or {})
            if len(rec.done) >= rec.n_workers and rec.state == GANG_RUNNING:
                await self._complete(rec)

    async def _complete(self, rec: GangRecord) -> None:
        rec.state = GANG_DONE
        self.ledger.release(rec.gang_id)
        await self._emit_release_span(rec, "done")
        per_rank = {str(r): rec.done[r] for r in sorted(rec.done)}
        last = rec.done.get(rec.n_workers - 1, {})
        doc = {
            "gang_id": rec.gang_id,
            "workers": rec.members,
            "per_rank": per_rank,
            # the headline numbers come from the last rank (the loss-owning
            # stage under MPMD; identical across ranks under SPMD)
            "loss": last.get("loss", last.get("final_loss")),
            "steps_done": last.get("steps_done"),
            "mesh": last.get("mesh"),
            "mode": last.get("mode", "spmd"),
        }
        if rec.kind == "serving":
            # serving gangs headline from rank 0 — the leader alone samples,
            # streams, and counts sessions/tokens (followers only replay)
            lead = rec.done.get(0, {})
            doc.update({
                "kind": "serving",
                "mode": lead.get("mode", "serving"),
                "sessions": lead.get("sessions"),
                "tokens": lead.get("tokens"),
                "tokens_per_s": lead.get("tokens_per_s"),
                "steps_done": lead.get("steps"),
            })
        ptr = await self._mem.put_result(rec.job_id, doc)
        res = JobResult(
            job_id=rec.job_id,
            status=JobState.SUCCEEDED.value,
            result_ptr=ptr,
            worker_id=f"gang:{rec.gang_id}",
            execution_ms=int(1000 * (time.monotonic() - rec.dispatched_at)),
            labels={"cordum.bus_msg_id": f"gang-result-{rec.gang_id}"},
        )
        await self.bus.publish(
            subj.result_subject(
                partition_of(rec.job_id, self.engine.shard_count),
                self.engine.shard_count,
            ),
            BusPacket.wrap(res, trace_id=rec.trace_id,
                           sender_id=self.engine.instance_id),
        )
        self.metrics.gang_completed.inc(status="succeeded")
        self._finish_record(rec, GANG_DONE)
        await self._pump()

    # ------------------------------------------------------------------
    # failure semantics: abort + attempts-bounded requeue
    # ------------------------------------------------------------------
    async def abort_gang(
        self,
        rec: GangRecord,
        *,
        reason: str,
        exclude: Optional[set] = None,
        requeue: bool = True,
        count_attempt: bool = True,
        holdoff_s: float = 0.0,
    ) -> bool:
        """Abort a RUNNING gang: broadcast the abort so every member stops
        between steps, release the full reservation, and (by default)
        requeue the job through the FIFO for a fresh attempt that excludes
        the failed workers.  Idempotent — concurrent abort causes (member
        report + watchdog) collapse into one."""
        if rec.state != GANG_RUNNING:
            return False
        rec.state = GANG_ABORTED
        rec.abort_reason = reason
        # metric label = the reason family only (the full reason carries
        # rank/exception detail — unbounded label cardinality)
        self.metrics.gang_aborts.inc(reason=reason.split(":", 1)[0])
        self.ledger.release(rec.gang_id)
        await self._emit_release_span(rec, reason)
        with contextlib.suppress(Exception):
            await self.bus.publish(
                subj.gang_subject(rec.gang_id),
                BusPacket.wrap(
                    GangMsg(gang_id=rec.gang_id, job_id=rec.job_id,
                            kind="abort", reason=reason),
                    trace_id=rec.trace_id, sender_id=self.engine.instance_id,
                ),
            )
        logx.warn("gang aborted", gang_id=rec.gang_id, job_id=rec.job_id,
                  reason=reason, requeue=requeue)
        self._finish_record(rec, GANG_ABORTED, reason=reason)
        if requeue:
            nxt = GangRecord(
                gang_id=new_id(),
                job_id=rec.job_id,
                req=rec.req,
                trace_id=rec.trace_id,
                parent_span_id=rec.parent_span_id,
                n_workers=rec.n_workers,
                chips=rec.chips,
                kind=rec.kind,
                exclude=set(rec.exclude) | set(exclude or ()),
                count_attempt=count_attempt,
                pending_fields=dict(rec.pending_fields),
            )
            nxt.reserve_span = self.tracer.begin(
                "gang-reserve", trace_id=rec.trace_id,
                parent_span_id=rec.parent_span_id,
                attrs={"job_id": rec.job_id, "gang_id": nxt.gang_id,
                       "workers": str(rec.n_workers), "requeue": reason},
            )
            if holdoff_s > 0:
                t = asyncio.ensure_future(self._requeue_later(nxt, holdoff_s))
                self._holdoffs.add(t)
                t.add_done_callback(self._holdoffs.discard)
            else:
                self._enqueue(nxt)
                await self._pump()
        await self._pump()
        return True

    async def _requeue_later(self, rec: GangRecord, holdoff_s: float) -> None:
        await asyncio.sleep(holdoff_s * (1.0 + random.uniform(-0.5, 0.5)))
        self._enqueue(rec)
        await self._pump()

    async def _emit_release_span(self, rec: GangRecord, reason: str) -> None:
        t0 = now_us()
        sp = self.tracer.begin(
            "gang-release", trace_id=rec.trace_id,
            parent_span_id=rec.parent_span_id,
            attrs={"job_id": rec.job_id, "gang_id": rec.gang_id,
                   "reason": reason},
        )
        sp.start_us = t0
        await self.tracer.finish(sp)
        if rec.reserve_span is not None:
            rec.reserve_span.attrs["abandoned"] = reason
            await self.tracer.finish(rec.reserve_span, status="ERROR")
            rec.reserve_span = None

    def _finish_record(self, rec: GangRecord, state: str, *, reason: str = "") -> None:
        rec.state = state
        if reason:
            rec.abort_reason = rec.abort_reason or reason
        if self._by_job.get(rec.job_id) is rec:
            del self._by_job[rec.job_id]
        self._by_gang.pop(rec.gang_id, None)
        with contextlib.suppress(ValueError):
            self._fifo.remove(rec)
        self.metrics.gang_queue_depth.set(float(len(self._fifo)))
        self._recent.append(rec)

    # ------------------------------------------------------------------
    # external hooks (engine cancel path, preemption governor)
    # ------------------------------------------------------------------
    async def on_cancel(self, job_id: str) -> None:
        rec = self._by_job.get(job_id)
        if rec is None:
            return
        if rec.state == GANG_QUEUED:
            if rec.reserve_span is not None:
                await self.tracer.finish(rec.reserve_span, status="ERROR")
                rec.reserve_span = None
            self._finish_record(rec, GANG_ABORTED, reason="cancelled")
        elif rec.state == GANG_RUNNING:
            await self.abort_gang(rec, reason="cancelled", requeue=False)

    async def _on_preempt(self, subject: str, pkt: BusPacket) -> None:
        """Unit preemption (docs/ADMISSION.md): a BATCH gang yields under
        interactive pressure as a whole — abort, release every device,
        requeue attempts-exempt after the jittered hold-off."""
        p = pkt.job_preempt
        if p is None or not p.job_id:
            return
        rec = self._by_job.get(p.job_id)
        if rec is None or rec.state != GANG_RUNNING:
            return
        if (rec.req.priority or "BATCH") != "BATCH":
            return
        await self.abort_gang(
            rec, reason="preempted", count_attempt=False,
            holdoff_s=PREEMPT_HOLDOFF_S,
        )
        self.metrics.preemptions.inc(reason="requeued")

    # ------------------------------------------------------------------
    # watchdog: dead members, rendezvous timeouts, FIFO pump, invariant
    # ------------------------------------------------------------------
    async def _watch_loop(self) -> None:
        while True:
            await asyncio.sleep(self.watch_interval_s)
            try:
                await self._watch_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - the watchdog must survive
                logx.error("gang watchdog error", err=str(e))

    async def _watch_once(self) -> None:
        live = self.registry.snapshot()
        now = time.monotonic()
        for rec in list(self._by_gang.values()):
            if rec.state != GANG_RUNNING:
                continue
            dead = [w for w in rec.members
                    if w not in live or live[w].draining]
            if dead:
                await self.abort_gang(rec, reason="worker_dead",
                                      exclude=set(dead))
                continue
            if (
                len(rec.ready) < rec.n_workers
                and now - rec.dispatched_at > 2 * self.rendezvous_timeout_s
            ):
                # scheduler-side backstop: the member-side barrier timeout
                # should have fired first; this recovers members that never
                # even received the dispatch
                await self.abort_gang(rec, reason="rendezvous_timeout")
        self.ledger.verify()
        await self._pump()

    # ------------------------------------------------------------------
    # observability (GET /api/v1/gangs, cordumctl gangs)
    # ------------------------------------------------------------------
    def doc(self) -> list[dict]:
        """Live gang table (+ a short tail of finished gangs), newest
        first — beaconed in the scheduler's telemetry health block and
        merged by the gateway's FleetAggregator."""
        out = []
        seen = set()
        for rec in [*self._by_gang.values(), *reversed(self._recent)]:
            if rec.gang_id in seen:
                continue
            seen.add(rec.gang_id)
            out.append({
                "gang_id": rec.gang_id,
                "job_id": rec.job_id,
                "state": rec.state,
                "kind": rec.kind or "spmd",
                "workers": rec.n_workers,
                "chips_per_worker": rec.chips,
                "members": list(rec.members),
                "ready": len(rec.ready),
                "done": len(rec.done),
                "age_s": round(rec.age_s, 2),
                "reason": rec.abort_reason,
            })
        return out


def render_gang_table(doc: dict) -> str:
    """ASCII gang table for ``cordumctl gangs`` from a /api/v1/gangs doc
    (matches the ``cordumctl capacity`` render style)."""
    rows = doc.get("gangs") or []
    header = f"{'GANG':<14} {'JOB':<14} {'STATE':<9} {'KIND':<8} " \
             f"{'WORKERS':>7} {'READY':>5} {'DONE':>4} {'AGE_S':>7}  MEMBERS"
    lines = [header, "-" * len(header)]
    for g in rows:
        lines.append(
            f"{str(g.get('gang_id', ''))[:12]:<14} "
            f"{str(g.get('job_id', ''))[:12]:<14} "
            f"{str(g.get('state', '')):<9} "
            f"{str(g.get('kind', '') or 'spmd'):<8} "
            f"{g.get('workers', 0):>7} "
            f"{g.get('ready', 0):>5} "
            f"{g.get('done', 0):>4} "
            f"{g.get('age_s', 0.0):>7.1f}  "
            f"{','.join(g.get('members') or [])}"
        )
    if not rows:
        lines.append("(no gangs)")
    queued = doc.get("queue_depth")
    if queued is not None:
        lines.append(f"queued: {queued}")
    return "\n".join(lines)
