"""Packed-array worker selection backed by the native C scan.

Maintains parallel ctypes arrays rebuilt whenever the registry version
changes (heartbeats mutate it ~every 10s per worker; dispatches happen
thousands of times a second — the pack cost amortizes across dispatches).
Capabilities are interned to bits (≤64 distinct), pools and topologies to
integer ids.  Falls back to the Python scan for shapes the C kernel doesn't
model (placement labels, per-pool device_kind / divergent pool
requirements).
"""
from __future__ import annotations

import ctypes
import time
from typing import Optional

from ...infra.registry import WorkerRegistry
from ...native import load_strategy_scan
from .strategy import HBM_OVERLOAD_FRACTION, _parse_tpu_requires

REBUILD_INTERVAL_S = 1.0  # also time-bounded: TTL-expired workers must drop
                          # from the pack even when no heartbeat mutates the
                          # registry version (dead-worker case)


class PackedWorkers:
    def __init__(self, registry: WorkerRegistry):
        self.registry = registry
        self._built_version = -1
        self._built_at = 0.0
        self._degenerate = False  # >64 distinct capabilities → python only
        self._lib = load_strategy_scan()
        self._cap_ids: dict[str, int] = {}
        self._pool_ids: dict[str, int] = {"": 0}
        self._topo_ids: dict[str, int] = {"": 0}
        self.worker_ids: list[str] = []
        self.n = 0
        # bumped whenever an interning table grows: callers caching resolved
        # routes (strategy _native_routes) key their entries on this so a
        # newly appearing pool/capability invalidates stale resolutions
        self.intern_gen = 0

    @property
    def available(self) -> bool:
        return self._lib is not None

    def _cap_bit(self, cap: str) -> Optional[int]:
        bit = self._cap_ids.get(cap)
        if bit is None:
            if len(self._cap_ids) >= 64:
                return None  # capability space exhausted → python fallback
            bit = len(self._cap_ids)
            self._cap_ids[cap] = bit
            self.intern_gen += 1
        return bit

    def _intern(self, table: dict[str, int], value: str) -> int:
        vid = table.get(value)
        if vid is None:
            vid = len(table)
            table[value] = vid
            self.intern_gen += 1
        return vid

    def _rebuild(self) -> None:
        snap = self.registry.snapshot()
        ids = sorted(snap)  # deterministic ties: lowest worker id wins
        n = len(ids)
        self.worker_ids = ids
        self.n = n
        self._cap_bits = (ctypes.c_uint64 * n)()
        self._pool_id = (ctypes.c_int32 * n)()
        self._topo_id = (ctypes.c_int32 * n)()
        self._chips = (ctypes.c_int32 * n)()
        self._active = (ctypes.c_float * n)()
        self._maxp = (ctypes.c_float * n)()
        self._cpu = (ctypes.c_float * n)()
        self._duty = (ctypes.c_float * n)()
        self._healthy = (ctypes.c_uint8 * n)()
        self._has_labels = [False] * n
        for i, wid in enumerate(ids):
            hb = snap[wid]
            bits = 0
            for cap in hb.capabilities:
                b = self._cap_bit(cap)
                if b is None:
                    # capability space exhausted: the C scan can no longer
                    # model eligibility — disable the native path entirely
                    self._degenerate = True
                    break
                bits |= 1 << b
            self._cap_bits[i] = bits
            self._pool_id[i] = self._intern(self._pool_ids, hb.pool)
            self._topo_id[i] = self._intern(self._topo_ids, hb.slice_topology)
            self._chips[i] = hb.chip_count
            self._active[i] = float(hb.active_jobs)
            self._maxp[i] = float(hb.max_parallel_jobs)
            self._cpu[i] = float(hb.cpu_load)
            self._duty[i] = float(hb.tpu_duty_cycle)
            # eligibility byte for the C scan: device health AND the HBM
            # pressure gate (is_overloaded's memory leg — the kernel computes
            # the load legs from active/cpu/duty itself but never sees HBM)
            hbm_full = (hb.hbm_total_gb > 0 and
                        hb.hbm_used_gb / hb.hbm_total_gb
                        >= HBM_OVERLOAD_FRACTION)
            self._healthy[i] = 1 if (hb.devices_healthy and not hbm_full) else 0
        self._built_version = self.registry.version

    def refresh(self) -> None:
        """Rebuild the pack if the registry moved (or the rebuild interval
        lapsed).  Raises LookupError when the native path is unusable."""
        if self._lib is None or self._degenerate:
            raise LookupError("native scan unavailable")
        now = time.monotonic()
        if (
            self._built_version != self.registry.version
            or now - self._built_at > REBUILD_INTERVAL_S
        ):
            self._rebuild()
            self._built_at = now
            if self._degenerate:
                raise LookupError("capability space exhausted")

    def prepare(
        self,
        *,
        required_caps: list[str],
        pool_names: list[str],
        min_chips: int,
        topology: str,
    ) -> tuple:
        """Resolve a routing shape to ready-to-call C-scan arguments.  The
        result is cacheable until ``intern_gen`` changes (a pool/cap that
        didn't exist at prepare time may exist later)."""
        req_caps = 0
        for cap in required_caps:
            b = self._cap_bit(cap)
            if b is None:
                raise LookupError("capability space exhausted")
            req_caps |= 1 << b
        pools = [self._pool_ids[p] for p in pool_names if p in self._pool_ids]
        arr = (ctypes.c_int32 * max(1, len(pools)))(*pools or [0])
        return (
            ctypes.c_uint64(req_caps), arr, len(pools), bool(pool_names),
            ctypes.c_int32(min_chips), topology,
        )

    def pick_prepared(self, prep: tuple) -> Optional[str]:
        """Run the C scan with :meth:`prepare`'d arguments.  Caller must
        :meth:`refresh` first (one refresh covers a whole batch of picks)."""
        req_caps, arr, n_pools, had_pools, min_chips, topology = prep
        if self.n == 0:
            return None
        if had_pools and not n_pools:
            return None  # none of the eligible pools has live workers
        if topology and topology not in self._topo_ids:
            return None  # no worker reports this topology
        topo_id = self._topo_ids.get(topology, 0) if topology else 0
        idx = self._lib.pick_worker(
            self.n, self._cap_bits, self._pool_id, self._topo_id, self._chips,
            self._active, self._maxp, self._cpu, self._duty, self._healthy,
            req_caps, arr, n_pools,
            min_chips, ctypes.c_int32(topo_id),
        )
        if idx < 0:
            return None
        return self.worker_ids[idx]

    def pick(
        self,
        *,
        required_caps: list[str],
        pool_names: list[str],
        min_chips: int,
        topology: str,
    ) -> Optional[str]:
        """Returns the chosen worker id, None for no-eligible-worker, or
        raises LookupError when this request can't use the native path."""
        self.refresh()
        return self.pick_prepared(self.prepare(
            required_caps=required_caps, pool_names=pool_names,
            min_chips=min_chips, topology=topology,
        ))
