"""Scheduler dynamic-config overlay: bootstrap + hot watch.

Reference ``cmd/cordum-scheduler/config_overlay.go:27-310``: on boot, seed
the config service from the YAML files (overlay wins over file afterwards);
then poll the effective config on an interval, hash-compare, and on change
atomically swap the routing table (``strategy.update_routing``) and the
reconciler timeouts.

Overlay document shape (under ``cfg:system:scheduler``):
  ``{"pools": {...pools.yaml doc...}, "timeouts": {...timeouts.yaml doc...}}``
"""
from __future__ import annotations

import asyncio
import hashlib
import json
from typing import Optional

from ...infra import logging as logx
from ...infra.config import PoolConfig, Timeouts, parse_pool_config, parse_timeouts
from ...infra.configsvc import ConfigService
from .reconciler import Reconciler
from .strategy import LeastLoadedStrategy

OVERLAY_DOC_ID = "scheduler"


class ConfigOverlay:
    def __init__(
        self,
        configsvc: ConfigService,
        strategy: LeastLoadedStrategy,
        reconciler: Optional[Reconciler] = None,
        *,
        interval_s: float = 30.0,
    ):
        self.configsvc = configsvc
        self.strategy = strategy
        self.reconciler = reconciler
        self.interval_s = interval_s
        self._hash = ""
        self._task: Optional[asyncio.Task] = None

    async def bootstrap(self, pools_doc: dict, timeouts_doc: dict) -> None:
        """Seed the overlay doc from file config unless one already exists."""
        existing = await self.configsvc.get("system", OVERLAY_DOC_ID)
        if existing is None:
            await self.configsvc.set(
                "system", OVERLAY_DOC_ID, {"pools": pools_doc, "timeouts": timeouts_doc}
            )
        await self.apply_once()

    async def apply_once(self) -> bool:
        doc = await self.configsvc.get("system", OVERLAY_DOC_ID)
        if doc is None:
            return False
        h = hashlib.sha256(
            json.dumps(doc.data, sort_keys=True, default=str).encode()
        ).hexdigest()
        if h == self._hash:
            return False
        self._hash = h
        pools_doc = doc.data.get("pools")
        if pools_doc:
            self.strategy.update_routing(parse_pool_config(pools_doc))
            logx.info("scheduler routing updated", revision=doc.revision)
        timeouts_doc = doc.data.get("timeouts")
        if timeouts_doc and self.reconciler is not None:
            self.reconciler.update_timeouts(parse_timeouts(timeouts_doc))
        return True

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="config-overlay")
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.apply_once()
            except Exception:
                logx.error("config overlay apply failed")


class WorkerSnapshotWriter:
    """Writes the live registry to ``sys:workers:snapshot`` every interval
    (reference ``core/infra/registry/snapshot.go``, 5s)."""

    def __init__(self, kv, registry, *, interval_s: float = 5.0):
        self.kv = kv
        self.registry = registry
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    async def write_once(self) -> None:
        dead = self.registry.expire()  # TTL expiry loop (registry_memory.go:24)
        if dead:
            logx.info("workers expired", workers=",".join(dead))
        snap = self.registry.snapshot_json()
        await self.kv.set("sys:workers:snapshot", json.dumps(snap).encode())

    async def start(self) -> None:
        async def loop():
            while True:
                try:
                    await self.write_once()
                except Exception:
                    logx.warn("worker snapshot write failed")
                await asyncio.sleep(self.interval_s)

        self._task = asyncio.ensure_future(loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="worker-snapshot-writer")
            self._task = None
