"""Disaggregated serving placement policy (docs/SERVING.md §Disaggregation).

Two consumers of the scheduler-local :class:`~cordum_tpu.obs.capacity.
CapacityView` that turn the measured capacity matrix into *live serving
placement* decisions (FlexNPU / FleetOpt, PAPERS.md; ROADMAP item 2 — the
policy layer over the PR 12 page-transfer substrate):

* :class:`ServingPlacer` — routes NEW ``llm.generate`` sessions to the
  worker with the best measured **prefill** tokens/s headroom.  Prefill is
  the right admission signal: a new session's first obligation is prompt
  ingestion (TTFT), and decode placement is corrected post-prefill by the
  worker-side hand-off.  Decode-roled workers are excluded from new-session
  placement whenever a prefill-capable worker exists — their step budget
  belongs to steady token generation.

* :class:`DecodeRebalancer` — a periodic governor watching decode occupancy
  and KV-page pressure across the serving fleet.  When one worker's load
  sits ``skew_ratio`` above the fleet median for ``hysteresis_ticks``
  consecutive evaluations, it publishes a :class:`~cordum_tpu.protocol.
  types.SessionRebalance` asking the hot worker to live-migrate its
  cheapest sessions (fewest live pages, oldest decode position) toward the
  peer with the most headroom.  Rate-limited per worker (``cooldown_s``)
  and paired with the worker-side migrated-in immunity window, so sessions
  never ping-pong even under oscillating skew.

Both degrade to nothing gracefully: an empty/stale capacity view disables
the placer (the strategy falls back to its measured-items/s routing and
ultimately exact LeastLoaded) and starves the governor of candidates.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, Optional

from ...infra import logging as logx
from ...protocol import subjects as subj
from ...protocol.types import (
    BusPacket,
    Heartbeat,
    LABEL_MIGRATE_ADDR,
    LABEL_SERVING_ROLE,
    OP_SERVING_PREFILL,
    SERVING_ROLE_DECODE,
    SessionRebalance,
)

DEFAULT_REBALANCE_INTERVAL_S = 5.0
DEFAULT_SKEW_RATIO = 1.5
DEFAULT_HYSTERESIS_TICKS = 2
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_MAX_MOVES = 2
# a worker with fewer active sessions than this is never "hot" (moving the
# only session on a near-idle worker is churn, not rebalancing)
MIN_HOT_SESSIONS = 2
# page pressure (pages_in_use / pages_total) above which pressure skew
# alone can mark a worker hot even with modest occupancy skew
PRESSURE_FLOOR = 0.5


class ServingPlacer:
    """Role-aware placement for new ``llm.generate`` sessions against the
    measured prefill-throughput matrix."""

    def __init__(self, view: Any, *, metrics: Any = None) -> None:
        self.view = view
        self.metrics = metrics
        # smooth-WRR credit per worker (nginx-style: deterministic,
        # starvation-free, converges to exact weight proportions)
        self._wrr: dict[str, float] = {}
        self.placed = 0
        self.fallbacks = 0

    def _gang_topology(self) -> tuple[set, dict]:
        """(follower worker-ids, {leader worker-id: fused gang row}) from
        the capacity view's serving-gang fold; empty when no gang beacons
        (or the view predates the fold — older schedulers keep working)."""
        followers: set = set()
        leaders: dict[str, dict] = {}
        gangs = getattr(self.view, "serving_gangs", None)
        for row in (gangs() if callable(gangs) else {}).values():
            leader = row.get("leader", "")
            if leader:
                leaders[leader] = row
            for wid, rank in (row.get("members") or {}).items():
                if wid != leader and int(rank or 0) > 0:
                    followers.add(wid)
        return followers, leaders

    def _role(self, hb: Heartbeat) -> str:
        """The worker's serving role: the fresh capacity beacon wins, the
        heartbeat label is the fallback (beacons lag ~2s behind boot)."""
        role = self.view.serving_role(hb.worker_id)
        if not role:
            role = (hb.labels or {}).get(LABEL_SERVING_ROLE, "")
        return role

    def pick(self, candidates: list[Heartbeat], *,
             speculable: bool = False) -> str:
        """The worker a new session should prefill on, or ``""`` when the
        view has no measured prefill signal (the caller degrades to its
        ordinary routing).  Score = measured prefill tokens/s (unmeasured
        workers get the median measured rate so they become measured) ×
        KV-page headroom fraction; distributed by smooth WRR.

        ``speculable=True`` (the session carried the ``LABEL_SPECULABLE``
        hint — templated/repetitive traffic) prefers workers whose
        capacity beacon reports a speculative acceptance rate: those are
        the draft-enabled workers that turn the workload's repetition
        into multi-token verified bursts (docs/SERVING.md §Speculative
        decoding).  Preference, not a filter — when no draft-enabled
        worker is live, placement degrades to the ordinary pool.

        Serving gangs (docs/SERVING.md §Sharded serving) collapse to one
        routable endpoint: follower ranks are excluded outright (their
        step budget is slaved to the leader's broadcast), and the leader
        is weighted by the gang's *fused* capacity row — measured gang
        decode tokens/s × min-of-ranks KV-page headroom — so a faster
        gang measurably out-draws a slower one."""
        followers, gang_rows = self._gang_topology()
        pool = [hb for hb in candidates
                if not self.view.draining(hb.worker_id)
                and hb.worker_id not in followers]
        prefill_capable = [
            hb for hb in pool if self._role(hb) != SERVING_ROLE_DECODE
        ]
        if prefill_capable:
            # decode-roled workers take sessions only when nothing else can
            pool = prefill_capable
        if speculable:
            draft_enabled = [
                hb for hb in pool
                if self.view.spec_accept(hb.worker_id) is not None
            ]
            if draft_enabled:
                pool = draft_enabled
        if not pool:
            self.fallbacks += 1
            return ""
        rates = {
            hb.worker_id: self.view.token_rate(hb.worker_id,
                                               OP_SERVING_PREFILL)
            for hb in pool
        }
        for wid, row in gang_rows.items():
            # a gang leader's routable rate is the fused gang row, not its
            # solo prefill history (which predates — or never saw — the gang)
            rate = float(row.get("tokens_per_s", 0.0) or 0.0)
            if rate > 0:
                rates[wid] = rate
        measured = sorted(r for r in rates.values() if r > 0)
        if not measured:
            # no prefill row measured anywhere: nothing analytic to say
            self.fallbacks += 1
            return ""
        median = measured[len(measured) // 2]
        weights: dict[str, float] = {}
        for hb in pool:
            base = rates[hb.worker_id] or median
            row = gang_rows.get(hb.worker_id)
            if row is not None:
                # min-of-ranks headroom: the gang stalls on its fullest rank
                total = float(row.get("pages_total_min", 0) or 0)
                free = float(row.get("pages_free_min", 0) or 0)
            else:
                kv = self.view.kv_pages(hb.worker_id)
                total = float(kv.get("pages_total", 0) or 0)
                free = float(kv.get("pages_free", 0) or 0)
            if total > 0:
                headroom = free / total
            else:
                headroom = 1.0  # arena unknown: rate alone decides
            w = base * headroom
            if w > 0:
                weights[hb.worker_id] = w
        if not weights:
            # every candidate's arena is full: admission-queueing territory,
            # let the load-based fallback spread the pain
            self.fallbacks += 1
            return ""
        self.placed += 1
        return self._wrr_pick(weights)

    def _wrr_pick(self, weights: dict[str, float]) -> str:
        for gone in [w for w in self._wrr if w not in weights]:
            del self._wrr[gone]
        total = sum(weights.values())
        best, best_credit = "", float("-inf")
        for wid, w in sorted(weights.items()):
            credit = self._wrr.get(wid, 0.0) + w
            self._wrr[wid] = credit
            if credit > best_credit:
                best, best_credit = wid, credit
        self._wrr[best] -= total
        return best


class DecodeRebalancer:
    """Periodic decode-load governor: skew detection over the capacity
    view, hysteresis + per-worker rate limiting, and ``SessionRebalance``
    fan-out toward measured headroom."""

    def __init__(
        self,
        bus: Any,
        view: Any,
        registry: Any,
        *,
        instance_id: str = "scheduler",
        interval_s: float = DEFAULT_REBALANCE_INTERVAL_S,
        skew_ratio: float = DEFAULT_SKEW_RATIO,
        hysteresis_ticks: int = DEFAULT_HYSTERESIS_TICKS,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        max_moves: int = DEFAULT_MAX_MOVES,
        min_hot_sessions: int = MIN_HOT_SESSIONS,
        metrics: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.bus = bus
        self.view = view
        self.registry = registry
        self.instance_id = instance_id
        self.interval_s = max(0.05, interval_s)
        self.skew_ratio = max(1.0, skew_ratio)
        self.hysteresis_ticks = max(1, hysteresis_ticks)
        self.cooldown_s = max(0.0, cooldown_s)
        self.max_moves = max(1, max_moves)
        self.min_hot_sessions = max(1, min_hot_sessions)
        self.metrics = metrics
        self.clock = clock
        self._hot_streak: dict[str, int] = {}
        self._last_cmd: dict[str, float] = {}
        self._task: Optional[asyncio.Task] = None
        self.commands_sent = 0

    @classmethod
    def from_config(cls, bus, view, registry, doc: dict, **kw):
        """Build from the pools.yaml ``rebalancer:`` stanza (schema-checked
        upstream); returns None when disabled."""
        if not (doc or {}).get("enabled", True):
            return None
        doc = doc or {}
        return cls(
            bus, view, registry,
            interval_s=float(doc.get("interval_s",
                                     DEFAULT_REBALANCE_INTERVAL_S)),
            skew_ratio=float(doc.get("skew_ratio", DEFAULT_SKEW_RATIO)),
            hysteresis_ticks=int(doc.get("hysteresis_ticks",
                                         DEFAULT_HYSTERESIS_TICKS)),
            cooldown_s=float(doc.get("cooldown_s", DEFAULT_COOLDOWN_S)),
            max_moves=int(doc.get("max_moves", DEFAULT_MAX_MOVES)),
            **kw,
        )

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception as e:  # noqa: BLE001 - logged, never swallowed
                logx.warn("rebalancer loop crashed during shutdown",
                          err=str(e))
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.tick()
            except Exception as e:  # noqa: BLE001 - governor is best-effort
                logx.warn("rebalance evaluation failed", err=str(e))

    async def tick(self) -> None:
        for cmd in self.plan():
            self.commands_sent += 1
            if self.metrics is not None:
                self.metrics.serving_rebalances.inc(stage="commanded")
            logx.info("rebalance commanded", worker=cmd.worker_id,
                      target=cmd.target_worker, moves=cmd.max_sessions,
                      reason=cmd.reason)
            await self.bus.publish(
                subj.SERVING_REBALANCE,
                BusPacket.wrap(cmd, sender_id=self.instance_id),
            )

    # ------------------------------------------------------------------
    def _load(self, wid: str) -> tuple[float, float]:
        """(active decode sessions, page pressure 0..1) for one worker."""
        occ = self.view.decode_occupancy(wid)
        kv = self.view.kv_pages(wid)
        sessions = float(occ.get("active_sessions", 0) or 0)
        total = float(kv.get("pages_total", 0) or 0)
        pressure = (
            float(kv.get("pages_in_use", 0) or 0) / total if total > 0
            else 0.0
        )
        return sessions, pressure

    def _migrate_addr(self, wid: str) -> str:
        hb = self.registry.get(wid)
        return (hb.labels or {}).get(LABEL_MIGRATE_ADDR, "") if hb else ""

    def plan(self) -> list[SessionRebalance]:
        """Pure skew evaluation: which hot workers should shed, where to,
        and how many sessions — the publish-free half the tests drive.
        Hysteresis state (hot streaks, cooldown stamps) advances here."""
        now = self.clock()
        workers = [
            wid for wid in self.view.serving_workers()
            if not self.view.draining(wid)
        ]
        if len(workers) < 2:
            self._hot_streak.clear()
            return []
        loads = {wid: self._load(wid) for wid in workers}
        sessions_sorted = sorted(s for s, _ in loads.values())
        pressure_sorted = sorted(p for _, p in loads.values())
        # LOWER median: with an even fleet the upper median is the hot
        # worker's own load (a 2-worker fleet could never look skewed)
        med_sessions = sessions_sorted[(len(sessions_sorted) - 1) // 2]
        med_pressure = pressure_sorted[(len(pressure_sorted) - 1) // 2]
        cmds: list[SessionRebalance] = []
        for wid in workers:
            sessions, pressure = loads[wid]
            occ_hot = (
                sessions >= self.min_hot_sessions
                and sessions >= self.skew_ratio * max(med_sessions, 1.0)
                and sessions >= med_sessions + 1
            )
            page_hot = (
                pressure >= PRESSURE_FLOOR
                and pressure >= self.skew_ratio * max(med_pressure, 1e-9)
            )
            if not (occ_hot or page_hot):
                self._hot_streak.pop(wid, None)
                continue
            streak = self._hot_streak.get(wid, 0) + 1
            self._hot_streak[wid] = streak
            if streak < self.hysteresis_ticks:
                continue  # transient spike: wait it out
            if now - self._last_cmd.get(wid, float("-inf")) < self.cooldown_s:
                continue  # rate limit: one command per window per worker
            target = self._pick_target(wid, loads)
            if not target:
                continue
            addr = self._migrate_addr(target)
            if not addr:
                continue
            excess = max(1.0, sessions - med_sessions)
            self._last_cmd[wid] = now
            self._hot_streak[wid] = 0
            cmds.append(SessionRebalance(
                worker_id=wid,
                target_worker=target,
                target_addr=addr,
                max_sessions=int(min(self.max_moves, excess)),
                reason=(f"occupancy {sessions:g} vs median "
                        f"{med_sessions:g}" if occ_hot else
                        f"page pressure {pressure:.2f} vs median "
                        f"{med_pressure:.2f}"),
                requested_by=self.instance_id,
            ))
        return cmds

    def _pick_target(self, hot_wid: str, loads: dict) -> str:
        """The non-hot worker with the most room: free pages × steady
        decode tokens/s (unmeasured decode rate counts as 1 so a fresh
        worker with free pages still ranks), damped by its own occupancy."""
        best, best_score = "", 0.0
        for wid, (sessions, _pressure) in loads.items():
            if wid == hot_wid:
                continue
            kv = self.view.kv_pages(wid)
            free = float(kv.get("pages_free", 0) or 0)
            if free <= 0:
                continue
            decode_rate = self.view.token_rate(wid, "llm.generate") or 1.0
            score = free * decode_rate / (1.0 + sessions)
            if score > best_score:
                best, best_score = wid, score
        return best
