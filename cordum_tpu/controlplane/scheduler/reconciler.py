"""Scheduler reconciler + pending replayer: the failure-detection loops.

Reconciler (reference ``core/controlplane/scheduler/reconciler.go``): a
singleton-behind-lock loop that (a) marks stale DISPATCHED/RUNNING jobs
TIMEOUT past their configured timeouts (batched), and (b) expires
``job:deadline`` entries.

Pending replayer (reference ``pending_replayer.go``): re-drives PENDING
jobs older than the dispatch timeout through the engine using the persisted
JobRequest — unsticks submits lost to crashes between persist and dispatch.

Worker failover (docs/SERVING.md §Migration, drain, and failover): expires
workers that missed heartbeats, evicts their affinity entries, and fails
their in-flight jobs over to new workers — a SIGKILL'd serving worker's
sessions resume elsewhere with a forced-decode resume prefix instead of
timing out.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ...infra import logging as logx
from ...infra.config import Timeouts
from ...infra.jobstore import IllegalTransition, JobStore
from ...infra.registry import WorkerRegistry
from ...protocol.subjects import direct_subject
from ...protocol.types import JobState
from ...utils.ids import now_ms, now_us
from .engine import Engine

BATCH = 200
MAX_ITERATIONS = 100
SINGLETON_LOCK = "lock:reconciler"


class Reconciler:
    def __init__(self, job_store: JobStore, timeouts: Timeouts, *, instance_id: str = "rec-0"):
        self.job_store = job_store
        self.timeouts = timeouts
        self.instance_id = instance_id
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    def update_timeouts(self, t: Timeouts) -> None:
        self.timeouts = t

    async def start(self) -> None:
        self._stop.clear()
        self._task = asyncio.ensure_future(self._loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        self._stop.set()
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="reconciler")
            self._task = None

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.run_once()
            except Exception:
                logx.error("reconciler pass failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.timeouts.scan_interval_s)
            except asyncio.TimeoutError:
                pass

    async def run_once(self) -> int:
        """One reconciliation pass; returns number of jobs timed out."""
        # singleton guard: only one replica reconciles at a time
        if not await self.job_store.kv.setnx(
            SINGLETON_LOCK, self.instance_id.encode(), ttl_s=self.timeouts.scan_interval_s
        ):
            return 0
        try:
            n = 0
            n += await self._timeout_state(JobState.DISPATCHED, self.timeouts.dispatch_timeout_s)
            n += await self._timeout_state(JobState.RUNNING, self.timeouts.running_timeout_s)
            n += await self._expire_deadlines()
            return n
        finally:
            # owner-checked release: never delete another replica's lock
            # (ours may have TTL-expired mid-pass and been re-acquired)
            cur = await self.job_store.kv.get(SINGLETON_LOCK)
            if cur is not None and cur.decode() == self.instance_id:
                await self.job_store.kv.delete(SINGLETON_LOCK)

    async def _timeout_state(self, state: JobState, timeout_s: float) -> int:
        total = 0
        cutoff_us = now_us() - int(timeout_s * 1e6)
        for _ in range(MAX_ITERATIONS):
            stale = await self.job_store.list_by_state_older_than(state.value, cutoff_us, BATCH)
            if not stale:
                break
            progressed = 0
            for job_id in stale:
                try:
                    changed = await self.job_store.set_state(
                        job_id,
                        JobState.TIMEOUT,
                        fields={"error_message": f"stale in {state.value} > {timeout_s}s"},
                        event="reconciler_timeout",
                    )
                    if changed:
                        total += 1
                        progressed += 1
                except IllegalTransition:
                    progressed += 1  # moved on concurrently; index will catch up
            if not progressed:
                break
        return total

    async def _expire_deadlines(self) -> int:
        expired = await self.job_store.expired_deadlines(now_ms(), limit=BATCH)
        n = 0
        for job_id in expired:
            await self.job_store.clear_deadline(job_id)
            if await self.job_store.is_terminal(job_id):
                continue
            try:
                if await self.job_store.set_state(
                    job_id,
                    JobState.TIMEOUT,
                    fields={"error_message": "deadline exceeded"},
                    event="deadline_expired",
                ):
                    n += 1
            except IllegalTransition:
                pass
        return n


class WorkerFailover:
    """Detects dead workers (missed heartbeats past the registry TTL) and
    fails their in-flight jobs over to new workers.

    Each pass expires the registry, evicts the dead workers' affinity
    entries (so session turns stop routing at the corpse), then scans the
    owner shard's DISPATCHED/RUNNING jobs for ones whose recorded
    ``dispatch_subject`` targets a dead worker's direct subject and drives
    :meth:`Engine.failover_job` for each — serving sessions resume on a new
    worker with their streamed tokens as a forced-decode prefix; stateless
    jobs simply re-run (worker idempotence dedupes the occasional race).
    Per-shard, no singleton lock: each shard fails over only jobs it owns."""

    def __init__(
        self,
        engine: Engine,
        job_store: JobStore,
        registry: WorkerRegistry,
        timeouts: Timeouts,
    ) -> None:
        self.engine = engine
        self.job_store = job_store
        self.registry = registry
        self.timeouts = timeouts
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def start(self) -> None:
        self._stop.clear()
        self._task = asyncio.ensure_future(self._loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        self._stop.set()
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="worker-failover")
            self._task = None

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.run_once()
            except Exception:
                logx.error("worker failover pass failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.timeouts.scan_interval_s)
            except asyncio.TimeoutError:
                pass

    async def run_once(self) -> int:
        dead = self.registry.expire()
        if not dead:
            return 0
        logx.warn("workers missed heartbeats; failing over their jobs",
                  workers=",".join(dead))
        for wid in dead:
            self.engine._evict_affinity(wid)
        dead_subjects = {direct_subject(w) for w in dead}
        n = 0
        for state in (JobState.DISPATCHED.value, JobState.RUNNING.value):
            stuck = await self.job_store.list_by_state_older_than(
                state, now_us(), BATCH
            )
            for job_id in stuck:
                if not self.engine.owns(job_id):
                    continue
                snap = await self.job_store.watch_meta(job_id)
                if snap.get("dispatch_subject", "") not in dead_subjects:
                    continue
                try:
                    if await self.engine.failover_job(job_id, reason="worker_dead"):
                        n += 1
                except Exception:
                    logx.warn("failover failed", job_id=job_id)
        return n


class PendingReplayer:
    """Replays stuck PENDING / APPROVAL-released jobs through the engine."""

    def __init__(self, engine: Engine, job_store: JobStore, timeouts: Timeouts):
        self.engine = engine
        self.job_store = job_store
        self.timeouts = timeouts
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def start(self) -> None:
        self._stop.clear()
        self._task = asyncio.ensure_future(self._loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        self._stop.set()
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="pending-replayer")
            self._task = None

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.run_once()
            except Exception:
                logx.error("pending replayer pass failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.timeouts.scan_interval_s)
            except asyncio.TimeoutError:
                pass

    async def run_once(self) -> int:
        # PENDING gets its own (short) cutoff: a submit that exhausted its
        # bus redeliveries under backpressure, or whose owner shard was
        # down, must resurface in seconds — replays are idempotent
        pending_cutoff_us = now_us() - int(self.timeouts.pending_replay_s * 1e6)
        stuck = await self.job_store.list_by_state_older_than(
            JobState.PENDING.value, pending_cutoff_us, BATCH
        )
        n = 0
        for job_id in stuck:
            if not self.engine.owns(job_id):
                # sharded: a job parked while its owner shard was down is
                # replayed by the OWNER after restart, preserving the
                # no-cross-shard-ownership invariant (ISSUE 5 degraded mode)
                continue
            req = await self.job_store.get_request(job_id)
            if req is None:
                continue
            try:
                await self.engine.handle_job_request(req)
                n += 1
            except Exception:
                logx.warn("replay failed", job_id=job_id)
        # SCHEDULED-but-never-published (crash/bus blip between
        # set_state(SCHEDULED) and the dispatch publish): the submit-path
        # in-flight short-circuit deliberately ignores redeliveries for these,
        # so the replayer re-drives the dispatch leg directly
        dispatch_cutoff_us = now_us() - int(self.timeouts.dispatch_timeout_s * 1e6)
        wedged = await self.job_store.list_by_state_older_than(
            JobState.SCHEDULED.value, dispatch_cutoff_us, BATCH
        )
        for job_id in wedged:
            if not self.engine.owns(job_id):
                continue
            try:
                if await self.engine.redispatch_scheduled(job_id):
                    n += 1
            except Exception:
                logx.warn("redispatch failed", job_id=job_id)
        # DISPATCHED/RUNNING past the result-replay window: the dispatch
        # packet or its terminal result may have been lost to a statebus
        # failover (pub/sub pushes are not replicated) — re-deliver to the
        # worker, whose idempotence turns the nudge into "republish your
        # result" (or a no-op for genuinely still-running jobs)
        nudge_cutoff_us = now_us() - int(self.timeouts.result_replay_s * 1e6)
        for state in (JobState.DISPATCHED.value, JobState.RUNNING.value):
            wedged = await self.job_store.list_by_state_older_than(
                state, nudge_cutoff_us, BATCH
            )
            for job_id in wedged:
                if not self.engine.owns(job_id):
                    continue
                try:
                    if await self.engine.nudge_inflight(job_id):
                        n += 1
                except Exception:
                    logx.warn("inflight nudge failed", job_id=job_id)
        return n
