"""Safety-kernel client: timeout + half-open circuit breaker, fail-closed.

Reference ``core/controlplane/scheduler/safety_client.go``: 2s check timeout;
breaker opens after 3 consecutive failures, stays open 30s, then allows 3
half-open probes and closes after 2 successes; every error path **denies**
(fail-closed).
"""
from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from ...protocol.types import Decision, PolicyCheckRequest, PolicyCheckResponse
from ...utils.eager import eager

CheckFn = Callable[[PolicyCheckRequest], Awaitable[PolicyCheckResponse]]

FAIL_THRESHOLD = 3
OPEN_SECONDS = 30.0
HALF_OPEN_PROBES = 3
CLOSE_SUCCESSES = 2


class CircuitBreaker:
    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        fail_threshold: int = FAIL_THRESHOLD,
        open_seconds: float = OPEN_SECONDS,
        half_open_probes: int = HALF_OPEN_PROBES,
        close_successes: int = CLOSE_SUCCESSES,
    ):
        self.state = self.CLOSED
        self.fail_threshold = fail_threshold
        self.open_seconds = open_seconds
        self.half_open_probes = half_open_probes
        self.close_successes = close_successes
        self._fails = 0
        self._successes = 0
        self._probes = 0
        self._opened_at = 0.0

    def allow(self) -> bool:
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if time.monotonic() - self._opened_at >= self.open_seconds:
                self.state = self.HALF_OPEN
                self._probes = 0
                self._successes = 0
            else:
                return False
        # half-open: limited probes
        if self._probes < self.half_open_probes:
            self._probes += 1
            return True
        return False

    def record_success(self) -> None:
        if self.state == self.HALF_OPEN:
            self._successes += 1
            if self._successes >= self.close_successes:
                self.state = self.CLOSED
                self._fails = 0
        else:
            self._fails = 0

    def record_failure(self) -> None:
        if self.state == self.HALF_OPEN:
            self._open()
            return
        self._fails += 1
        if self._fails >= self.fail_threshold:
            self._open()

    def _open(self) -> None:
        self.state = self.OPEN
        self._opened_at = time.monotonic()
        self._fails = 0


def _deny(reason: str) -> PolicyCheckResponse:
    return PolicyCheckResponse(decision=Decision.DENY.value, reason=reason)


class SafetyClient:
    """Wraps any async check function (in-process kernel or remote RPC)."""

    def __init__(self, check_fn: CheckFn, *, timeout_s: float = 2.0, breaker: CircuitBreaker | None = None):
        self._check = check_fn
        self.timeout_s = timeout_s
        self.breaker = breaker or CircuitBreaker()

    async def check(self, req: PolicyCheckRequest) -> PolicyCheckResponse:
        if not self.breaker.allow():
            return _deny("safety kernel circuit open (fail-closed)")
        try:
            # eager completion: an in-process kernel with a warm policy
            # cache finishes without suspending — no Task, no timer.  The
            # check timeout only matters for checks that actually park
            # (remote RPC, cold reload), which take the wait_for path.
            done, resp = eager(self._check(req))
            if not done:
                resp = await asyncio.wait_for(
                    asyncio.ensure_future(resp), self.timeout_s
                )
        except asyncio.TimeoutError:
            self.breaker.record_failure()
            return _deny("safety kernel check timed out (fail-closed)")
        except Exception as e:  # noqa: BLE001 - any kernel error denies
            self.breaker.record_failure()
            return _deny(f"safety kernel error: {e} (fail-closed)")
        self.breaker.record_success()
        return resp
