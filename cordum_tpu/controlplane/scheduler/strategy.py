"""Dispatch-subject selection strategies.

``LeastLoadedStrategy`` recreates the reference semantics
(``core/controlplane/scheduler/strategy_least_loaded.go:40-262``) with
TPU-slice awareness:

  * topic → eligible pools from :class:`~cordum_tpu.infra.config.PoolConfig`
  * pool eligibility: worker capabilities must cover the pool's ``requires``
    *and* the job's own ``metadata.requires``; TPU constraints
    (``chips:N``, ``topology:AxBxC``, pool ``min_chips``/``topology``/
    ``device_kind``) are matched against heartbeat slice telemetry
  * label hints: ``preferred_worker_id`` / ``preferred_pool``; placement
    labels (``placement.<k>=<v>`` must equal the worker's label ``<k>``)
  * overload skip: ≥90% of ``max_parallel_jobs``, or cpu ≥90, or TPU duty
    cycle ≥90, or unhealthy devices
  * score = ``active_jobs + cpu_load/100 + tpu_duty_cycle/100`` (reference
    used gpu_utilization; TPU duty cycle is the analogue); least wins
  * batch affinity: jobs carrying the ``cordum.batch_key`` label stick to
    the worker that last won for that key (TTL'd), so the worker-side
    micro-batch queues actually fill instead of each job landing on a
    different slice (docs/BATCHING.md)
  * session affinity (the serving generalization of batch affinity): jobs
    carrying ``cordum.session_key`` stick to the worker holding that
    conversation's KV pages, with a much longer TTL sized to conversation
    gaps rather than batch-fill windows (docs/SERVING.md); hit/miss/new
    outcomes feed ``cordum_session_affinity_total``
  * chosen worker → direct subject ``worker.<id>.jobs``; no worker →
    topic fan-in subject (queue-group consumption)

``update_routing`` atomically swaps the pool config (hot reload path).
"""
from __future__ import annotations

import itertools
import re
import time
from typing import Optional

from ...infra.config import Pool, PoolConfig
from ...infra.registry import WorkerRegistry
from ...protocol.subjects import direct_subject
from ...protocol.types import (
    Heartbeat,
    JobRequest,
    LABEL_BATCH_KEY,
    LABEL_OP,
    LABEL_SESSION_KEY,
    LABEL_SPECULABLE,
    SERVING_OPS,
)

_CHIPS_RE = re.compile(r"^chips:(\d+)$")
_TOPOLOGY_RE = re.compile(r"^topology:([0-9x]+)$")

OVERLOAD_FRACTION = 0.9
OVERLOAD_UTIL = 90.0
HBM_OVERLOAD_FRACTION = 0.95
BATCH_AFFINITY_TTL_S = 5.0
# Sessions outlive batch-fill windows: the TTL covers think-time between a
# conversation's turns, after which its KV pages are presumed reclaimed and
# re-routing is free.
SESSION_AFFINITY_TTL_S = 120.0
# Hibernated sessions keep affinity alive far past the normal TTL: their KV
# lives in the owning worker's host-RAM cold arena (docs/SERVING.md §Prefix
# cache and tiering), so the "pages presumed reclaimed" assumption behind the
# 120s TTL does not apply — routing the next turn anywhere else silently
# degrades to a cold re-prefill.  SessionMoved(reason="hibernated") pins the
# entry; reason="restored" (or any normal retarget) unpins it.
SESSION_HIBERNATE_TTL_S = 3600.0
_AFFINITY_CAP = 1024
# internal key namespace so an arbitrary session id can never collide with
# a batch key (batch keys stay raw for back-compat)
_SESSION_PREFIX = "session\x00"


class Strategy:
    def pick_subject(self, req: JobRequest) -> str:
        raise NotImplementedError

    def pick_subjects(self, reqs: list[JobRequest]) -> list[str]:
        """Batched selection (scheduler tick batching): one subject per
        request.  The default just loops; strategies that scan state
        per pick override this to amortize the scan across the batch."""
        return [self.pick_subject(r) for r in reqs]


class NaiveStrategy(Strategy):
    """Topic passthrough (reference strategy_naive.go)."""

    def pick_subject(self, req: JobRequest) -> str:
        return req.topic


def _parse_tpu_requires(requires: list[str]) -> tuple[list[str], int, str]:
    """Split requires into plain capabilities vs TPU constraints."""
    caps: list[str] = []
    min_chips = 0
    topology = ""
    for r in requires:
        m = _CHIPS_RE.match(r)
        if m:
            min_chips = max(min_chips, int(m.group(1)))
            continue
        m = _TOPOLOGY_RE.match(r)
        if m:
            topology = m.group(1)
            continue
        caps.append(r)
    return caps, min_chips, topology


def pool_requirement_mismatch(hb: Heartbeat, pool: Optional[Pool]) -> str:
    """Why a worker fails its pool's slice-requirement keys (pools.yaml
    ``min_chips`` / ``topology`` / ``device_kind``), or ``""`` when it
    passes.  Split out so the exclusion can be *announced* — a worker
    silently dropped from its own pool's routing is a misconfiguration the
    operator should hear about once, not discover via starvation."""
    if pool is None:
        return ""
    if pool.min_chips and hb.chip_count < pool.min_chips:
        return (f"advertises {hb.chip_count} chips < pool min_chips "
                f"{pool.min_chips}")
    if pool.topology and hb.slice_topology != pool.topology:
        return (f"topology {hb.slice_topology or '(none)'} != pool topology "
                f"{pool.topology}")
    if pool.device_kind and hb.device_kind and hb.device_kind != pool.device_kind:
        return (f"device_kind {hb.device_kind!r} != pool device_kind "
                f"{pool.device_kind!r}")
    return ""


# one-shot pool-exclusion warnings: (worker_id, pool_name) pairs already
# announced (capped so an unbounded worker churn can't grow it forever)
_POOL_EXCLUSION_WARNED: set[tuple[str, str]] = set()
_POOL_EXCLUSION_WARN_CAP = 4096


def warn_pool_exclusion(hb: Heartbeat, pool: Optional[Pool]) -> None:
    """Log ONCE per (worker, pool) when a worker is excluded from a pool's
    routing by the pool's slice-requirement keys."""
    reason = pool_requirement_mismatch(hb, pool)
    if not reason or pool is None:
        return
    key = (hb.worker_id, pool.name)
    if key in _POOL_EXCLUSION_WARNED:
        return
    if len(_POOL_EXCLUSION_WARNED) >= _POOL_EXCLUSION_WARN_CAP:
        _POOL_EXCLUSION_WARNED.clear()
    _POOL_EXCLUSION_WARNED.add(key)
    from ...infra import logging as logx

    logx.warn("worker excluded from pool routing",
              worker_id=hb.worker_id, pool=pool.name, reason=reason)


def worker_satisfies(
    hb: Heartbeat, pool: Optional[Pool], job_requires: list[str]
) -> bool:
    caps = set(hb.capabilities)
    req_caps, min_chips, topology = _parse_tpu_requires(job_requires)
    if pool is not None:
        pool_caps, pool_chips, pool_topology = _parse_tpu_requires(pool.requires)
        req_caps += pool_caps
        min_chips = max(min_chips, pool_chips, pool.min_chips)
        topology = topology or pool_topology or pool.topology
        if pool.device_kind and hb.device_kind and pool.device_kind != hb.device_kind:
            return False
    if not set(req_caps) <= caps:
        return False
    if min_chips and hb.chip_count < min_chips:
        return False
    # a worker reporting no topology cannot satisfy a topology requirement
    # (symmetric with the chips check above)
    if topology and hb.slice_topology != topology:
        return False
    return True


def is_overloaded(hb: Heartbeat) -> bool:
    if not hb.devices_healthy:
        return True
    if hb.draining:
        return True  # drain mode: finishing/migrating work, no new placements
    if hb.max_parallel_jobs > 0 and hb.active_jobs >= OVERLOAD_FRACTION * hb.max_parallel_jobs:
        return True
    if hb.cpu_load >= OVERLOAD_UTIL or hb.tpu_duty_cycle >= OVERLOAD_UTIL:
        return True
    # HBM pressure: a worker whose accelerator memory is effectively full
    # cannot take another placement even if its MXU duty cycle looks idle
    # (weights/KV arenas are resident; the next job would OOM, not queue)
    if hb.hbm_total_gb > 0 and hb.hbm_used_gb / hb.hbm_total_gb >= HBM_OVERLOAD_FRACTION:
        return True
    return False


def load_score(hb: Heartbeat) -> float:
    return hb.active_jobs + hb.cpu_load / 100.0 + hb.tpu_duty_cycle / 100.0


def _placement_labels(labels: dict[str, str]) -> dict[str, str]:
    return {
        k[len("placement."):]: v
        for k, v in labels.items()
        if k.startswith("placement.")
    }


class LeastLoadedStrategy(Strategy):
    def __init__(self, registry: WorkerRegistry, pool_config: PoolConfig, *,
                 native: bool = True, metrics=None):
        self.registry = registry
        self._pool_config = pool_config
        self.metrics = metrics
        # affinity: batch_key / namespaced session_key -> (worker_id, stamp)
        self._affinity: dict[str, tuple[str, float]] = {}
        # namespaced session keys whose entry uses SESSION_HIBERNATE_TTL_S
        # (the conversation's KV is tiered to that worker's cold arena)
        self._pinned: set[str] = set()
        # session-affinity outcome counters (the bench's affinity-hit-rate
        # source; mirrored to cordum_session_affinity_total when metrics set)
        self.session_affinity_hits = 0
        self.session_affinity_misses = 0
        self.session_affinity_new = 0
        self.session_affinity_evicted = 0
        self.session_affinity_retargeted = 0
        # routing caches (ISSUE 6): topic→pools and the native scan's
        # resolved arguments are identical for every job of one shape, so
        # re-deriving them per pick (regex parses, pool scans, ctypes array
        # builds) was pure hot-path overhead.  Both caches invalidate on
        # update_routing; native entries also carry the packed scan's
        # interning generation (tables grow when new pools/caps appear).
        self._topic_pools: dict[str, list[Pool]] = {}
        self._native_routes: dict[tuple, tuple] = {}
        self._packed = None
        if native:
            try:
                from .native_scan import PackedWorkers

                packed = PackedWorkers(registry)
                if packed.available:
                    self._packed = packed
            except Exception:  # no compiler / load failure → pure python
                self._packed = None

    def update_routing(self, pool_config: PoolConfig) -> None:
        self._pool_config = pool_config
        self._topic_pools = {}
        self._native_routes = {}

    def _pools_for_topic(self, topic: str) -> list[Pool]:
        pools = self._topic_pools.get(topic)
        if pools is None:
            pools = self._pool_config.pools_for_topic(topic)
            if len(self._topic_pools) > 4096:
                self._topic_pools.clear()  # unbounded topic space guard
            self._topic_pools[topic] = pools
        return pools

    # -- batch affinity ---------------------------------------------------
    def _record_affinity(self, key: str, worker_id: str) -> None:
        if len(self._affinity) >= _AFFINITY_CAP:
            # amortized prune: drop the oldest half (insertion-ordered dict)
            for k in list(itertools.islice(self._affinity, _AFFINITY_CAP // 2)):
                del self._affinity[k]
                self._pinned.discard(k)
        self._affinity[key] = (worker_id, time.monotonic())
        # recording is (re-)election: only the hibernate retarget re-pins
        self._pinned.discard(key)

    def evict_worker(self, worker_id: str) -> int:
        """Invalidate every affinity entry (session AND batch) pointing at
        ``worker_id`` — called when a worker deregisters, drains, or misses
        heartbeats, so session turns stop routing to a dead/draining worker
        for up to the 120s session TTL.  Returns the number of entries
        dropped; session evictions count in
        ``cordum_session_affinity_total{outcome="evicted"}``."""
        dead = [k for k, (wid, _) in self._affinity.items() if wid == worker_id]
        for k in dead:
            del self._affinity[k]
            self._pinned.discard(k)  # dead worker's cold arena died with it
            if k.startswith(_SESSION_PREFIX):
                self._count_session_affinity("evicted")
        return len(dead)

    def _affinity_worker(
        self, key: str, pools: list[Pool], job_requires: list[str],
        placement: dict[str, str], ttl_s: float = BATCH_AFFINITY_TTL_S,
    ) -> str:
        """The sticky worker for an affinity key, if it is still a legal
        target.  An overloaded / vanished / no-longer-eligible sticky worker
        returns "" so the scan below elects (and records) a new one — the
        whole key's queue (or session) migrates together instead of smearing
        across workers."""
        ent = self._affinity.get(key)
        if ent is None:
            return ""
        worker_id, stamped = ent
        if key in self._pinned:
            ttl_s = SESSION_HIBERNATE_TTL_S  # cold-arena keepalive
        if time.monotonic() - stamped >= ttl_s:
            self._affinity.pop(key, None)
            self._pinned.discard(key)
            return ""
        hb = self.registry.get(worker_id)
        if hb is None or hb.draining:
            # missed-heartbeat / draining worker: drop the entry outright
            # (lazy mirror of evict_worker) instead of leaving it to block
            # the key until the TTL expires
            self._affinity.pop(key, None)
            self._pinned.discard(key)
            if key.startswith(_SESSION_PREFIX):
                self._count_session_affinity("evicted")
            return ""
        if is_overloaded(hb):
            return ""
        pool = next((p for p in pools if p.name == hb.pool), None)
        if pool is None:
            return ""
        if not worker_satisfies(hb, pool, job_requires):
            return ""
        if placement and any(hb.labels.get(k) != v for k, v in placement.items()):
            return ""
        self._affinity[key] = (worker_id, time.monotonic())  # sliding TTL
        return worker_id

    def _native_pick(self, req: JobRequest, pools, job_requires) -> Optional[str]:
        """Native packed scan for the common shape; LookupError → python.

        The per-shape resolution (pool-uniformity validation, requires
        parsing, capability-bit and pool-id interning, the ctypes pools
        array) is cached per ``(topic, requires)`` — only the C scan itself
        runs per pick."""
        packed = self._packed
        if packed is None:
            raise LookupError("native disabled")
        packed.refresh()  # rebuild pack if registry moved; may bump intern_gen
        key = (req.topic, tuple(job_requires), tuple(p.name for p in pools))
        ent = self._native_routes.get(key)
        if ent is None or ent[0] != packed.intern_gen:
            prep = self._resolve_native_route(pools, job_requires, packed)
            if len(self._native_routes) > 4096:
                self._native_routes.clear()
            ent = (packed.intern_gen, prep)
            self._native_routes[key] = ent
        prep = ent[1]
        if prep is None:
            raise LookupError("shape not modeled by native scan")
        return packed.pick_prepared(prep)

    def _resolve_native_route(self, pools, job_requires, packed):
        """→ prepared native-scan args, or None for shapes the C kernel
        doesn't model (cached either way)."""
        first = pools[0]
        # pools must agree on constraints for the single-pass C scan
        for p in pools[1:]:
            if (p.requires, p.min_chips, p.topology, p.device_kind) != (
                first.requires, first.min_chips, first.topology, first.device_kind
            ):
                return None
        if first.device_kind:
            return None  # device_kind filter not in native scan
        req_caps, min_chips, topology = _parse_tpu_requires(job_requires)
        pool_caps, pool_chips, pool_topology = _parse_tpu_requires(first.requires)
        try:
            return packed.prepare(
                required_caps=req_caps + pool_caps,
                pool_names=[p.name for p in pools],
                min_chips=max(min_chips, pool_chips, first.min_chips),
                topology=topology or pool_topology or first.topology,
            )
        except LookupError:
            return None

    def pick_subjects(self, reqs: list[JobRequest]) -> list[str]:
        """Batched selection: jobs sharing a routing shape (topic, requires,
        routing labels) within one tick share ONE scan — the registry is
        static between heartbeats, so sequential picks would return the
        same worker anyway."""
        memo: dict[tuple, str] = {}
        out: list[str] = []
        for req in reqs:
            key = self._shape_key(req)
            hit = memo.get(key)
            if hit is None:
                hit = self.pick_subject(req)
                memo[key] = hit
            out.append(hit)
        return out

    @staticmethod
    def _shape_key(req: JobRequest) -> tuple:
        labels = req.labels or {}
        routing = tuple(sorted(
            (k, v) for k, v in labels.items()
            if k in ("preferred_worker_id", "preferred_pool",
                     LABEL_BATCH_KEY, LABEL_SESSION_KEY)
            or k.startswith("placement.")
        ))
        requires = tuple(req.metadata.requires) if req.metadata else ()
        return (req.topic, requires, routing)

    def _count_session_affinity(self, outcome: str) -> None:
        if outcome == "hit":
            self.session_affinity_hits += 1
        elif outcome == "miss":
            self.session_affinity_misses += 1
        elif outcome == "evicted":
            self.session_affinity_evicted += 1
        elif outcome == "retargeted":
            self.session_affinity_retargeted += 1
        else:
            self.session_affinity_new += 1
        if self.metrics is not None:
            self.metrics.session_affinity.inc(outcome=outcome)

    def retarget_session(
        self, session_key: str, worker_id: str, *, pinned: bool = False
    ) -> None:
        """Point a session's affinity at its new owner — a ``SessionMoved``
        announcement after a hand-off/rebalance/drain migration commits
        (docs/SERVING.md §Disaggregation).  Follow-up turns and cancels
        then route to the worker actually holding the KV pages instead of
        the original placement.  ``pinned`` (reason="hibernated") switches
        the entry to :data:`SESSION_HIBERNATE_TTL_S`; any later normal
        retarget — including reason="restored" — unpins it."""
        if not session_key or not worker_id:
            return
        key = _SESSION_PREFIX + session_key
        self._record_affinity(key, worker_id)
        if pinned:
            self._pinned.add(key)
        self._count_session_affinity("retargeted")

    def pick_subject(self, req: JobRequest) -> str:
        labels = req.labels or {}
        job_requires = list(req.metadata.requires) if req.metadata else []

        pools = self._pools_for_topic(req.topic)
        if not pools:
            # topic not mapped to any pool: fan-in on the topic subject —
            # never direct-dispatch to workers whose pools don't serve it
            return req.topic
        placement = _placement_labels(labels)

        # direct worker hint — still subject to capability/placement checks so
        # a hint can never route a job to a worker that cannot run it
        preferred_worker = labels.get("preferred_worker_id", "")
        if preferred_worker:
            hb = self.registry.get(preferred_worker)
            if hb is not None and not is_overloaded(hb):
                pool = next((p for p in pools if p.name == hb.pool), None) if pools else None
                pool_ok = pool is not None or not pools
                placement_ok = all(hb.labels.get(k) == v for k, v in placement.items())
                if pool_ok and placement_ok and worker_satisfies(hb, pool, job_requires):
                    return direct_subject(preferred_worker)
        preferred_pool = labels.get("preferred_pool", "")
        if preferred_pool:
            hinted = [p for p in pools if p.name == preferred_pool]
            if hinted:
                pools = hinted

        # session affinity: a conversation's decode turns ride to the worker
        # holding its KV pages (the serving generalization of batch affinity;
        # explicit worker hints still win above)
        session_key = labels.get(LABEL_SESSION_KEY, "")
        session_akey = ""
        if session_key:
            session_akey = _SESSION_PREFIX + session_key
            had_entry = session_akey in self._affinity
            sticky = self._affinity_worker(
                session_akey, pools, job_requires, placement,
                ttl_s=SESSION_AFFINITY_TTL_S,
            )
            if sticky:
                self._count_session_affinity("hit")
                return direct_subject(sticky)
            # a dead entry (expired / evicted) means the session's pages are
            # on a worker we can no longer use: a true migration, vs "new"
            # for the first routing of a session
            self._count_session_affinity("miss" if had_entry else "new")

        # batch affinity: same-key jobs ride to the sticky worker so its
        # micro-batch queues fill (explicit worker hints still win above)
        batch_key = labels.get(LABEL_BATCH_KEY, "")
        if batch_key:
            sticky = self._affinity_worker(batch_key, pools, job_requires, placement)
            if sticky:
                if session_akey:
                    # a session-carrying job routed by its batch key (e.g. a
                    # workflow turn riding wf-tpl template co-location) must
                    # still elect its session entry, or every later turn of
                    # the run re-counts "new" and can never hit
                    self._record_affinity(session_akey, sticky)
                return direct_subject(sticky)

        # native packed scan (the hot path: no hints, uniform pools)
        if not placement and not preferred_worker:
            try:
                winner = self._native_pick(req, pools, job_requires)
                if winner:
                    if batch_key:
                        self._record_affinity(batch_key, winner)
                    if session_akey:
                        self._record_affinity(session_akey, winner)
                return direct_subject(winner) if winner else req.topic
            except LookupError:
                pass  # shapes the C kernel doesn't model → python scan

        best_worker = ""
        best_score = float("inf")
        for hb in self.registry.snapshot().values():
            # pool membership: worker's reported pool must be one of the
            # topic's pools (when the topic maps to pools at all)
            pool: Optional[Pool] = None
            if pools:
                matched = [p for p in pools if p.name == hb.pool]
                if not matched:
                    continue
                pool = matched[0]
            if not worker_satisfies(hb, pool, job_requires):
                # pools.yaml min_chips/topology/device_kind exclusions are
                # announced once per (worker, pool) — a worker dropped from
                # its OWN pool's routing is a config problem, not noise
                warn_pool_exclusion(hb, pool)
                continue
            if placement and any(hb.labels.get(k) != v for k, v in placement.items()):
                continue
            if is_overloaded(hb):
                continue
            score = load_score(hb)
            if score < best_score or (score == best_score and hb.worker_id < best_worker):
                best_score = score
                best_worker = hb.worker_id
        if best_worker:
            if batch_key:
                self._record_affinity(batch_key, best_worker)
            if session_akey:
                self._record_affinity(session_akey, best_worker)
            return direct_subject(best_worker)
        return req.topic


class ThroughputAwareStrategy(LeastLoadedStrategy):
    """Heterogeneity-aware routing on the measured throughput matrix
    (Gavel, PAPERS.md; ROADMAP item 1 — the capacity observatory's first
    data-plane consumer).

    Each job carrying the gateway-stamped ``cordum.op`` label routes to
    eligible workers in proportion to their measured **steady-state
    headroom** for that op: ``items/s × (1 − load_fraction)`` from the
    :class:`~cordum_tpu.obs.capacity.CapacityView`, distributed by smooth
    weighted round-robin (nginx-style: deterministic, starvation-free — a
    3× faster worker gets exactly 3× the jobs).  Workers the matrix has
    not measured for the op get the median measured weight so they receive
    traffic and *become* measured.

    Degradation ladder (each step is exact LeastLoaded behavior):
    affinity/hint/placement-labeled jobs delegate wholesale (sticky
    sessions beat throughput); ops with NO fresh measured row fall back to
    the LeastLoaded scan; an absent CapacityView disables the override
    entirely.
    """

    def __init__(self, registry: WorkerRegistry, pool_config: PoolConfig, *,
                 capacity=None, placer=None, native: bool = True,
                 metrics=None):
        super().__init__(registry, pool_config, native=native, metrics=metrics)
        self.capacity = capacity
        # role-aware serving placement (docs/SERVING.md §Disaggregation):
        # new llm.generate sessions route by measured prefill tokens/s
        # headroom instead of the generic items/s WRR
        self.placer = placer
        # smooth-WRR state per op: worker → current credit
        self._wrr: dict[str, dict[str, float]] = {}
        self.routed_measured = 0
        self.routed_fallback = 0
        self.routed_placed = 0

    _ROUTING_LABELS = ("preferred_worker_id", "preferred_pool",
                       LABEL_BATCH_KEY, LABEL_SESSION_KEY)

    def pick_subjects(self, reqs: list[JobRequest]) -> list[str]:
        # no shape memoization: the WRR must distribute jobs WITHIN a tick
        # (the parent's one-pick-per-shape would send a whole tick batch to
        # one worker, defeating proportional routing)
        return [self.pick_subject(r) for r in reqs]

    def _eligible_workers(self, req: JobRequest, pools,
                          job_requires) -> list[Heartbeat]:
        out: list[Heartbeat] = []
        for hb in self.registry.snapshot().values():
            pool = next((p for p in pools if p.name == hb.pool), None)
            if pool is None:
                continue
            if not worker_satisfies(hb, pool, job_requires):
                warn_pool_exclusion(hb, pool)
                continue
            if is_overloaded(hb):
                continue
            out.append(hb)
        return out

    def _pick_serving(self, req: JobRequest, labels: dict) -> str:
        """Role-aware placement for a serving job (docs/SERVING.md
        §Disaggregation).  A follow-up turn rides its session affinity to
        the page-holding worker (retargeted on migration); a NEW session
        goes to the placer's best measured prefill-headroom worker.
        Returns "" when the placer has nothing analytic to say — the
        caller degrades to the generic measured-items/s routing."""
        pools = self._pools_for_topic(req.topic)
        if not pools:
            return ""
        job_requires = list(req.metadata.requires) if req.metadata else []
        session_key = labels.get(LABEL_SESSION_KEY, "")
        session_akey = ""
        had_entry = False
        if session_key:
            session_akey = _SESSION_PREFIX + session_key
            had_entry = session_akey in self._affinity
            sticky = self._affinity_worker(
                session_akey, pools, job_requires, {},
                ttl_s=SESSION_AFFINITY_TTL_S,
            )
            if sticky:
                self._count_session_affinity("hit")
                return direct_subject(sticky)
        winner = self.placer.pick(
            self._eligible_workers(req, pools, job_requires),
            speculable=bool(labels.get(LABEL_SPECULABLE)),
        )
        if not winner:
            # no counting here: the caller's fallback re-runs the affinity
            # check and counts the outcome exactly once
            return ""
        if session_akey:
            self._count_session_affinity("miss" if had_entry else "new")
            self._record_affinity(session_akey, winner)
        self.routed_placed += 1
        return direct_subject(winner)

    def pick_subject(self, req: JobRequest) -> str:
        labels = req.labels or {}
        # serving jobs take the role-aware placement path FIRST: session
        # affinity is honored inside it (sticky turns beat throughput), and
        # only hint/placement-labeled jobs bypass it entirely
        if (
            self.placer is not None
            and labels.get(LABEL_OP, "") in SERVING_OPS
            and not labels.get("preferred_worker_id")
            and not labels.get("preferred_pool")
            and not labels.get(LABEL_BATCH_KEY)
            and not any(k.startswith("placement.") for k in labels)
        ):
            subject = self._pick_serving(req, labels)
            if subject:
                return subject
        if self.capacity is None or any(
            labels.get(k) for k in self._ROUTING_LABELS
        ) or any(k.startswith("placement.") for k in labels):
            return super().pick_subject(req)
        op = labels.get(LABEL_OP, "")
        if not op:
            return super().pick_subject(req)
        pools = self._pools_for_topic(req.topic)
        if not pools:
            return req.topic
        job_requires = list(req.metadata.requires) if req.metadata else []
        candidates = self._eligible_workers(req, pools, job_requires)
        if not candidates:
            return req.topic
        measured = {
            hb.worker_id: self.capacity.rate(hb.worker_id, op)
            for hb in candidates
        }
        rates = sorted(r for r in measured.values() if r > 0)
        if not rates:
            # matrix empty/stale for this op: exact LeastLoaded behavior
            self.routed_fallback += 1
            return super().pick_subject(req)
        median = rates[len(rates) // 2]
        weights: dict[str, float] = {}
        for hb in candidates:
            base = measured[hb.worker_id] or median
            if hb.max_parallel_jobs > 0:
                load_frac = min(1.0, hb.active_jobs / hb.max_parallel_jobs)
            else:
                load_frac = min(1.0, load_score(hb) / 16.0)
            weights[hb.worker_id] = base * max(0.1, 1.0 - load_frac)
        winner = self._wrr_pick(op, weights)
        self.routed_measured += 1
        return direct_subject(winner)

    def _wrr_pick(self, op: str, weights: dict[str, float]) -> str:
        """Smooth weighted round-robin: add each worker's weight to its
        credit, pick the max, subtract the total — selections converge to
        exact weight proportions with no randomness and no starvation."""
        if len(self._wrr) > 1024:
            self._wrr.clear()  # unbounded-op-space guard
        state = self._wrr.setdefault(op, {})
        for gone in [w for w in state if w not in weights]:
            del state[gone]
        total = sum(weights.values())
        best, best_credit = "", float("-inf")
        for wid, w in sorted(weights.items()):
            credit = state.get(wid, 0.0) + w
            state[wid] = credit
            if credit > best_credit:
                best, best_credit = wid, credit
        state[best] -= total
        return best
