"""Workflow-engine service: bus consumer + run reconciler.

Recreates the reference service (``core/controlplane/workflowengine/``):
subscribes ``sys.job.result`` in the ``cordum-workflow-engine`` queue group,
takes a per-run lock before advancing the run (NAK-with-delay on
contention — two consumers may converge on the same run), and a reconciler
loop that (a) resumes delay steps and parked retries whose time has come,
and (b) replays terminal job states from the JobStore into the engine for
results the service missed (crash between worker publish and engine apply).
"""
from __future__ import annotations

import asyncio
import random
import time
from typing import Optional

from ...infra import logging as logx
from ...infra.bus import Bus, MAX_NAK_DELAY_S, RetryAfter
from ...infra.jobstore import JobStore
from ...protocol import subjects as subj
from ...protocol.types import BusPacket, JobResult, JobState, TERMINAL_STATES
from ...workflow import models as M
from ...workflow.engine import Engine as WorkflowEngine, split_job_id

# base NAK delay for run-lock contention; doubles per redelivery with ±25 %
# jitter, capped at MAX_NAK_DELAY_S (the scheduler's tenant-NAK convention,
# docs/PROTOCOL.md §Subjects) — two replicas converging on one hot run
# de-synchronize instead of retrying in lockstep
RUN_LOCK_NAK_BASE_S = 0.05


class WorkflowEngineService:
    def __init__(
        self,
        *,
        engine: WorkflowEngine,
        bus: Bus,
        job_store: Optional[JobStore] = None,
        instance_id: str = "wf-svc-0",
        reconcile_interval_s: float = 5.0,
    ):
        self.engine = engine
        self.bus = bus
        self.job_store = job_store
        self.instance_id = instance_id
        self.reconcile_interval_s = reconcile_interval_s
        self._subs: list = []
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def start(self) -> None:
        self._subs.append(
            await self.bus.subscribe(
                subj.RESULT, self._on_result, queue=subj.QUEUE_WORKFLOW_ENGINE
            )
        )
        # under a sharded scheduler, workers echo the owning shard's
        # partition and publish on ``sys.job.result.<p>`` — without this
        # wildcard the engine would only advance runs via the reconciler's
        # JobStore replay (one reconcile interval of latency per step)
        self._subs.append(
            await self.bus.subscribe(
                f"{subj.RESULT}.>", self._on_result, queue=subj.QUEUE_WORKFLOW_ENGINE
            )
        )
        # context.* steps executed in-engine report on their own subject
        # (the scheduler must not see jobs it never dispatched); same queue
        # group, so any replica applies them under the run lock
        self._subs.append(
            await self.bus.subscribe(
                subj.STEP_RESULT, self._on_result, queue=subj.QUEUE_WORKFLOW_ENGINE
            )
        )
        self._stop.clear()
        self._task = asyncio.ensure_future(self._reconcile_loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        self._stop.set()
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="workflow-reconciler")
            self._task = None

    # ------------------------------------------------------------------
    async def _on_result(self, subject: str, pkt: BusPacket) -> None:
        res = pkt.job_result
        if res is None or not res.job_id:
            return
        await self.handle_job_result(res, redeliveries=pkt.redelivery_count)

    async def handle_job_result(self, res: JobResult, *, redeliveries: int = 0) -> None:
        try:
            run_id, _, _ = split_job_id(res.job_id)
        except ValueError:
            return  # not a workflow job
        if not await self.engine.store.acquire_run_lock(run_id, self.instance_id):
            delay = min(
                MAX_NAK_DELAY_S,
                RUN_LOCK_NAK_BASE_S * (2 ** max(0, redeliveries)),
            )
            delay *= 1.0 + random.uniform(-0.25, 0.25)
            raise RetryAfter(delay, f"run {run_id} locked")
        try:
            await self.engine.handle_job_result(res)
        finally:
            await self.engine.store.release_run_lock(run_id, self.instance_id)

    # ------------------------------------------------------------------
    async def _reconcile_loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.reconcile_once()
            except Exception:
                logx.error("workflow reconciler pass failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.reconcile_interval_s)
            except asyncio.TimeoutError:
                pass

    async def reconcile_once(self) -> int:
        """Resume due waits and replay missed terminal job states.

        The per-pass scan is batched: all status indexes are read in one
        concurrent zrange batch, and runs whose lock is already held are
        skipped off a single lock-prefix scan instead of paying a setnx
        round trip per busy run.  Pass cost lands in
        ``cordum_workflow_reconcile_seconds``; the live-run count feeds
        ``cordum_workflow_active_runs``."""
        t0 = time.monotonic()
        progressed = 0
        store = self.engine.store
        rows = await store.list_run_ids_by_statuses((M.PENDING, M.RUNNING, M.WAITING))
        metrics = self.engine.metrics
        metrics.workflow_active_runs.set(float(len({rid for _, rid in rows})))
        held = await store.held_run_locks() if rows else set()
        for _status, run_id in rows:
            if run_id in held:
                continue  # busy under another replica; next pass retries
            if not await store.acquire_run_lock(run_id, self.instance_id):
                continue  # lost a race since the prefix scan
            try:
                if await self.engine.resume_due(run_id):
                    progressed += 1
                if self.job_store is not None:
                    progressed += await self._replay_terminal_jobs(run_id)
            except Exception:
                # one poisoned run must not starve the rest of the pass
                logx.error("reconcile failed for run", run_id=run_id)
            finally:
                await store.release_run_lock(run_id, self.instance_id)
        metrics.workflow_reconcile_seconds.observe(time.monotonic() - t0)
        return progressed

    async def _replay_terminal_jobs(self, run_id: str) -> int:
        """If the JobStore saw a terminal state for a step's job but the run
        still shows it RUNNING, synthesize the JobResult and apply it."""
        run = await self.engine.store.get_run(run_id)
        if run is None:
            return 0
        n = 0
        for sr in run.steps.values():
            for t in [sr, *sr.children.values()]:
                if t.status != M.RUNNING or not t.job_id:
                    continue
                meta = await self.job_store.get_meta(t.job_id)
                state = meta.get("state", "")
                if state and state in (s.value for s in TERMINAL_STATES):
                    try:
                        execution_ms = int(meta.get("execution_ms", "0") or 0)
                    except ValueError:
                        execution_ms = 0
                    # the replay mirrors every JobResult field the live path
                    # persists (scheduler _result_fields); result labels are
                    # transport-only stream metadata the engine never reads,
                    # so the synthesized result carries the wire default
                    res = JobResult(
                        job_id=t.job_id,
                        status=state,
                        result_ptr=meta.get("result_ptr", ""),
                        worker_id=meta.get("worker_id", ""),
                        execution_ms=execution_ms,
                        error_code=meta.get("error_code", ""),
                        error_message=meta.get("error_message", ""),
                    )
                    await self.engine.handle_job_result(res)
                    n += 1
        return n
