"""Workflow-engine service: bus consumer + run reconciler.

Recreates the reference service (``core/controlplane/workflowengine/``):
subscribes ``sys.job.result`` in the ``cordum-workflow-engine`` queue group,
takes a per-run lock before advancing the run (NAK-with-delay on
contention — two consumers may converge on the same run), and a reconciler
loop that (a) resumes delay steps and parked retries whose time has come,
and (b) replays terminal job states from the JobStore into the engine for
results the service missed (crash between worker publish and engine apply).
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ...infra import logging as logx
from ...infra.bus import Bus, RetryAfter
from ...infra.jobstore import JobStore
from ...protocol import subjects as subj
from ...protocol.types import BusPacket, JobResult, JobState, TERMINAL_STATES
from ...workflow import models as M
from ...workflow.engine import Engine as WorkflowEngine, split_job_id


class WorkflowEngineService:
    def __init__(
        self,
        *,
        engine: WorkflowEngine,
        bus: Bus,
        job_store: Optional[JobStore] = None,
        instance_id: str = "wf-svc-0",
        reconcile_interval_s: float = 5.0,
    ):
        self.engine = engine
        self.bus = bus
        self.job_store = job_store
        self.instance_id = instance_id
        self.reconcile_interval_s = reconcile_interval_s
        self._subs: list = []
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def start(self) -> None:
        self._subs.append(
            await self.bus.subscribe(
                subj.RESULT, self._on_result, queue=subj.QUEUE_WORKFLOW_ENGINE
            )
        )
        self._stop.clear()
        self._task = asyncio.ensure_future(self._reconcile_loop())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        self._stop.set()
        if self._task:
            self._task.cancel()
            await logx.join_task(self._task, name="workflow-reconciler")
            self._task = None

    # ------------------------------------------------------------------
    async def _on_result(self, subject: str, pkt: BusPacket) -> None:
        res = pkt.job_result
        if res is None or not res.job_id:
            return
        await self.handle_job_result(res)

    async def handle_job_result(self, res: JobResult) -> None:
        try:
            run_id, _, _ = split_job_id(res.job_id)
        except ValueError:
            return  # not a workflow job
        if not await self.engine.store.acquire_run_lock(run_id, self.instance_id):
            raise RetryAfter(0.05, f"run {run_id} locked")
        try:
            await self.engine.handle_job_result(res)
        finally:
            await self.engine.store.release_run_lock(run_id, self.instance_id)

    # ------------------------------------------------------------------
    async def _reconcile_loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.reconcile_once()
            except Exception:
                logx.error("workflow reconciler pass failed")
            try:
                await asyncio.wait_for(self._stop.wait(), self.reconcile_interval_s)
            except asyncio.TimeoutError:
                pass

    async def reconcile_once(self) -> int:
        """Resume due waits and replay missed terminal job states."""
        progressed = 0
        for status in (M.PENDING, M.RUNNING, M.WAITING):
            for run_id in await self.engine.store.list_run_ids_by_status(status):
                if not await self.engine.store.acquire_run_lock(run_id, self.instance_id):
                    continue
                try:
                    if await self.engine.resume_due(run_id):
                        progressed += 1
                    if self.job_store is not None:
                        progressed += await self._replay_terminal_jobs(run_id)
                except Exception:
                    # one poisoned run must not starve the rest of the pass
                    logx.error("reconcile failed for run", run_id=run_id)
                finally:
                    await self.engine.store.release_run_lock(run_id, self.instance_id)
        return progressed

    async def _replay_terminal_jobs(self, run_id: str) -> int:
        """If the JobStore saw a terminal state for a step's job but the run
        still shows it RUNNING, synthesize the JobResult and apply it."""
        run = await self.engine.store.get_run(run_id)
        if run is None:
            return 0
        n = 0
        for sr in run.steps.values():
            for t in [sr, *sr.children.values()]:
                if t.status != M.RUNNING or not t.job_id:
                    continue
                meta = await self.job_store.get_meta(t.job_id)
                state = meta.get("state", "")
                if state and state in (s.value for s in TERMINAL_STATES):
                    res = JobResult(
                        job_id=t.job_id,
                        status=state,
                        result_ptr=meta.get("result_ptr", ""),
                        worker_id=meta.get("worker_id", ""),
                        error_code=meta.get("error_code", ""),
                        error_message=meta.get("error_message", ""),
                    )
                    await self.engine.handle_job_result(res)
                    n += 1
        return n
