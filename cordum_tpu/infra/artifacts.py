"""Artifact store with retention classes (reference
``core/infra/artifacts/store.go:5-27``: short/standard/audit retention →
TTLs; keys ``art:<id>``, pointers ``kv://art:<id>``)."""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from ..utils.ids import new_id, now_us
from .kv import KV, pointer_for_key

RETENTION_TTLS = {
    "short": 3600.0,
    "standard": 7 * 24 * 3600.0,
    "audit": 90 * 24 * 3600.0,
}


@dataclass
class ArtifactMetadata:
    artifact_id: str = ""
    content_type: str = "application/octet-stream"
    size: int = 0
    retention: str = "standard"
    labels: dict = field(default_factory=dict)
    created_at_us: int = 0


class ArtifactStore:
    def __init__(self, kv: KV) -> None:
        self.kv = kv

    async def put(
        self,
        data: bytes,
        *,
        artifact_id: str = "",
        content_type: str = "application/octet-stream",
        retention: str = "standard",
        labels: Optional[dict] = None,
    ) -> ArtifactMetadata:
        aid = artifact_id or new_id()
        ttl = RETENTION_TTLS.get(retention, RETENTION_TTLS["standard"])
        meta = ArtifactMetadata(
            artifact_id=aid,
            content_type=content_type,
            size=len(data),
            retention=retention,
            labels=labels or {},
            created_at_us=now_us(),
        )
        await self.kv.set(f"art:{aid}", data, ttl)
        await self.kv.set(f"art:meta:{aid}", json.dumps(meta.__dict__).encode(), ttl)
        return meta

    async def get(self, artifact_id: str) -> tuple[Optional[bytes], Optional[ArtifactMetadata]]:
        data = await self.kv.get(f"art:{artifact_id}")
        mb = await self.kv.get(f"art:meta:{artifact_id}")
        meta = ArtifactMetadata(**json.loads(mb)) if mb else None
        return data, meta

    @staticmethod
    def pointer(artifact_id: str) -> str:
        return pointer_for_key(f"art:{artifact_id}")
