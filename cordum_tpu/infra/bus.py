"""Message bus abstraction (the framework's NATS-equivalent).

The control plane communicates through subjects carrying serialized
``BusPacket`` envelopes.  Reference behavior being recreated
(``core/infra/bus/nats.go``):

  * queue groups: one subscriber per group receives each message; plain
    subscriptions fan out
  * wildcard subjects (``job.*``, ``sys.job.>``, ``worker.*.jobs``)
  * durable subjects get at-least-once semantics: a handler raising
    :class:`RetryAfter` triggers redelivery after the given delay (the
    JetStream NAK-with-delay path, nats.go:154-163); other exceptions are
    logged and acked (no redelivery)
  * msg-id dedupe window: duplicate publishes of the same job/worker-scoped
    message id inside the window are dropped (nats.go:404-435)

Implementations: :class:`LoopbackBus` (in-process; also the integration-test
bus, mirroring the reference's loopback test bus pattern,
``scheduler/integration_test.go:18-46``) and the TCP statebus bus for
multi-process deployments.
"""
from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from ..protocol import subjects as subj
from ..protocol.types import BusPacket, LABEL_APPROVAL_GRANTED, LABEL_BUS_MSG_ID
from ..utils.globmatch import subject_match

log = logging.getLogger("cordum.bus")

Handler = Callable[[str, BusPacket], Awaitable[None]]

DEDUP_WINDOW_S = 120.0  # JetStream 2m dedup window equivalent
MAX_REDELIVERIES = 5
MAX_NAK_DELAY_S = 30.0  # cap on a single RetryAfter backoff sleep


class RetryAfter(Exception):
    """Raise from a durable-subject handler to request redelivery after a
    delay (reference scheduler/retry.go:9-47)."""

    def __init__(self, delay_s: float, reason: str = "") -> None:
        super().__init__(reason or f"retry after {delay_s}s")
        self.delay_s = delay_s


class _AttrGetter:
    """dict.get-shaped view over an object's attributes (msg-id derivation
    works on raw wire dicts AND typed payloads through one code path)."""

    __slots__ = ("_obj",)

    def __init__(self, obj: Any) -> None:
        self._obj = obj

    def __call__(self, name: str, default: Any = "") -> Any:
        return getattr(self._obj, name, default)


def compute_msg_id(subject: str, pkt: BusPacket) -> str:
    """Stable message id for dedupe: explicit label override, else derived
    from the payload's job/worker identity (reference nats.go:404-435).

    Works on the *raw* payload dict of a lazily decoded packet so the
    dedupe/routing path never forces the dataclass conversion."""
    p = pkt.raw_payload
    if p is None:
        p = pkt.payload
    if type(p) is dict:
        get = p.get
    else:
        get = _AttrGetter(p)
    labels = get("labels", None) or {}
    if isinstance(labels, dict):
        override = labels.get(LABEL_BUS_MSG_ID)
        if override:
            return f"{subject}|{override}"
    # spans: every span id is unique, so it IS the dedupe identity — two
    # spans of one trace finishing in the same microsecond must not collide
    # on the trace_id/created_at fall-through below
    span_id = get("span_id", "")
    if span_id:
        return f"{subject}|{pkt.kind}|{span_id}"
    job_id = get("job_id", "")
    if job_id:
        # Approval republishes reuse the job_id on the submit subject and must
        # NOT dedupe against the original submit — nor against each other (a
        # rejected tampered republish must not suppress the real approval),
        # so they are time-bucketed instead.  The engine's terminal
        # short-circuit + hash check make re-processing them idempotent.
        if isinstance(labels, dict) and labels.get(LABEL_APPROVAL_GRANTED) == "true":
            return f"{subject}|{pkt.kind}|{job_id}|approved|{pkt.created_at_us}"
        # Results carry a status: a terminal result must not dedupe against an
        # earlier non-terminal RUNNING hint for the same job.
        status = get("status", "")
        if status:
            return f"{subject}|{pkt.kind}|{job_id}|{status}"
        return f"{subject}|{pkt.kind}|{job_id}"
    worker_id = get("worker_id", "")
    if worker_id:
        # heartbeats must not dedupe against each other: include time bucket
        return f"{subject}|{pkt.kind}|{worker_id}|{pkt.created_at_us}"
    return f"{subject}|{pkt.kind}|{pkt.trace_id}|{pkt.created_at_us}"


@dataclass
class _Subscription:
    pattern: str
    handler: Handler
    queue: Optional[str]
    sid: int
    closed: bool = False


class Bus:
    """Async pub/sub interface."""

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        raise NotImplementedError

    async def subscribe(
        self, pattern: str, handler: Handler, *, queue: Optional[str] = None
    ) -> "Subscription":
        raise NotImplementedError

    async def close(self) -> None:
        return None

    async def ping(self) -> bool:
        return True

    def has_listener(self, subject: str) -> bool:
        """Best-effort hint: may anything receive a publish to ``subject``?
        Wire-backed buses can't know their remote subscribers, so the
        default is the conservative True; the in-process bus answers
        exactly, letting hot-path publishers (span emission) skip building
        packets nobody will ever see."""
        return True


class Subscription:
    def __init__(self, unsub: Callable[[], None]) -> None:
        self._unsub = unsub

    def unsubscribe(self) -> None:
        self._unsub()


class LoopbackBus(Bus):
    """In-process bus.

    ``durable=True`` (default) gives at-least-once semantics on durable
    subjects: delivery happens on background tasks, RetryAfter causes delayed
    redelivery, and publishes are deduped by msg-id inside the window.
    ``sync=True`` delivers inline in ``publish`` (deterministic unit tests).
    """

    def __init__(self, *, sync: bool = False, durable: bool = True) -> None:
        self._subs: list[_Subscription] = []
        # exact-pattern index: most subscriptions are concrete subjects, and
        # matching every publish against every pattern (N_subs × N_publishes
        # subject_match calls) was a measurable slice of the 1×1 hot path
        self._exact: dict[str, list[_Subscription]] = {}
        self._wild: list[_Subscription] = []
        # subject → matched-subscription cache: with any wildcard subscriber
        # attached (gateway sys.job.> tap, telemetry aggregator), every
        # publish re-ran subject_match per wildcard — measurably ~3-8% of
        # the 1×1 hot path.  The subject set is small and stable, so the
        # match is computed once per subject and invalidated on any
        # (un)subscribe.
        self._target_cache: dict[str, list[_Subscription]] = {}
        self._sid = itertools.count(1)
        self._rr: dict[tuple[str, str], int] = {}
        self._sync = sync
        self._durable = durable
        self._dedup: dict[str, float] = {}
        self._tasks: set[asyncio.Task] = set()
        self._closed = False
        self.published: list[tuple[str, BusPacket]] = []  # test observability

    async def subscribe(
        self, pattern: str, handler: Handler, *, queue: Optional[str] = None
    ) -> Subscription:
        sub = _Subscription(pattern, handler, queue, next(self._sid))
        self._subs.append(sub)
        if "*" in pattern or ">" in pattern:
            self._wild.append(sub)
        else:
            self._exact.setdefault(pattern, []).append(sub)
        self._target_cache.clear()

        def _unsub() -> None:
            sub.closed = True
            if sub in self._subs:
                self._subs.remove(sub)
            if sub in self._wild:
                self._wild.remove(sub)
            bucket = self._exact.get(sub.pattern)
            if bucket and sub in bucket:
                bucket.remove(sub)
                if not bucket:
                    del self._exact[sub.pattern]
            self._target_cache.clear()

        return Subscription(_unsub)

    def _matched(self, subject: str) -> list[_Subscription]:
        matched = self._target_cache.get(subject)
        if matched is None:
            matched = [s for s in self._exact.get(subject, ()) if not s.closed]
            if self._wild:
                matched += [
                    s for s in self._wild
                    if not s.closed and subject_match(s.pattern, subject)
                ]
            if len(self._target_cache) > 4096:  # unbounded-subject backstop
                self._target_cache.clear()
            self._target_cache[subject] = matched
        return matched

    def has_listener(self, subject: str) -> bool:
        return bool(self._matched(subject))

    def _targets(self, subject: str) -> list[_Subscription]:
        matched = self._matched(subject)
        if not matched:
            return matched
        # collapse queue groups to one member (round-robin)
        out: list[_Subscription] = []
        groups: dict[tuple[str, str], list[_Subscription]] = {}
        for s in matched:
            if s.queue is None:
                out.append(s)
            else:
                groups.setdefault((s.pattern, s.queue), []).append(s)
        for key, members in groups.items():
            i = self._rr.get(key, 0)
            out.append(members[i % len(members)])
            self._rr[key] = i + 1
        return out

    def _dedup_hit(self, subject: str, pkt: BusPacket) -> bool:
        if not subj.is_durable_subject(subject):
            return False
        mid = compute_msg_id(subject, pkt)
        now = time.monotonic()
        # amortized prune: evict the oldest half (insertion-ordered dict)
        if len(self._dedup) > 8192:
            for k in list(itertools.islice(self._dedup, 4096)):
                del self._dedup[k]
        seen = self._dedup.get(mid)
        if seen is not None and now - seen < DEDUP_WINDOW_S:
            return True
        self._dedup[mid] = now
        return False

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        if self._closed:
            return
        targets = self._targets(subject)
        if not targets:
            # nobody listening: skip dedupe bookkeeping AND the
            # encode/decode round trip (delivery happens at publish time,
            # so an unheard message is dropped either way)
            self.published.append((subject, pkt))
            return
        if self._durable and self._dedup_hit(subject, pkt):
            return
        self.published.append((subject, pkt))
        # round-trip through the wire format so both sides see the same shapes
        wire = pkt.to_wire()
        for sub in targets:
            decoded = BusPacket.from_wire(wire)
            if self._sync:
                await self._deliver(sub, subject, decoded)
            else:
                t = asyncio.ensure_future(self._deliver(sub, subject, decoded))
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)

    async def _deliver(self, sub: _Subscription, subject: str, pkt: BusPacket) -> None:
        # Iterative redelivery loop: the old recursive form grew one stack
        # frame per NAK, so a hot RetryAfter cycle (delay≈0) walked toward
        # the recursion limit across MAX_REDELIVERIES; the requested delay
        # is additionally capped so a handler can't park the delivery task
        # arbitrarily long.
        attempt = 1
        while True:
            try:
                await sub.handler(subject, pkt)
                return
            except RetryAfter as ra:
                durable = self._durable and subj.is_durable_subject(subject)
                if not durable or attempt >= MAX_REDELIVERIES or sub.closed or self._closed:
                    log.warning("dropping message on %s after %d attempts", subject, attempt)
                    return
                attempt += 1
                # handlers read this to back off exponentially (the tenant-
                # concurrency NAK path) instead of NAKing at a fixed cadence
                pkt.redelivery_count = attempt - 1
                await asyncio.sleep(min(max(ra.delay_s, 0.0), MAX_NAK_DELAY_S))
            except Exception:
                log.exception("handler error on %s (acked; no redelivery)", subject)
                return

    async def drain(self) -> None:
        """Wait for all in-flight async deliveries (tests)."""
        while True:
            pending = [t for t in list(self._tasks) if not t.done()]
            if not pending:
                break
            await asyncio.gather(*pending, return_exceptions=True)

    async def close(self) -> None:
        self._closed = True
        for t in list(self._tasks):
            t.cancel()
        self._subs.clear()
        self._exact.clear()
        self._wild.clear()
        self._target_cache.clear()
