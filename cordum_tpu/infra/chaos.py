"""Fault-injection harness for statebus replication/failover testing.

Reusable building blocks for chaos tests (tests/test_chaos.py, pytest
marker ``chaos``) and operator drills:

* :class:`ChaosProxy` — a TCP proxy that sits between a client and a
  statebus endpoint and, on command, **delays**, **black-holes** (traffic
  stalls but the connection stays open: the half-open/dead-host failure
  mode that only liveness pings catch), **half-closes**, **severs** (RST
  every live connection once) or **drops** (sever + refuse new
  connections) the link — then ``restore()``s it.
* :class:`ServerProc` — deterministic kill/restart around a real
  ``python -m cordum_tpu.cmd.statebus`` subprocess: SIGKILL for crash
  semantics (no GOAWAY, no flush beyond the AOF's per-record policy),
  SIGTERM for the graceful path, and a readiness probe so restarts are
  race-free.

Everything here is asyncio-native and port-0 friendly so chaos tests can
run inside one pytest process without fixed ports.
"""
from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

from . import logging as logx

_MODES = ("pass", "delay", "blackhole", "drop")


def free_port() -> int:
    """An OS-assigned free TCP port (bind-and-release)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Pipe:
    """One direction of one proxied connection."""

    def __init__(self, proxy: "ChaosProxy", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
        self.proxy = proxy
        self.reader = reader
        self.writer = writer
        self.task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        try:
            while True:
                # black-hole gate: bytes stall here (kernel buffers fill,
                # the peer sees a live-but-silent connection) until restore
                await self.proxy._gate.wait()
                chunk = await self.reader.read(65536)
                if not chunk:
                    break
                if self.proxy.delay_s > 0:
                    await asyncio.sleep(self.proxy.delay_s)
                # re-check after the (possibly long) read: a blackhole set
                # while we were blocked reading must hold THIS chunk too —
                # without it one in-flight chunk leaks through the gate,
                # making loss-window tests racy
                await self.proxy._gate.wait()
                self.writer.write(chunk)
                await self.writer.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self.writer.close()
            except (OSError, RuntimeError):
                pass  # transport already torn down


class ChaosProxy:
    """Controllable TCP proxy in front of one ``(host, port)`` target."""

    def __init__(self, target_host: str, target_port: int, *,
                 listen_host: str = "127.0.0.1", listen_port: int = 0) -> None:
        self.target_host = target_host
        self.target_port = target_port
        self.listen_host = listen_host
        self.port = listen_port
        self.mode = "pass"
        self.delay_s = 0.0
        self.connections_total = 0
        self._server: Optional[asyncio.base_events.Server] = None
        self._pipes: list[_Pipe] = []
        self._writers: list[asyncio.StreamWriter] = []
        self._gate = asyncio.Event()
        self._gate.set()

    @property
    def url(self) -> str:
        return f"statebus://{self.listen_host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self.listen_host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logx.info("chaos proxy listening", port=self.port,
                  target=f"{self.target_host}:{self.target_port}")

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        self.sever()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        if self.mode == "drop":
            writer.close()  # accept-then-reset: the endpoint looks dead
            return
        try:
            up_reader, up_writer = await asyncio.open_connection(
                self.target_host, self.target_port)
        except (OSError, ConnectionError):
            writer.close()
            return
        self.connections_total += 1
        self._writers.extend((writer, up_writer))
        pipes = [_Pipe(self, reader, up_writer), _Pipe(self, up_reader, writer)]
        self._pipes.extend(pipes)
        await asyncio.gather(*(p.task for p in pipes), return_exceptions=True)

    # -- failure controls ------------------------------------------------
    def set_delay(self, seconds: float) -> None:
        """Add per-chunk latency in BOTH directions (keeps ordering)."""
        self.delay_s = max(0.0, seconds)
        self.mode = "delay" if self.delay_s > 0 else "pass"

    def blackhole(self) -> None:
        """Stop forwarding without closing anything: connections stay
        ESTABLISHED but go silent — the failure mode a crashed host behind
        a switch produces, detectable only by liveness pings."""
        self.mode = "blackhole"
        self._gate.clear()

    def sever(self) -> None:
        """RST every live proxied connection once (new ones still accepted
        in the current mode)."""
        for p in self._pipes:
            p.task.cancel()
        for w in self._writers:
            try:
                w.close()
            except (OSError, RuntimeError):
                pass  # transport already torn down
        self._pipes.clear()
        self._writers.clear()

    def drop(self) -> None:
        """Sever everything AND refuse (accept-then-reset) new connections
        until ``restore()`` — the endpoint looks hard-down."""
        self.mode = "drop"
        self._gate.set()
        self.sever()

    def restore(self) -> None:
        """Back to transparent pass-through for current + new connections."""
        self.mode = "pass"
        self.delay_s = 0.0
        self._gate.set()


class ServerProc:
    """A real ``cmd.statebus`` subprocess with deterministic kill/restart.

    ``env`` carries the statebus configuration (STATEBUS_PORT,
    STATEBUS_AOF, STATEBUS_REPLICA_OF, STATEBUS_PEERS, ...).  ``start()``
    blocks until the server answers a ``role`` probe, so tests never race
    the bind; ``kill()`` is SIGKILL (crash semantics: no GOAWAY, no final
    fsync); ``terminate()`` is SIGTERM (graceful path).
    """

    def __init__(self, port: int, *, env: Optional[dict] = None,
                 cwd: str = "") -> None:
        self.port = port
        self.env = dict(env or {})
        self.cwd = cwd or os.getcwd()
        self.proc: Optional[subprocess.Popen] = None

    async def start(self, *, timeout_s: float = 20.0) -> None:
        from .replication import probe_role

        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "STATEBUS_PORT": str(self.port), **self.env}
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cordum_tpu.cmd.statebus"],
            env=env, cwd=self.cwd)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"statebus proc exited rc={self.proc.returncode} during start")
            if await probe_role("127.0.0.1", self.port, timeout_s=0.5) is not None:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(f"statebus on :{self.port} never became ready")

    def kill(self) -> None:
        """SIGKILL: the process dies mid-whatever — the crash the
        replication layer exists to survive."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def terminate(self) -> None:
        """SIGTERM: graceful shutdown (AOF fsync + GOAWAY broadcast)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)

    async def restart(self, *, timeout_s: float = 20.0) -> None:
        self.kill()
        await self.start(timeout_s=timeout_s)

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None
