"""Fault-injection harness for statebus replication/failover testing.

Reusable building blocks for chaos tests (tests/test_chaos.py, pytest
marker ``chaos``) and operator drills:

* :class:`ChaosProxy` — a TCP proxy that sits between a client and a
  statebus endpoint and, on command, **delays**, **black-holes** (traffic
  stalls but the connection stays open: the half-open/dead-host failure
  mode that only liveness pings catch), **half-closes**, **severs** (RST
  every live connection once) or **drops** (sever + refuse new
  connections) the link — then ``restore()``s it.  Delay, blackhole and
  sever take a ``direction`` (``"both"`` | ``"c2s"`` | ``"s2c"``) so
  ASYMMETRIC partitions are expressible: requests flow but replies stall,
  acks vanish while data keeps arriving — the failure modes a migration
  handshake must survive (docs/SERVING.md §Migration).
* :class:`WorkerProc` — deterministic kill/restart around a real
  ``python -m cordum_tpu.cmd.worker`` subprocess (SIGKILL = the crash the
  serving-session failover path exists to survive; SIGTERM = graceful
  drain).
* :class:`ServerProc` — deterministic kill/restart around a real
  ``python -m cordum_tpu.cmd.statebus`` subprocess: SIGKILL for crash
  semantics (no GOAWAY, no flush beyond the AOF's per-record policy),
  SIGTERM for the graceful path, and a readiness probe so restarts are
  race-free.

Everything here is asyncio-native and port-0 friendly so chaos tests can
run inside one pytest process without fixed ports.
"""
from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

from . import logging as logx

_MODES = ("pass", "delay", "blackhole", "drop")


def free_port() -> int:
    """An OS-assigned free TCP port (bind-and-release)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _DirState:
    """Fault state for ONE direction of the proxied link (client→server or
    server→client): its blackhole gate and per-chunk delay."""

    __slots__ = ("gate", "delay_s")

    def __init__(self) -> None:
        self.gate = asyncio.Event()
        self.gate.set()
        self.delay_s = 0.0


class _Pipe:
    """One direction of one proxied connection."""

    def __init__(self, proxy: "ChaosProxy", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, direction: str) -> None:
        self.proxy = proxy
        self.reader = reader
        self.writer = writer
        self.direction = direction  # "c2s" | "s2c"
        self.task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        state = self.proxy._dirs[self.direction]
        try:
            while True:
                # black-hole gate: bytes stall here (kernel buffers fill,
                # the peer sees a live-but-silent connection) until restore
                await state.gate.wait()
                chunk = await self.reader.read(65536)
                if not chunk:
                    break
                if state.delay_s > 0:
                    await asyncio.sleep(state.delay_s)
                # re-check after the (possibly long) read: a blackhole set
                # while we were blocked reading must hold THIS chunk too —
                # without it one in-flight chunk leaks through the gate,
                # making loss-window tests racy
                await state.gate.wait()
                self.writer.write(chunk)
                await self.writer.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self.writer.close()
            except (OSError, RuntimeError):
                pass  # transport already torn down


_DIRECTIONS = ("c2s", "s2c")


def _dirs_for(direction: str) -> tuple[str, ...]:
    if direction == "both":
        return _DIRECTIONS
    if direction not in _DIRECTIONS:
        raise ValueError(f"direction must be both|c2s|s2c, got {direction!r}")
    return (direction,)


class ChaosProxy:
    """Controllable TCP proxy in front of one ``(host, port)`` target."""

    def __init__(self, target_host: str, target_port: int, *,
                 listen_host: str = "127.0.0.1", listen_port: int = 0) -> None:
        self.target_host = target_host
        self.target_port = target_port
        self.listen_host = listen_host
        self.port = listen_port
        self.mode = "pass"
        self.connections_total = 0
        self._server: Optional[asyncio.base_events.Server] = None
        self._pipes: list[_Pipe] = []
        self._writers: list[asyncio.StreamWriter] = []
        self._dirs: dict[str, _DirState] = {d: _DirState() for d in _DIRECTIONS}

    @property
    def delay_s(self) -> float:
        """Back-compat view: the max per-direction delay."""
        return max(s.delay_s for s in self._dirs.values())

    @property
    def url(self) -> str:
        return f"statebus://{self.listen_host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self.listen_host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logx.info("chaos proxy listening", port=self.port,
                  target=f"{self.target_host}:{self.target_port}")

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        self.sever()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        if self.mode == "drop":
            writer.close()  # accept-then-reset: the endpoint looks dead
            return
        try:
            up_reader, up_writer = await asyncio.open_connection(
                self.target_host, self.target_port)
        except (OSError, ConnectionError):
            writer.close()
            return
        self.connections_total += 1
        self._writers.extend((writer, up_writer))
        pipes = [_Pipe(self, reader, up_writer, "c2s"),
                 _Pipe(self, up_reader, writer, "s2c")]
        self._pipes.extend(pipes)
        await asyncio.gather(*(p.task for p in pipes), return_exceptions=True)

    # -- failure controls ------------------------------------------------
    # `direction` selects which half of the link the fault hits: "c2s"
    # (requests/data toward the server), "s2c" (replies/acks toward the
    # client), or "both".  Asymmetric faults are what distinguish "the
    # peer is dead" from "the peer is alive but I can't hear it" — the
    # cases a (session, offset) handshake must not confuse.
    def set_delay(self, seconds: float, direction: str = "both") -> None:
        """Add per-chunk latency in the given direction(s) (keeps ordering)."""
        for d in _dirs_for(direction):
            self._dirs[d].delay_s = max(0.0, seconds)
        self.mode = "delay" if self.delay_s > 0 else "pass"

    def blackhole(self, direction: str = "both") -> None:
        """Stop forwarding (in the given direction(s)) without closing
        anything: connections stay ESTABLISHED but go silent — the failure
        mode a crashed host behind a switch produces, detectable only by
        liveness pings.  ``direction="s2c"`` models the asymmetric partition
        where requests arrive but replies vanish."""
        self.mode = "blackhole"
        for d in _dirs_for(direction):
            self._dirs[d].gate.clear()

    def sever(self, direction: str = "both") -> None:
        """RST the live proxied flows (new connections still accepted in
        the current mode).  With a single direction this is a half-close:
        only that flow's pipes die; the opposite direction keeps moving
        until the endpoint reacts."""
        dirs = set(_dirs_for(direction))
        keep: list[_Pipe] = []
        for p in self._pipes:
            if p.direction in dirs:
                p.task.cancel()
                try:
                    p.writer.close()
                except (OSError, RuntimeError):
                    pass  # transport already torn down
            else:
                keep.append(p)
        self._pipes = keep
        if direction == "both":
            for w in self._writers:
                try:
                    w.close()
                except (OSError, RuntimeError):
                    pass  # transport already torn down
            self._writers.clear()

    def drop(self) -> None:
        """Sever everything AND refuse (accept-then-reset) new connections
        until ``restore()`` — the endpoint looks hard-down."""
        self.mode = "drop"
        for s in self._dirs.values():
            s.gate.set()
        self.sever()

    def restore(self) -> None:
        """Back to transparent pass-through for current + new connections,
        in both directions."""
        self.mode = "pass"
        for s in self._dirs.values():
            s.delay_s = 0.0
            s.gate.set()


class ServerProc:
    """A real ``cmd.statebus`` subprocess with deterministic kill/restart.

    ``env`` carries the statebus configuration (STATEBUS_PORT,
    STATEBUS_AOF, STATEBUS_REPLICA_OF, STATEBUS_PEERS, ...).  ``start()``
    blocks until the server answers a ``role`` probe, so tests never race
    the bind; ``kill()`` is SIGKILL (crash semantics: no GOAWAY, no final
    fsync); ``terminate()`` is SIGTERM (graceful path).
    """

    def __init__(self, port: int, *, env: Optional[dict] = None,
                 cwd: str = "") -> None:
        self.port = port
        self.env = dict(env or {})
        self.cwd = cwd or os.getcwd()
        self.proc: Optional[subprocess.Popen] = None

    async def start(self, *, timeout_s: float = 20.0) -> None:
        from .replication import probe_role

        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "STATEBUS_PORT": str(self.port), **self.env}
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cordum_tpu.cmd.statebus"],
            env=env, cwd=self.cwd)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"statebus proc exited rc={self.proc.returncode} during start")
            if await probe_role("127.0.0.1", self.port, timeout_s=0.5) is not None:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(f"statebus on :{self.port} never became ready")

    def kill(self) -> None:
        """SIGKILL: the process dies mid-whatever — the crash the
        replication layer exists to survive."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def terminate(self) -> None:
        """SIGTERM: graceful shutdown (AOF fsync + GOAWAY broadcast)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)

    async def restart(self, *, timeout_s: float = 20.0) -> None:
        self.kill()
        await self.start(timeout_s=timeout_s)

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class WorkerProc:
    """A real ``cmd.worker`` subprocess with deterministic kill semantics —
    the serving-fleet half of the chaos harness (docs/SERVING.md
    §Migration, drain, and failover).

    ``env`` carries the worker configuration (WORKER_ID,
    CORDUM_STATEBUS_URL, WORKER_SERVING_*, ...); CPU is always forced so
    chaos runs never claim a TPU grant.  ``kill()`` is SIGKILL (a crashed
    worker: heartbeats just stop, sessions strand until the scheduler's
    WorkerFailover notices); ``terminate()`` is SIGTERM (graceful drain:
    sessions live-migrate to peers before exit).  Readiness is the
    caller's job — poll the scheduler registry or tail the log for the
    worker's first heartbeat."""

    def __init__(self, worker_id: str, *, env: Optional[dict] = None,
                 cwd: str = "", log_path: str = "") -> None:
        self.worker_id = worker_id
        self.env = dict(env or {})
        self.cwd = cwd or os.getcwd()
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_f = None

    def start(self) -> None:
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "CORDUM_FORCE_CPU": "1",
               "WORKER_ID": self.worker_id, **self.env}
        out = None
        if self.log_path:
            self._log_f = open(self.log_path, "ab")
            out = self._log_f
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cordum_tpu.cmd.worker"],
            env=env, cwd=self.cwd, stdout=out, stderr=out)

    def kill(self) -> None:
        """SIGKILL: the crash mid-decode that serving-session failover
        exists to survive — no drain, no final heartbeat, nothing."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)
        self._close_log()

    def terminate(self, timeout_s: float = 60.0) -> None:
        """SIGTERM: graceful drain (live-migrate sessions, finish jobs,
        exit)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)
        self._close_log()

    def _close_log(self) -> None:
        if self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
            self._log_f = None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None
