"""Hot-path record codec: msgpack with legacy-JSON read compatibility.

The wire protocol (BusPacket, statebus frames) has always been msgpack;
until ISSUE 6 the jobstore's *stored* records — event-log entries, safety
decisions, approvals — were still ``json.dumps``/``json.loads``, which was a
measurable slice of the 1×1 scheduler hot path (cordumlint CL007 now keeps
JSON out of those modules).  This module is the one place that:

* encodes records as msgpack (``pack_record``),
* decodes either encoding (``unpack_record``): new msgpack records AND
  legacy JSON blobs written by pre-ISSUE-6 builds, so old AOF/KV data keeps
  loading after an upgrade (JSON documents start with ``{``/``[``/``"`` or a
  digit-ish prefix that msgpack would mis-read as a fixint, so the sniff is
  on the JSON side), and
* owns the *contract* JSON that deliberately stays JSON (values embedded in
  worker env vars), with an interning cache so the scheduler doesn't
  re-parse the same effective-config string once per job.
"""
from __future__ import annotations

import json
from typing import Any, Optional

import msgpack

# Legacy jobstore records were produced by json.dumps(dict) — they always
# start with one of these bytes (allowing leading whitespace).
_JSON_HEADS = frozenset(b"{[\"")


def pack_record(obj: Any) -> bytes:
    """Encode a stored record (event-log entry, decision, approval)."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack_record(b: bytes) -> Any:
    """Decode a stored record written by this build (msgpack) or a
    pre-ISSUE-6 build (JSON)."""
    head = b.lstrip()[:1] if b else b""
    if head and head[0] in _JSON_HEADS:
        return json.loads(b)
    return msgpack.unpackb(b, raw=False)


# ---------------------------------------------------------------------------
# contract JSON (worker env vars) — stays JSON, parsed/encoded here so the
# hot-path modules stay msgpack-only under CL007
# ---------------------------------------------------------------------------

_PARSE_CACHE: dict[str, Any] = {}
_PARSE_CACHE_CAP = 256


def dumps_env_json(obj: Any, *, sort_keys: bool = False) -> str:
    """JSON for values embedded in worker env vars (CORDUM_POLICY_CONSTRAINTS
    etc.) — the env contract is JSON so non-Python workers can read it."""
    return json.dumps(obj, sort_keys=sort_keys)


def loads_env_json(s: str) -> Optional[Any]:
    """Parse a JSON env-contract string, interning the result: the scheduler
    sees the same effective-config string once per job, so the parse is
    cached by the exact string.  Callers MUST treat the returned object as
    read-only (it is shared across calls).  Returns None on invalid JSON."""
    hit = _PARSE_CACHE.get(s)
    if hit is not None:
        return hit
    try:
        parsed = json.loads(s)
    except (ValueError, TypeError):
        return None
    if len(_PARSE_CACHE) >= _PARSE_CACHE_CAP:
        _PARSE_CACHE.clear()  # tiny cache; wholesale reset is fine
    _PARSE_CACHE[s] = parsed
    return parsed
