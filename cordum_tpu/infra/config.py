"""Static configuration: env vars + YAML files.

Three tiers as in the reference (SURVEY.md §5 "Config/flag system"):
env (:func:`load`), YAML files (pools/timeouts here; safety policy lives in
``controlplane.safetykernel.policy``), and the dynamic config service
(:mod:`cordum_tpu.infra.configsvc`).

TPU-first pools: a pool may declare ``requires`` (capabilities like ``tpu``),
plus slice constraints — ``min_chips``, ``topology`` — that the slice-aware
strategy checks against worker heartbeats (reference pools parser:
``core/infra/config/pools.go:12-110``; TPU fields are the north-star
extension from BASELINE.json).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import yaml


@dataclass
class Config:
    # one endpoint, or a comma-separated list of statebus partition
    # endpoints (infra.statebus.connect_partitioned routes by keyspace)
    statebus_url: str = ""
    safety_kernel_addr: str = ""
    pool_config_path: str = ""
    timeout_config_path: str = ""
    safety_policy_path: str = ""
    context_engine_addr: str = ""
    gateway_http_addr: str = "127.0.0.1:8081"
    metrics_addr: str = ""
    api_keys: list[str] = field(default_factory=list)
    log_format: str = ""
    # scheduler keyspace sharding: total shard count the publishers stamp
    # partitions for (CORDUM_SCHEDULER_SHARDS; pools.yaml `scheduler.shards`
    # overrides for the scheduler binary itself)
    scheduler_shards: int = 1


def load() -> Config:
    env = os.environ
    keys = [k.strip() for k in env.get("CORDUM_API_KEYS", env.get("CORDUM_API_KEY", "")).split(",") if k.strip()]
    return Config(
        statebus_url=env.get("CORDUM_STATEBUS_URL", ""),
        safety_kernel_addr=env.get("SAFETY_KERNEL_ADDR", ""),
        pool_config_path=env.get("POOL_CONFIG_PATH", "config/pools.yaml"),
        timeout_config_path=env.get("TIMEOUT_CONFIG_PATH", "config/timeouts.yaml"),
        safety_policy_path=env.get("SAFETY_POLICY_PATH", "config/safety.yaml"),
        context_engine_addr=env.get("CONTEXT_ENGINE_ADDR", ""),
        gateway_http_addr=env.get("GATEWAY_HTTP_ADDR", "127.0.0.1:8081"),
        metrics_addr=env.get("METRICS_ADDR", ""),
        api_keys=keys,
        log_format=env.get("CORDUM_LOG_FORMAT", ""),
        scheduler_shards=max(1, int(env.get("CORDUM_SCHEDULER_SHARDS", "1") or 1)),
    )


# ---------------------------------------------------------------------------
# pools
# ---------------------------------------------------------------------------


@dataclass
class Pool:
    name: str
    requires: list[str] = field(default_factory=list)
    max_parallel_jobs: int = 0  # 0 = worker-reported
    # TPU slice constraints (north-star: slice-aware routing over a v5p pod)
    min_chips: int = 0
    topology: str = ""  # e.g. "2x2x1"; empty = any
    device_kind: str = ""  # e.g. "TPU v5p"; empty = any
    # micro-batching limits for this pool's workers (cordum_tpu/batching);
    # 0 = the worker's built-in defaults
    max_batch_size: int = 0  # rows per flushed XLA call
    max_batch_wait_ms: float = 0.0  # adaptive-window ceiling
    # serving limits for this pool's workers (cordum_tpu/serving,
    # docs/SERVING.md); 0 = the worker's built-in defaults
    serving_cache_pages: int = 0  # KV page-arena size (page 0 is reserved)
    serving_page_size: int = 0  # token slots per page
    serving_max_sessions: int = 0  # concurrent decode sessions per worker
    serving_max_new_tokens: int = 0  # per-request generation cap
    serving_prefill_budget: int = 0  # ragged-step chunked-prefill tokens
    # prefill/decode disaggregation (docs/SERVING.md §Disaggregation):
    # serving_role biases placement — "prefill" workers ingest prompts and
    # hand sessions off post-prefill, "decode" workers adopt them, "mixed"
    # (default) does both and never hands off.  serving_handoff_tokens > 0
    # fires the hand-off once prefill crosses that many tokens (long
    # prompts start moving before ingestion finishes); 0 = on completion.
    serving_role: str = ""  # prefill | decode | mixed ("" = mixed)
    serving_handoff_tokens: int = 0
    # prefix cache + session tiering (docs/SERVING.md §Prefix cache and
    # tiering): serving_prefix_cache toggles copy-on-write shared-prefix KV
    # pages (on by default); serving_hibernate_after_s > 0 tiers cached
    # prefixes idle past the threshold into the worker's host-RAM cold
    # arena (0 = never hibernate)
    serving_prefix_cache: bool = True
    serving_hibernate_after_s: float = 0.0
    # self-speculative decoding (docs/SERVING.md §Speculative decoding):
    # serving_speculative toggles the zero-extra-weights n-gram drafter
    # inside the ragged step (on by default; harmless when prompts never
    # repeat — the adaptive throttle collapses draft length to 1);
    # serving_draft_k caps tokens drafted per session per step (0 = the
    # engine default)
    serving_speculative: bool = True
    serving_draft_k: int = 0
    # cold-arena backing store for hibernated sessions: "" (host RAM only,
    # lost on restart) or "statebus" (journaled to the statebus KV so a
    # restarted worker restores its hibernated records —
    # docs/SERVING.md §Session tiering)
    serving_cold_tier: str = ""


@dataclass
class PoolConfig:
    topics: dict[str, list[str]] = field(default_factory=dict)  # topic -> pool names
    pools: dict[str, Pool] = field(default_factory=dict)
    # scheduler.shards: keyspace shard count for the scheduler fleet (each
    # shard binary also needs its --shard-index); 1 = unsharded
    scheduler_shards: int = 1
    # statebus: replication defaults for the statebus fleet (cmd.statebus;
    # env vars win): partitions, replicas-per-partition, sync_replication,
    # heartbeat_timeout_s — docs/PROTOCOL.md §Replication
    statebus: dict = field(default_factory=dict)
    # slo: per-job-class objectives (name → {job_class, latency_ms,
    # latency_target, availability_target}) consumed by the gateway's
    # SLOTracker (cordum_tpu/obs/slo.py)
    slo: dict = field(default_factory=dict)
    # admission: gateway capacity-aware admission control (per-tenant
    # quotas, headroom shedding, brownout ladder) consumed by the gateway's
    # AdmissionController (docs/ADMISSION.md)
    admission: dict = field(default_factory=dict)
    # rebalancer: the scheduler-side decode rebalancer's knobs (interval,
    # skew threshold, cooldown, moves per command) consumed by
    # DecodeRebalancer (docs/SERVING.md §Disaggregation)
    rebalancer: dict = field(default_factory=dict)
    # gang: gang-scheduling knobs (rendezvous/peer timeouts) consumed by
    # the scheduler's GangScheduler and the workers' GangRunner
    # (docs/GANG.md)
    gang: dict = field(default_factory=dict)

    def pools_for_topic(self, topic: str) -> list[Pool]:
        names = self.topics.get(topic)
        if names is None:
            # wildcard topic keys (e.g. "job.tpu.>") match like bus subjects
            from ..utils.globmatch import subject_match

            names = []
            for pattern, pool_names in self.topics.items():
                if subject_match(pattern, topic):
                    names.extend(pool_names)
        return [self.pools[n] for n in names if n in self.pools]


def parse_pool_config(doc: dict, *, source: str = "pools") -> PoolConfig:
    from .configschema import POOLS_SCHEMA, validate

    validate(doc, POOLS_SCHEMA, source)
    cfg = PoolConfig()
    for name, p in (doc.get("pools") or {}).items():
        p = p or {}
        cfg.pools[name] = Pool(
            name=name,
            requires=list(p.get("requires") or []),
            max_parallel_jobs=int(p.get("max_parallel_jobs") or 0),
            min_chips=int(p.get("min_chips") or 0),
            topology=str(p.get("topology") or ""),
            device_kind=str(p.get("device_kind") or ""),
            max_batch_size=int(p.get("max_batch_size") or 0),
            max_batch_wait_ms=float(p.get("max_batch_wait_ms") or 0.0),
            serving_cache_pages=int(p.get("serving_cache_pages") or 0),
            serving_page_size=int(p.get("serving_page_size") or 0),
            serving_max_sessions=int(p.get("serving_max_sessions") or 0),
            serving_max_new_tokens=int(p.get("serving_max_new_tokens") or 0),
            serving_prefill_budget=int(p.get("serving_prefill_budget") or 0),
            serving_role=str(p.get("serving_role") or ""),
            serving_handoff_tokens=int(p.get("serving_handoff_tokens") or 0),
            serving_prefix_cache=bool(p.get("serving_prefix_cache", True)),
            serving_speculative=bool(p.get("serving_speculative", True)),
            serving_draft_k=int(p.get("serving_draft_k") or 0),
            serving_cold_tier=str(p.get("serving_cold_tier") or ""),
            serving_hibernate_after_s=float(
                p.get("serving_hibernate_after_s") or 0.0
            ),
        )
    for topic, pools in (doc.get("topics") or {}).items():
        if isinstance(pools, str):
            pools = [pools]
        cfg.topics[topic] = list(pools or [])
    cfg.scheduler_shards = max(1, int((doc.get("scheduler") or {}).get("shards") or 1))
    cfg.statebus = dict(doc.get("statebus") or {})
    cfg.slo = dict(doc.get("slo") or {})
    cfg.admission = dict(doc.get("admission") or {})
    cfg.rebalancer = dict(doc.get("rebalancer") or {})
    cfg.gang = dict(doc.get("gang") or {})
    return cfg


def load_pool_config(path: str) -> PoolConfig:
    if not os.path.exists(path):
        # default: one pool, default topic routed to it
        return parse_pool_config({"topics": {"job.default": "default"}, "pools": {"default": {}}})
    with open(path) as f:
        # schema-validated at parse: a typo'd pool file fails startup with a
        # pointed error instead of loading silently (reference validation.go:11)
        return parse_pool_config(yaml.safe_load(f) or {}, source=path)


# ---------------------------------------------------------------------------
# timeouts
# ---------------------------------------------------------------------------


@dataclass
class Timeouts:
    dispatch_timeout_s: float = 300.0
    running_timeout_s: float = 9000.0
    scan_interval_s: float = 30.0
    # how long a job may sit PENDING before the replayer re-drives it.
    # Deliberately much shorter than dispatch_timeout_s: a PENDING job whose
    # submit exhausted its bus redeliveries (tenant-concurrency backpressure
    # on a burst, or its owner shard being down) is safe to replay early —
    # the job lock + in-flight short-circuit make replays idempotent.
    pending_replay_s: float = 15.0
    # how long a job may sit DISPATCHED/RUNNING before the replayer
    # re-delivers it to its dispatch subject.  The worker side is
    # idempotent (in-flight redeliveries dropped, completed jobs republish
    # the cached result), so this is a result-replay request: it recovers
    # dispatches and terminal results lost to a statebus failover window
    # (pub/sub pushes are not replicated — docs/PROTOCOL.md §Replication)
    # without re-running work.
    result_replay_s: float = 20.0
    per_workflow: dict[str, float] = field(default_factory=dict)
    per_topic: dict[str, float] = field(default_factory=dict)


def parse_timeouts(doc: dict, *, source: str = "timeouts") -> Timeouts:
    from .configschema import TIMEOUTS_SCHEMA, validate

    validate(doc, TIMEOUTS_SCHEMA, source)
    t = Timeouts()
    rec = doc.get("reconciler") or {}
    t.dispatch_timeout_s = float(rec.get("dispatch_timeout_seconds", t.dispatch_timeout_s))
    t.running_timeout_s = float(rec.get("running_timeout_seconds", t.running_timeout_s))
    t.scan_interval_s = float(rec.get("scan_interval_seconds", t.scan_interval_s))
    t.pending_replay_s = float(rec.get("pending_replay_seconds", t.pending_replay_s))
    t.result_replay_s = float(rec.get("result_replay_seconds", t.result_replay_s))
    t.per_workflow = {k: float(v) for k, v in (doc.get("workflows") or {}).items()}
    t.per_topic = {k: float(v) for k, v in (doc.get("topics") or {}).items()}
    return t


def load_timeouts(path: str) -> Timeouts:
    if not os.path.exists(path):
        return Timeouts()
    with open(path) as f:
        return parse_timeouts(yaml.safe_load(f) or {}, source=path)
