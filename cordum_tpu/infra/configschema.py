"""Config validation: embedded JSON schemas + the typed config taxonomy.

The reference validates every YAML config file at parse time against
embedded JSON schemas (``core/infra/config/validation.go:11``,
``config/schema/*.schema.json``) and defines a typed taxonomy of effective-
config fields (``core/infra/config/categories.go:6-160``: safety / budget /
rate / retry / resources / models / context / slo / observability /
alerting).  This module is the TPU-native equivalent: a typo'd pool file or
malformed safety policy fails startup with a pointed error instead of
loading silently, and the taxonomy documents (and validates) every
effective-config field the code actually reads.

``python -m cordum_tpu.infra.configschema`` prints the taxonomy as markdown
(the generated doc lives at ``docs/CONFIG.md``).
"""
from __future__ import annotations

from typing import Any

import jsonschema


class ConfigError(ValueError):
    """A config document failed schema validation."""


_STR_LIST = {"type": "array", "items": {"type": "string"}}
_STR_MAP = {"type": "object", "additionalProperties": {"type": "string"}}
_NONNEG = {"type": "number", "minimum": 0}
_NONNEG_INT = {"type": "integer", "minimum": 0}

# ---------------------------------------------------------------------------
# pools.yaml  (reference core/infra/config/pools.go + pool.schema.json)
# ---------------------------------------------------------------------------

POOLS_SCHEMA: dict[str, Any] = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "properties": {
        "topics": {
            "type": "object",
            "additionalProperties": {
                "anyOf": [{"type": "string"}, _STR_LIST],
            },
        },
        "pools": {
            "type": "object",
            "additionalProperties": {
                "anyOf": [{"type": "null"}, {
                    "type": "object",
                    "properties": {
                        "requires": _STR_LIST,
                        "max_parallel_jobs": _NONNEG_INT,
                        # TPU slice constraints (north-star extension)
                        "min_chips": _NONNEG_INT,
                        "topology": {"type": "string", "pattern": r"^(\d+x\d+(x\d+)?)?$"},
                        "device_kind": {"type": "string"},
                        # micro-batching limits (cordum_tpu/batching)
                        "max_batch_size": _NONNEG_INT,
                        "max_batch_wait_ms": _NONNEG,
                        # serving limits (cordum_tpu/serving, docs/SERVING.md)
                        "serving_cache_pages": _NONNEG_INT,
                        "serving_page_size": _NONNEG_INT,
                        "serving_max_sessions": _NONNEG_INT,
                        "serving_max_new_tokens": _NONNEG_INT,
                        "serving_prefill_budget": _NONNEG_INT,
                        # prefill/decode disaggregation (docs/SERVING.md
                        # §Disaggregation): placement role + the mid-prefill
                        # hand-off token threshold (0 = on completion)
                        "serving_role": {
                            "enum": ["prefill", "decode", "mixed", ""],
                        },
                        "serving_handoff_tokens": _NONNEG_INT,
                        # prefix cache + session tiering (docs/SERVING.md
                        # §Prefix cache and tiering): CoW shared-prefix KV
                        # toggle + idle seconds before a cached prefix is
                        # hibernated to the host-RAM cold arena (0 = never)
                        "serving_prefix_cache": {"type": "boolean"},
                        "serving_speculative": {"type": "boolean"},
                        "serving_draft_k": _NONNEG_INT,
                        "serving_hibernate_after_s": _NONNEG,
                        # cold-arena backing store: "" = host RAM only,
                        # "statebus" = journaled to the statebus KV so
                        # hibernated sessions survive a worker restart
                        "serving_cold_tier": {"enum": ["statebus", ""]},
                    },
                    "additionalProperties": False,
                }],
            },
        },
        # scheduler keyspace sharding (ISSUE 5): total shard count; each
        # shard binary picks its index via --shard-index / SCHEDULER_SHARD_INDEX
        "scheduler": {
            "type": "object",
            "properties": {"shards": {"type": "integer", "minimum": 1}},
            "additionalProperties": False,
        },
        # statebus replication fleet defaults (cmd.statebus; env vars win —
        # docs/PROTOCOL.md §Replication): partition count, replicas per
        # partition, commit ack mode, and the primary-dead detection window
        "statebus": {
            "type": "object",
            "properties": {
                "partitions": {"type": "integer", "minimum": 1},
                "replicas": _NONNEG_INT,
                "sync_replication": {"type": "boolean"},
                "heartbeat_timeout_s": _NONNEG,
            },
            "additionalProperties": False,
        },
        # SLO objectives per job class (cordum_tpu/obs/slo.py): the gateway's
        # SLOTracker evaluates multi-window burn rates against these from the
        # fleet-aggregated series (docs/OBSERVABILITY.md §Fleet telemetry)
        "slo": {
            "type": "object",
            "additionalProperties": {
                "type": "object",
                "properties": {
                    "job_class": {"type": "string"},
                    "latency_ms": {"type": "number", "exclusiveMinimum": 0},
                    "latency_target": {
                        "type": "number", "minimum": 0, "exclusiveMaximum": 1,
                    },
                    "availability_target": {
                        "type": "number", "minimum": 0, "exclusiveMaximum": 1,
                    },
                },
                "required": ["latency_ms"],
                "additionalProperties": False,
            },
        },
        # capacity-aware gateway admission control (docs/ADMISSION.md): the
        # AdmissionController sheds analytically against the measured fleet
        # capacity matrix, enforces per-tenant token-bucket quotas, and runs
        # the brownout ladder off the interactive SLO burn signal
        "admission": {
            "type": "object",
            "properties": {
                "enabled": {"type": "boolean"},
                # admit up to this fraction of measured steady-state capacity
                "safety_factor": {
                    "type": "number", "exclusiveMinimum": 0, "maximum": 1,
                },
                # offered-rate EWMA smoothing (0 < alpha <= 1)
                "smoothing_alpha": {
                    "type": "number", "exclusiveMinimum": 0, "maximum": 1,
                },
                # cold/stale-matrix fallback: shed batch past this fleet
                # scheduler backlog; interactive sheds at the bound below
                "queue_depth_limit": {"type": "integer", "minimum": 1},
                "interactive_queue_bound": {"type": "integer", "minimum": 1},
                "min_retry_after_s": _NONNEG,
                "max_retry_after_s": _NONNEG,
                # ops shed at brownout tier 2 (best-effort work)
                "best_effort_ops": _STR_LIST,
                # per-tenant token buckets; rate_rps 0 = unlimited.  The
                # "default" entry applies to tenants with no explicit stanza.
                "tenants": {
                    "type": "object",
                    "additionalProperties": {
                        "type": "object",
                        "properties": {
                            "rate_rps": _NONNEG,
                            "burst": _NONNEG,
                        },
                        "additionalProperties": False,
                    },
                },
            },
            "additionalProperties": False,
        },
        # scheduler-side decode rebalancer (docs/SERVING.md
        # §Disaggregation): skew detection against the capacity view's
        # decode occupancy + KV-page pressure, hysteresis-guarded and
        # rate-limited so sessions never ping-pong
        "rebalancer": {
            "type": "object",
            "properties": {
                "enabled": {"type": "boolean"},
                # evaluation cadence
                "interval_s": {"type": "number", "exclusiveMinimum": 0},
                # a worker is hot when occupancy >= skew_ratio x fleet median
                "skew_ratio": {"type": "number", "minimum": 1},
                # consecutive hot evaluations required before a move fires
                "hysteresis_ticks": {"type": "integer", "minimum": 1},
                # per-worker floor between rebalance commands
                "cooldown_s": _NONNEG,
                # sessions moved per command
                "max_moves": {"type": "integer", "minimum": 1},
            },
            "additionalProperties": False,
        },
        # gang scheduling (docs/GANG.md): scheduler-side reservation +
        # worker-side rendezvous knobs for multi-chip SPMD/MPMD gangs
        "gang": {
            "type": "object",
            "properties": {
                "enabled": {"type": "boolean"},
                # worker-side barrier timeout (the scheduler watchdog
                # backstops at 2x before declaring the rendezvous dead)
                "rendezvous_timeout_s": {
                    "type": "number", "exclusiveMinimum": 0,
                },
                # MPMD stage-traffic wait: a peer silent for this long
                # mid-step aborts the gang
                "peer_timeout_s": {"type": "number", "exclusiveMinimum": 0},
                # an unplaceable gang (no slice can EVER cover it) fails
                # to the DLQ after queueing this long
                "queued_timeout_s": {"type": "number", "exclusiveMinimum": 0},
            },
            "additionalProperties": False,
        },
        # tolerated here so one file can carry pools + reconciler (dev mode)
        "reconciler": {"type": "object"},
    },
    "additionalProperties": False,
}

# ---------------------------------------------------------------------------
# timeouts.yaml  (reference core/infra/config/timeouts.go)
# ---------------------------------------------------------------------------

TIMEOUTS_SCHEMA: dict[str, Any] = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "properties": {
        "reconciler": {
            "type": "object",
            "properties": {
                "dispatch_timeout_seconds": _NONNEG,
                "running_timeout_seconds": _NONNEG,
                "scan_interval_seconds": _NONNEG,
                "pending_replay_seconds": _NONNEG,
                "result_replay_seconds": _NONNEG,
            },
            "additionalProperties": False,
        },
        "workflows": {"type": "object", "additionalProperties": _NONNEG},
        "topics": {"type": "object", "additionalProperties": _NONNEG},
    },
    "additionalProperties": False,
}

# ---------------------------------------------------------------------------
# safety.yaml  (reference core/infra/config/safety_policy.go:13-146 +
# safety_policy.schema.json; TPU additions: max_chips/allowed_topologies)
# ---------------------------------------------------------------------------

_MCP_SCHEMA = {
    "type": "object",
    "properties": {
        f"{d}_{kind}": _STR_LIST
        for d in ("allow", "deny")
        for kind in ("servers", "tools", "resources", "actions")
    },
    "additionalProperties": False,
}

_CONSTRAINTS_SCHEMA = {
    "type": "object",
    "properties": {
        "max_tokens": _NONNEG_INT,
        "max_cost_usd": _NONNEG,
        "sandbox": {"type": "string"},
        "toolchain": {"type": "string"},
        "diff_limit": {"type": "string"},
        "redaction_level": {"type": "string"},
        "max_chips": _NONNEG_INT,
        "allowed_topologies": _STR_LIST,
        "env": _STR_MAP,
    },
    "additionalProperties": False,
}

_RULE_SCHEMA = {
    "type": "object",
    "properties": {
        "id": {"type": "string"},
        "description": {"type": "string"},
        "match": {
            "type": "object",
            "properties": {
                "tenants": _STR_LIST,
                "topics": _STR_LIST,
                "capabilities": _STR_LIST,
                "risk_tags": _STR_LIST,
                "requires": _STR_LIST,
                "pack_ids": _STR_LIST,
                "actor_ids": _STR_LIST,
                "actor_types": _STR_LIST,
                "labels": _STR_MAP,
                "secrets_present": {"type": "boolean"},
                "mcp": {"type": "boolean"},
            },
            "additionalProperties": False,
        },
        "decision": {
            "enum": ["allow", "deny", "require_approval",
                     "allow_with_constraints", "throttle"],
        },
        "reason": {"type": "string"},
        "constraints": _CONSTRAINTS_SCHEMA,
        "remediations": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "id": {"type": "string"},
                    "description": {"type": "string"},
                    "replacement_topic": {"type": "string"},
                    "replacement_capability": {"type": "string"},
                    "add_labels": _STR_MAP,
                    "remove_labels": _STR_LIST,
                },
                "additionalProperties": False,
            },
        },
        "throttle_delay_s": _NONNEG,
    },
    "required": ["decision"],
    "additionalProperties": False,
}

SAFETY_SCHEMA: dict[str, Any] = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "properties": {
        "default_tenant": {"type": "string"},
        "tenants": {
            "type": "object",
            "additionalProperties": {
                # null bodies tolerated (an empty `staging:` stanza is valid
                # YAML and the parser treats it as {}), matching POOLS_SCHEMA
                "anyOf": [{"type": "null"}, {
                    "type": "object",
                    "properties": {
                        "allow_topics": _STR_LIST,
                        "deny_topics": _STR_LIST,
                        "max_concurrent_jobs": _NONNEG_INT,
                        "mcp": _MCP_SCHEMA,
                    },
                    "additionalProperties": False,
                }],
            },
        },
        "rules": {"type": "array", "items": _RULE_SCHEMA},
    },
    "additionalProperties": False,
}

# ---------------------------------------------------------------------------
# Effective-config taxonomy (reference categories.go:6-160).  One entry per
# field the control plane actually reads from the merged effective config.
# Each: (category, field, type, consumer, description).
# ---------------------------------------------------------------------------

TAXONOMY: list[tuple[str, str, str, str, str]] = [
    ("safety", "safety.denied_topics", "list[str]",
     "safetykernel.kernel", "extra topic globs denied for every tenant"),
    ("safety", "safety.default_decision", "str",
     "safetykernel.kernel", "fallback decision when no rule matches (allow|deny)"),
    ("safety", "safety.require_approval_topics", "list[str]",
     "safetykernel.kernel", "topic globs that always require human approval"),
    ("budget", "budgets.max_tokens", "int",
     "scheduler.engine", "per-job token ceiling clamped into JobRequest.budget"),
    ("budget", "budgets.max_cost_usd", "float",
     "scheduler.engine", "per-job cost ceiling"),
    ("budget", "budgets.deadline_seconds", "int",
     "scheduler.engine", "default job deadline when the request carries none"),
    ("rate", "rate_limits.concurrent_jobs", "int",
     "scheduler.engine", "per-tenant concurrent-job cap (org-scoped overrides win)"),
    ("rate", "rate_limits.api_rps", "float",
     "gateway.app", "gateway token-bucket refill rate"),
    ("rate", "rate_limits.api_burst", "int",
     "gateway.app", "gateway token-bucket burst size"),
    ("retry", "retry.max_attempts", "int",
     "scheduler.engine", "dispatch attempts before DLQ"),
    ("retry", "retry.backoff_base_seconds", "float",
     "workflow.engine", "workflow step retry backoff base"),
    ("retry", "retry.backoff_multiplier", "float",
     "workflow.engine", "workflow step retry backoff multiplier"),
    ("resources", "resources.default_pool", "str",
     "scheduler.strategy", "pool used when no topic route matches"),
    ("resources", "resources.max_chips", "int",
     "scheduler.strategy", "slice-size ceiling applied to placements"),
    ("resources", "resources.allowed_topologies", "list[str]",
     "scheduler.strategy", "ICI topologies a tenant may occupy (e.g. 2x2x1)"),
    ("models", "models.default_model", "str",
     "worker.handlers", "model id used by model-exec jobs with no explicit model"),
    ("models", "models.allowed_models", "list[str]",
     "safetykernel.kernel", "allowlist for model-exec topics"),
    ("models", "models.dtype", "str",
     "worker.training", "compute dtype for TPU jobs (bfloat16|float32)"),
    ("context", "context.window_tokens", "int",
     "context.service", "BuildWindow token budget default"),
    ("context", "context.history_events", "int",
     "context.service", "CHAT/RAG mode: trailing history events attached"),
    ("context", "context.rag_top_k", "int",
     "context.service", "RAG mode: chunks retrieved per query"),
    ("context", "context.embed_batch", "int",
     "context.service", "TPU embedder batch size (pad-to-batch on MXU)"),
    ("slo", "slo.dispatch_p99_ms", "float",
     "infra.metrics", "alert threshold: dispatch latency p99"),
    ("slo", "slo.e2e_p99_ms", "float",
     "infra.metrics", "alert threshold: submit→result p99"),
    ("observability", "observability.log_format", "str",
     "infra.logging", "text|json"),
    ("observability", "observability.trace_sample_rate", "float",
     "infra.jobstore", "fraction of jobs recorded into trace sets"),
    ("alerting", "alerting.dlq_depth_warn", "int",
     "infra.dlq", "DLQ depth that trips a SystemAlert"),
    ("alerting", "alerting.worker_loss_warn", "int",
     "infra.registry", "expired-worker count that trips a SystemAlert"),
]

_TYPE_TO_SCHEMA = {
    "int": _NONNEG_INT,
    "float": _NONNEG,
    "str": {"type": "string"},
    "list[str]": _STR_LIST,
}


def effective_schema() -> dict[str, Any]:
    """JSON schema for the merged effective config, generated from TAXONOMY.

    Unknown top-level categories are allowed (packs may overlay their own
    namespaces); known categories reject unknown/mistyped fields.
    """
    cats: dict[str, dict] = {}
    for _, path, typ, _, _ in TAXONOMY:
        cat, key = path.split(".", 1)
        c = cats.setdefault(cat, {"type": "object", "properties": {},
                                  "additionalProperties": False})
        c["properties"][key] = _TYPE_TO_SCHEMA[typ]
    return {"type": "object", "properties": cats}


def validate(doc: Any, schema: dict[str, Any], source: str = "config") -> None:
    """Raise :class:`ConfigError` with a pointed path on schema violation."""
    v = jsonschema.Draft202012Validator(schema)
    errors = sorted(v.iter_errors(doc), key=lambda e: list(e.absolute_path))
    if errors:
        e = errors[0]
        where = "/".join(str(p) for p in e.absolute_path) or "<root>"
        more = f" (+{len(errors) - 1} more)" if len(errors) > 1 else ""
        raise ConfigError(f"{source}: {where}: {e.message}{more}")


def taxonomy_markdown() -> str:
    """The taxonomy rendered as the docs/CONFIG.md table."""
    out = [
        "# Effective-config taxonomy",
        "",
        "Generated by `python -m cordum_tpu.infra.configschema` from",
        "`cordum_tpu/infra/configschema.py` (reference analogue:",
        "`core/infra/config/categories.go:6-160`). Fields merge shallowly",
        "system → org → team → workflow → step (`infra/configsvc.py`) and",
        "reach jobs as the `CORDUM_EFFECTIVE_CONFIG` env var.",
        "",
        "| Category | Field | Type | Consumer | Description |",
        "|---|---|---|---|---|",
    ]
    for cat, path, typ, consumer, desc in TAXONOMY:
        out.append(f"| {cat} | `{path}` | `{typ}` | `{consumer}` | {desc} |")
    out.append("")
    return "\n".join(out)


if __name__ == "__main__":
    print(taxonomy_markdown())
