"""Dynamic config service: scoped, revisioned documents with shallow-merge
effective view (reference ``core/configsvc/service.go:14-170``).

Scopes merge system → org → team → workflow → step; ``effective()`` is the
shallow merge, ``effective_snapshot()`` is ``{version, hash}`` used to pin
policy decisions.  Documents live at ``cfg:<scope>:<id>``.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Any, Optional

from ..utils.ids import now_us
from .kv import KV

SCOPES = ("system", "org", "team", "workflow", "step")


def cfg_key(scope: str, doc_id: str) -> str:
    return f"cfg:{scope}:{doc_id}"


@dataclass
class ConfigDoc:
    scope: str
    doc_id: str
    revision: int
    data: dict[str, Any]
    updated_at_us: int = 0


class ConfigService:
    def __init__(self, kv: KV) -> None:
        self.kv = kv

    async def get(self, scope: str, doc_id: str) -> Optional[ConfigDoc]:
        b = await self.kv.get(cfg_key(scope, doc_id))
        if not b:
            return None
        d = json.loads(b)
        return ConfigDoc(scope, doc_id, d.get("revision", 0), d.get("data", {}), d.get("updated_at_us", 0))

    async def set(self, scope: str, doc_id: str, data: dict[str, Any]) -> ConfigDoc:
        if scope not in SCOPES:
            raise ValueError(f"unknown scope {scope!r}")
        cur = await self.get(scope, doc_id)
        rev = (cur.revision if cur else 0) + 1
        doc = ConfigDoc(scope, doc_id, rev, data, now_us())
        await self.kv.set(
            cfg_key(scope, doc_id),
            json.dumps({"revision": rev, "data": data, "updated_at_us": doc.updated_at_us}).encode(),
        )
        return doc

    async def patch(self, scope: str, doc_id: str, patch: dict[str, Any]) -> ConfigDoc:
        """RFC 7386-style JSON merge patch (pack overlays use this)."""
        cur = await self.get(scope, doc_id)
        data = dict(cur.data) if cur else {}
        _merge_patch(data, patch)
        return await self.set(scope, doc_id, data)

    async def delete(self, scope: str, doc_id: str) -> bool:
        return (await self.kv.delete(cfg_key(scope, doc_id))) > 0

    async def list(self, scope: str) -> list[str]:
        prefix = f"cfg:{scope}:"
        return [k[len(prefix):] for k in await self.kv.keys(prefix)]

    async def effective(
        self,
        *,
        org: str = "",
        team: str = "",
        workflow: str = "",
        step: str = "",
        system_id: str = "default",
    ) -> dict[str, Any]:
        """Shallow merge system→org→team→workflow→step (later wins per key)."""
        merged: dict[str, Any] = {}
        for scope, doc_id in (
            ("system", system_id),
            ("org", org),
            ("team", team),
            ("workflow", workflow),
            ("step", step),
        ):
            if not doc_id:
                continue
            doc = await self.get(scope, doc_id)
            if doc:
                merged.update(doc.data)
        return merged

    async def effective_snapshot(self, **kw: str) -> dict[str, str]:
        eff = await self.effective(**kw)
        canonical = json.dumps(eff, sort_keys=True, separators=(",", ":"))
        h = hashlib.sha256(canonical.encode()).hexdigest()[:16]
        return {"hash": h, "config": canonical}


def _merge_patch(target: dict, patch: dict) -> None:
    for k, v in patch.items():
        if v is None:
            target.pop(k, None)
        elif isinstance(v, dict) and isinstance(target.get(k), dict):
            _merge_patch(target[k], v)
        else:
            target[k] = v
