"""Dead-letter queue store (reference ``core/infra/memory/dlq_store.go``)."""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

from ..utils.ids import now_us
from .kv import KV

# per-job re-drive hook: takes a DLQ job id, returns the new job id when the
# retry was published (the gateway's retry path), or None when it could not
# be (missing original request etc.)
RetryFn = Callable[[str], Awaitable[Optional[str]]]


@dataclass
class DLQEntry:
    job_id: str = ""
    topic: str = ""
    status: str = ""
    reason: str = ""
    reason_code: str = ""
    last_state: str = ""
    attempts: int = 0
    tenant_id: str = ""
    created_at_us: int = 0
    labels: dict = field(default_factory=dict)


def entry_key(job_id: str) -> str:
    return f"dlq:entry:{job_id}"


INDEX_KEY = "dlq:index"


class DLQStore:
    def __init__(self, kv: KV) -> None:
        self.kv = kv

    async def add(self, e: DLQEntry) -> None:
        e.created_at_us = e.created_at_us or now_us()
        await self.kv.set(entry_key(e.job_id), json.dumps(e.__dict__).encode())
        await self.kv.zadd(INDEX_KEY, e.job_id, float(e.created_at_us))

    async def get(self, job_id: str) -> Optional[DLQEntry]:
        b = await self.kv.get(entry_key(job_id))
        return DLQEntry(**json.loads(b)) if b else None

    async def list(self, offset: int = 0, limit: int = 50) -> list[DLQEntry]:
        ids = await self.kv.zrange(INDEX_KEY, offset, offset + limit - 1, desc=True)
        out = []
        for jid in ids:
            e = await self.get(jid)
            if e:
                out.append(e)
        return out

    async def count(self) -> int:
        return await self.kv.zcard(INDEX_KEY)

    async def delete(self, job_id: str) -> bool:
        n = await self.kv.delete(entry_key(job_id))
        await self.kv.zrem(INDEX_KEY, job_id)
        return n > 0

    # ------------------------------------------------------------------
    # bulk operations
    # ------------------------------------------------------------------
    async def retry_all(
        self, retry_fn: RetryFn, *, limit: int = 0
    ) -> list[tuple[str, Optional[str]]]:
        """Re-drive every dead-lettered job through ``retry_fn`` (the
        existing per-job retry path), oldest first.  Returns
        ``[(job_id, new_job_id | None), ...]``; entries whose retry was
        published are removed from the queue, failed re-drives stay."""
        ids = await self.kv.zrange(INDEX_KEY, 0, (limit - 1) if limit else -1)
        out: list[tuple[str, Optional[str]]] = []
        for jid in ids:
            new_id = await retry_fn(jid)
            if new_id is not None:
                await self.delete(jid)
            out.append((jid, new_id))
        return out

    async def purge_older_than(self, cutoff_us: int) -> int:
        """Drop every entry dead-lettered at or before ``cutoff_us``; returns
        the number purged."""
        ids = await self.kv.zrangebyscore(INDEX_KEY, 0, float(cutoff_us))
        n = 0
        for jid in ids:
            if await self.delete(jid):
                n += 1
        return n
