"""Statebus wire-frame primitives, shared by the server, the client and the
replication link (``[4-byte BE length][msgpack array]`` — docs/PROTOCOL.md
§Statebus wire format).

Split out of ``statebus.py`` so :mod:`cordum_tpu.infra.replication` can
frame/deframe the same protocol without importing the server module (which
imports replication for the primary/replica machinery).
"""
from __future__ import annotations

import asyncio
import struct
from typing import Any, Optional

import msgpack

LEN = struct.Struct(">I")


def encode_frame(obj: Any) -> bytes:
    b = msgpack.packb(obj, use_bin_type=True)
    return LEN.pack(len(b)) + b


async def read_frame(reader: asyncio.StreamReader) -> Optional[list]:
    try:
        head = await reader.readexactly(4)
        (n,) = LEN.unpack(head)
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class FrameWriter:
    """Per-connection write coalescer.

    ``send()`` enqueues a frame synchronously; one flusher task drains the
    accumulated batch per wakeup.  N replies (or N pipelined requests)
    produced in one event-loop tick cost ONE socket write + drain instead
    of N lock/write/drain cycles — without this, pipelined commits arriving
    from many scheduler shards interleave into tiny writes and the
    per-frame ``drain()`` syscalls dominate the statebus hot path.
    Batch sizes surface as ``cordum_statebus_coalesced_batch``.
    """

    __slots__ = ("_writer", "_buf", "_wake", "_task", "_metrics", "_closed")

    def __init__(self, writer: asyncio.StreamWriter, metrics: Any = None) -> None:
        self._writer = writer
        self._buf: list[bytes] = []
        self._wake = asyncio.Event()
        self._metrics = metrics
        self._closed = False
        self._task = asyncio.ensure_future(self._run())

    def send(self, frame: bytes) -> None:
        if self._closed:
            raise ConnectionError("statebus frame writer closed")
        self._buf.append(frame)
        self._wake.set()

    async def _run(self) -> None:
        try:
            while not self._closed:
                await self._wake.wait()
                self._wake.clear()
                if not self._buf:
                    continue
                buf, self._buf = self._buf, []
                if self._metrics is not None:
                    self._metrics.statebus_coalesced_batch.observe(float(len(buf)))
                self._writer.write(buf[0] if len(buf) == 1 else b"".join(buf))
                # drain AFTER the batch: backpressure throttles the flusher
                # (and everything queued behind it), never individual sends
                await self._writer.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            # peer gone mid-flush: subsequent send() raises; the owning
            # connection's read loop drives recovery/teardown
            self._closed = True

    async def close(self) -> None:
        self._closed = True
        self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, ConnectionError, OSError):
            pass
