"""Job state store: atomic state machine + observability indexes.

Recreates the semantics of the reference Redis job store
(``core/infra/memory/job_store.go``, 1392 LoC):

  * per-job metadata hash ``job:meta:<id>`` (~30 fields)
  * optimistic (WATCH-equivalent) state transitions validated against the
    legal-transition table (job_store.go:71-92) — illegal transitions fail,
    terminal states are immutable
  * per-state sorted-set indexes ``job:index:<STATE>``, plus ``job:recent``
    and the ``job:deadline`` z-set scanned by the reconciler
  * append-only per-job event log ``job:events:<id>`` and trace sets
    ``trace:<id>`` (the tracing story — SURVEY.md §5)
  * tenant active-job counts for concurrency limits
  * scoped idempotency keys (SETNX), per-job locks (SETNX+TTL)
  * safety-decision and approval records binding approvals to job hashes
  * persisted JobRequest blobs so the pending replayer can resubmit
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

from ..protocol.types import (
    JobRequest,
    JobState,
    TERMINAL_STATES,
    is_allowed_transition,
)
from ..utils.ids import now_us
from .kv import KV

DEFAULT_META_TTL_S = 7 * 24 * 3600.0
RECENT_CAP = 10_000
EVENTS_CAP = 200


class IllegalTransition(Exception):
    def __init__(self, job_id: str, prev: str, nxt: str) -> None:
        super().__init__(f"job {job_id}: illegal transition {prev or '<none>'} -> {nxt}")
        self.prev = prev
        self.next = nxt


def meta_key(job_id: str) -> str:
    return f"job:meta:{job_id}"


def index_key(state: str) -> str:
    return f"job:index:{state}"


def events_key(job_id: str) -> str:
    return f"job:events:{job_id}"


def trace_key(trace_id: str) -> str:
    return f"trace:{trace_id}"


def request_key(job_id: str) -> str:
    return f"job:request:{job_id}"


RECENT_KEY = "job:recent"
DEADLINE_KEY = "job:deadline"


@dataclass
class SafetyDecisionRecord:
    job_id: str = ""
    decision: str = ""
    reason: str = ""
    rule_id: str = ""
    policy_snapshot: str = ""
    job_hash: str = ""
    constraints: Optional[dict] = None
    remediations: list[dict] = field(default_factory=list)
    decided_at_us: int = 0


@dataclass
class ApprovalRecord:
    job_id: str = ""
    approved_by: str = ""
    approved: bool = False
    reason: str = ""
    job_hash: str = ""
    policy_snapshot: str = ""
    decided_at_us: int = 0


class JobStore:
    def __init__(self, kv: KV, *, meta_ttl_s: float = DEFAULT_META_TTL_S) -> None:
        self.kv = kv
        self.meta_ttl_s = meta_ttl_s

    # ------------------------------------------------------------------
    # state machine
    # ------------------------------------------------------------------
    async def get_state(self, job_id: str) -> str:
        v = await self.kv.hget(meta_key(job_id), "state")
        return v.decode() if v else ""

    async def get_meta(self, job_id: str) -> dict[str, str]:
        h = await self.kv.hgetall(meta_key(job_id))
        return {k: v.decode() for k, v in h.items()}

    async def set_state(
        self,
        job_id: str,
        state: JobState,
        *,
        fields: Optional[dict[str, str]] = None,
        event: str = "",
        max_retries: int = 16,
    ) -> bool:
        """Atomic validated transition.  Returns True if the state changed,
        False if the job is already in ``state`` (idempotent re-apply).
        Raises :class:`IllegalTransition` otherwise."""
        key = meta_key(job_id)
        for _ in range(max_retries):
            ver, h = await self.kv.watch_read(key)
            prev = h.get("state", b"").decode()
            if prev == state.value:
                if fields:
                    await self.kv.hset(key, {k: v.encode() for k, v in fields.items()})
                return False
            if not is_allowed_transition(prev, state):
                raise IllegalTransition(job_id, prev, state.value)
            ts = now_us()
            mapping: dict[str, bytes] = {
                "state": state.value.encode(),
                "updated_at_us": str(ts).encode(),
            }
            if not h:
                mapping["created_at_us"] = str(ts).encode()
            if state in TERMINAL_STATES:
                mapping["finished_at_us"] = str(ts).encode()
            for k, v in (fields or {}).items():
                mapping[k] = v.encode()
            ops: list[tuple] = [("hset", key, mapping)]
            if prev:
                ops.append(("zrem", index_key(prev), job_id))
            ops.append(("zadd", index_key(state.value), job_id, float(ts)))
            ops.append(("zadd", RECENT_KEY, job_id, float(ts)))
            ev = {
                "ts_us": ts,
                "state": state.value,
                "prev": prev,
                "event": event or f"state:{state.value}",
            }
            ops.append(("rpush", events_key(job_id), json.dumps(ev).encode()))
            ops.append(("expire", key, self.meta_ttl_s))
            if state in TERMINAL_STATES:
                ops.append(("zrem", DEADLINE_KEY, job_id))
                tenant = h.get("tenant_id", b"").decode()
                if tenant and prev and prev not in (s.value for s in TERMINAL_STATES):
                    ops.append(("zrem", f"job:tenant_active:{tenant}", job_id))
            if await self.kv.commit({key: ver}, ops):
                return True
        raise RuntimeError(f"job {job_id}: transition to {state.value} lost race repeatedly")

    async def set_fields(self, job_id: str, fields: dict[str, str]) -> None:
        await self.kv.hset(meta_key(job_id), {k: v.encode() for k, v in fields.items()})
        await self.kv.expire(meta_key(job_id), self.meta_ttl_s)

    async def is_terminal(self, job_id: str) -> bool:
        st = await self.get_state(job_id)
        return bool(st) and st in (s.value for s in TERMINAL_STATES)

    # ------------------------------------------------------------------
    # indexes / listing
    # ------------------------------------------------------------------
    async def list_by_state(self, state: str, limit: int = 100) -> list[str]:
        ids = await self.kv.zrange(index_key(state), 0, limit - 1 if limit else -1)
        return ids

    async def list_by_state_older_than(
        self, state: str, cutoff_us: int, limit: int = 200
    ) -> list[str]:
        return await self.kv.zrangebyscore(index_key(state), 0, float(cutoff_us), limit=limit)

    async def list_recent(self, limit: int = 100) -> list[str]:
        return await self.kv.zrange(RECENT_KEY, 0, limit - 1, desc=True)

    # ------------------------------------------------------------------
    # deadlines
    # ------------------------------------------------------------------
    async def register_deadline(self, job_id: str, deadline_unix_ms: int) -> None:
        await self.kv.zadd(DEADLINE_KEY, job_id, float(deadline_unix_ms))

    async def expired_deadlines(self, now_ms: int, limit: int = 100) -> list[str]:
        return await self.kv.zrangebyscore(DEADLINE_KEY, 0, float(now_ms), limit=limit)

    async def clear_deadline(self, job_id: str) -> None:
        await self.kv.zrem(DEADLINE_KEY, job_id)

    # ------------------------------------------------------------------
    # events / traces
    # ------------------------------------------------------------------
    async def append_event(self, job_id: str, event: str, **kw: Any) -> None:
        ev = {"ts_us": now_us(), "event": event, **kw}
        await self.kv.rpush(events_key(job_id), json.dumps(ev).encode())
        await self.kv.ltrim(events_key(job_id), -EVENTS_CAP, -1)

    async def events(self, job_id: str) -> list[dict]:
        return [json.loads(b) for b in await self.kv.lrange(events_key(job_id))]

    async def add_to_trace(self, trace_id: str, job_id: str) -> None:
        if trace_id:
            await self.kv.sadd(trace_key(trace_id), job_id)

    async def trace(self, trace_id: str) -> set[str]:
        return await self.kv.smembers(trace_key(trace_id))

    # ------------------------------------------------------------------
    # tenant concurrency
    # ------------------------------------------------------------------
    async def tenant_active_add(self, tenant_id: str, job_id: str) -> int:
        key = f"job:tenant_active:{tenant_id}"
        await self.kv.zadd(key, job_id, float(now_us()))
        return await self.kv.zcard(key)

    async def tenant_active_remove(self, tenant_id: str, job_id: str) -> None:
        await self.kv.zrem(f"job:tenant_active:{tenant_id}", job_id)

    async def tenant_active_count(self, tenant_id: str) -> int:
        return await self.kv.zcard(f"job:tenant_active:{tenant_id}")

    # ------------------------------------------------------------------
    # idempotency + locks
    # ------------------------------------------------------------------
    async def try_set_idempotency_key(
        self, scope: str, key: str, job_id: str, ttl_s: float = 24 * 3600
    ) -> tuple[bool, str]:
        """Reserve ``key`` in ``scope``; returns (reserved, existing_job_id)."""
        k = f"idem:{scope}:{key}"
        ok = await self.kv.setnx(k, job_id.encode(), ttl_s)
        if ok:
            return True, job_id
        cur = await self.kv.get(k)
        return False, cur.decode() if cur else ""

    async def acquire_job_lock(self, job_id: str, owner: str, ttl_s: float = 30.0) -> bool:
        return await self.kv.setnx(f"lock:job:{job_id}", owner.encode(), ttl_s)

    async def release_job_lock(self, job_id: str, owner: str) -> None:
        cur = await self.kv.get(f"lock:job:{job_id}")
        if cur is not None and cur.decode() == owner:
            await self.kv.delete(f"lock:job:{job_id}")

    # ------------------------------------------------------------------
    # persisted requests (for replays + approvals)
    # ------------------------------------------------------------------
    async def put_request(self, req: JobRequest) -> None:
        await self.kv.set(request_key(req.job_id), req.to_wire(), self.meta_ttl_s)

    async def get_request(self, job_id: str) -> Optional[JobRequest]:
        b = await self.kv.get(request_key(job_id))
        return JobRequest.from_wire(b) if b else None

    # ------------------------------------------------------------------
    # safety decisions + approvals
    # ------------------------------------------------------------------
    async def put_safety_decision(self, rec: SafetyDecisionRecord) -> None:
        rec.decided_at_us = rec.decided_at_us or now_us()
        await self.kv.set(
            f"job:safety:{rec.job_id}", json.dumps(rec.__dict__).encode(), self.meta_ttl_s
        )

    async def get_safety_decision(self, job_id: str) -> Optional[SafetyDecisionRecord]:
        b = await self.kv.get(f"job:safety:{job_id}")
        return SafetyDecisionRecord(**json.loads(b)) if b else None

    async def put_approval(self, rec: ApprovalRecord) -> None:
        rec.decided_at_us = rec.decided_at_us or now_us()
        await self.kv.set(
            f"job:approval:{rec.job_id}", json.dumps(rec.__dict__).encode(), self.meta_ttl_s
        )

    async def get_approval(self, job_id: str) -> Optional[ApprovalRecord]:
        b = await self.kv.get(f"job:approval:{job_id}")
        return ApprovalRecord(**json.loads(b)) if b else None

    # ------------------------------------------------------------------
    async def cancel_job(self, job_id: str) -> bool:
        """Move a non-terminal job to CANCELLED; False if terminal/unknown."""
        st = await self.get_state(job_id)
        if not st or st in (s.value for s in TERMINAL_STATES):
            return False
        try:
            await self.set_state(job_id, JobState.CANCELLED, event="cancel")
            return True
        except IllegalTransition:
            return False
