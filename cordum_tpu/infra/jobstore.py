"""Job state store: atomic state machine + observability indexes.

Recreates the semantics of the reference Redis job store
(``core/infra/memory/job_store.go``, 1392 LoC):

  * per-job metadata hash ``job:meta:<id>`` (~30 fields)
  * optimistic (WATCH-equivalent) state transitions validated against the
    legal-transition table (job_store.go:71-92) — illegal transitions fail,
    terminal states are immutable
  * per-state sorted-set indexes ``job:index:<STATE>``, plus ``job:recent``
    and the ``job:deadline`` z-set scanned by the reconciler
  * append-only per-job event log ``job:events:<id>`` and trace sets
    ``trace:<id>`` (the tracing story — SURVEY.md §5)
  * tenant active-job counts for concurrency limits
  * scoped idempotency keys (SETNX), per-job locks (SETNX+TTL)
  * safety-decision and approval records binding approvals to job hashes
  * persisted JobRequest blobs so the pending replayer can resubmit
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..protocol.types import (
    JobRequest,
    JobState,
    TERMINAL_STATES,
    is_allowed_transition,
)
from ..utils.ids import now_us
from .codec import pack_record, unpack_record
from .kv import KV

DEFAULT_META_TTL_S = 7 * 24 * 3600.0
RECENT_CAP = 10_000
EVENTS_CAP = 200

_TERMINAL_VALUES = frozenset(s.value for s in TERMINAL_STATES)


class IllegalTransition(Exception):
    def __init__(self, job_id: str, prev: str, nxt: str) -> None:
        super().__init__(f"job {job_id}: illegal transition {prev or '<none>'} -> {nxt}")
        self.prev = prev
        self.next = nxt


def meta_key(job_id: str) -> str:
    return f"job:meta:{job_id}"


def index_key(state: str) -> str:
    return f"job:index:{state}"


def events_key(job_id: str) -> str:
    return f"job:events:{job_id}"


def trace_key(trace_id: str) -> str:
    return f"trace:{trace_id}"


def request_key(job_id: str) -> str:
    return f"job:request:{job_id}"


RECENT_KEY = "job:recent"
DEADLINE_KEY = "job:deadline"


@dataclass
class SafetyDecisionRecord:
    job_id: str = ""
    decision: str = ""
    reason: str = ""
    rule_id: str = ""
    policy_snapshot: str = ""
    job_hash: str = ""
    constraints: Optional[dict] = None
    remediations: list[dict] = field(default_factory=list)
    decided_at_us: int = 0


@dataclass
class ApprovalRecord:
    job_id: str = ""
    approved_by: str = ""
    approved: bool = False
    reason: str = ""
    job_hash: str = ""
    policy_snapshot: str = ""
    decided_at_us: int = 0


@dataclass
class MetaSnapshot:
    """Optimistic view of one job's ``job:meta`` hash: ``(version, fields)``.

    Returned by :meth:`JobStore.watch_meta` and threaded through
    :meth:`JobStore.apply_chain`, which refreshes it locally from the
    pipeline's post-commit version — so a sequence of transitions on one
    job needs exactly one read round trip (or zero, for the optimistic
    fresh-job path that starts from ``MetaSnapshot()`` = "key absent").
    """

    version: int = 0
    fields: dict[str, bytes] = field(default_factory=dict)

    @property
    def state(self) -> str:
        v = self.fields.get("state")
        return v.decode() if v else ""

    @property
    def is_terminal(self) -> bool:
        return self.state in _TERMINAL_VALUES

    def get(self, key: str, default: str = "") -> str:
        v = self.fields.get(key)
        return v.decode() if v else default

    def decoded(self) -> dict[str, str]:
        return {k: v.decode() for k, v in self.fields.items()}


# One validated state transition inside an apply_chain() call:
# (state, fields-or-None, event-name)
Transition = tuple[JobState, Optional[dict[str, str]], str]


class JobStore:
    def __init__(self, kv: KV, *, meta_ttl_s: float = DEFAULT_META_TTL_S) -> None:
        self.kv = kv
        self.meta_ttl_s = meta_ttl_s

    # ------------------------------------------------------------------
    # state machine
    # ------------------------------------------------------------------
    async def get_state(self, job_id: str) -> str:
        v = await self.kv.hget(meta_key(job_id), "state")
        return v.decode() if v else ""

    async def get_meta(self, job_id: str) -> dict[str, str]:
        h = await self.kv.hgetall(meta_key(job_id))
        return {k: v.decode() for k, v in h.items()}

    async def watch_meta(self, job_id: str) -> MetaSnapshot:
        """One-round-trip ``(version, hash)`` snapshot of ``job:meta``."""
        ver, h = await self.kv.watch_read(meta_key(job_id))
        return MetaSnapshot(ver, h)

    def _chain_ops(
        self, job_id: str, snap: MetaSnapshot, steps: list[Transition]
    ) -> tuple[list[tuple], dict[str, bytes], bool]:
        """Build the pipelined op list for a chain of validated transitions
        applied on top of ``snap``.  Returns ``(ops, overlay, changed)``
        where ``overlay`` is the field delta for refreshing the snapshot
        locally after a successful commit.  Raises
        :class:`IllegalTransition` on the first invalid step."""
        key = meta_key(job_id)
        ops: list[tuple] = []
        overlay: dict[str, bytes] = {}
        cur = dict(snap.fields)
        prev = (cur.get("state") or b"").decode()
        exists = bool(cur)
        changed = False
        for state, fields, event in steps:
            if prev == state.value:
                # idempotent re-apply: update fields only, no transition ops
                if fields:
                    m = {k: v.encode() for k, v in fields.items()}
                    ops.append(("hset", key, m))
                    overlay.update(m)
                    cur.update(m)
                continue
            if not is_allowed_transition(prev, state):
                raise IllegalTransition(job_id, prev, state.value)
            ts = now_us()
            mapping: dict[str, bytes] = {
                "state": state.value.encode(),
                "updated_at_us": str(ts).encode(),
            }
            if not exists:
                mapping["created_at_us"] = str(ts).encode()
            if state in TERMINAL_STATES:
                mapping["finished_at_us"] = str(ts).encode()
            for k, v in (fields or {}).items():
                mapping[k] = v.encode()
            ops.append(("hset", key, mapping))
            if prev:
                ops.append(("zrem", index_key(prev), job_id))
            ops.append(("zadd", index_key(state.value), job_id, float(ts)))
            ops.append(("zadd", RECENT_KEY, job_id, float(ts)))
            ev = {
                "ts_us": ts,
                "state": state.value,
                "prev": prev,
                "event": event or f"state:{state.value}",
            }
            ops.append(("rpush", events_key(job_id), pack_record(ev)))
            if state in TERMINAL_STATES:
                ops.append(("zrem", DEADLINE_KEY, job_id))
                tenant = (cur.get("tenant_id") or b"").decode()
                if tenant and prev and prev not in _TERMINAL_VALUES:
                    ops.append(("zrem", f"job:tenant_active:{tenant}", job_id))
            overlay.update(mapping)
            cur.update(mapping)
            prev = state.value
            exists = True
            changed = True
        if changed:
            ops.append(("ltrim", events_key(job_id), -EVENTS_CAP, -1))
            ops.append(("expire", key, self.meta_ttl_s))
        return ops, overlay, changed

    def build_chain_ops(
        self, job_id: str, snap: MetaSnapshot, steps: list[Transition]
    ) -> tuple[list[tuple], dict[str, bytes], bool]:
        """Public transition-op builder for callers that fold SEVERAL jobs'
        chains into one grouped pipelined commit (scheduler tick batching):
        returns ``(ops, overlay, changed)`` exactly like the internal
        builder, leaving the commit (and its watches) to the caller."""
        return self._chain_ops(job_id, snap, steps)

    async def apply_chain(
        self,
        job_id: str,
        steps: list[Transition],
        *,
        snap: Optional[MetaSnapshot] = None,
        extra_ops: Optional[list[tuple]] = None,
        max_retries: int = 16,
    ) -> tuple[Optional[bool], MetaSnapshot]:
        """Apply a chain of validated transitions (plus any ``extra_ops``
        record writes) as ONE pipelined, version-checked commit.

        ``snap`` (from :meth:`watch_meta`, a previous ``apply_chain``, or
        ``MetaSnapshot()`` for the optimistic "job does not exist yet" fast
        path) makes the first attempt read-free; a conflict re-reads and
        retries.  Returns ``(changed, snap)``: ``True`` if any step moved
        the state, ``False`` if every step was an idempotent re-apply, and
        ``None`` when ``max_retries`` attempts all lost the race (the
        returned snapshot is then a fresh read the caller can inspect).
        Raises :class:`IllegalTransition` on an invalid step."""
        key = meta_key(job_id)
        for attempt in range(max_retries):
            if snap is None:
                snap = await self.watch_meta(job_id)
            ops, overlay, changed = self._chain_ops(job_id, snap, steps)
            if extra_ops:
                ops = [*ops, *extra_ops]
            if not ops:
                return False, snap
            # direct pipe_execute: _chain_ops only emits PIPELINE_OPS names
            # (re-validated store-side), so the Pipeline buffering/validation
            # layer is pure overhead on this hot path (BENCH_r05 regression)
            ok, versions = await self.kv.pipe_execute({key: snap.version}, ops)
            if ok:
                merged = dict(snap.fields)
                merged.update(overlay)
                return changed, MetaSnapshot(versions.get(key, 0), merged)
            snap = None  # lost the race: re-read on the next attempt
        return None, await self.watch_meta(job_id)

    async def set_state(
        self,
        job_id: str,
        state: JobState,
        *,
        fields: Optional[dict[str, str]] = None,
        event: str = "",
        max_retries: int = 16,
        snap: Optional[MetaSnapshot] = None,
        extra_ops: Optional[list[tuple]] = None,
    ) -> bool:
        """Atomic validated transition.  Returns True if the state changed,
        False if the job is already in ``state`` (idempotent re-apply).
        Raises :class:`IllegalTransition` otherwise."""
        changed, _ = await self.apply_chain(
            job_id, [(state, fields, event)],
            snap=snap, extra_ops=extra_ops, max_retries=max_retries,
        )
        if changed is None:
            raise RuntimeError(
                f"job {job_id}: transition to {state.value} lost race repeatedly"
            )
        return changed

    def set_fields_ops(self, job_id: str, fields: dict[str, str]) -> list[tuple]:
        return [
            ("hset", meta_key(job_id), {k: v.encode() for k, v in fields.items()}),
            ("expire", meta_key(job_id), self.meta_ttl_s),
        ]

    async def set_fields(self, job_id: str, fields: dict[str, str]) -> None:
        await self.kv.pipe_execute({}, self.set_fields_ops(job_id, fields))

    async def is_terminal(self, job_id: str) -> bool:
        st = await self.get_state(job_id)
        return bool(st) and st in _TERMINAL_VALUES

    # ------------------------------------------------------------------
    # indexes / listing
    # ------------------------------------------------------------------
    async def list_by_state(self, state: str, limit: int = 100) -> list[str]:
        ids = await self.kv.zrange(index_key(state), 0, limit - 1 if limit else -1)
        return ids

    async def list_by_state_older_than(
        self, state: str, cutoff_us: int, limit: int = 200
    ) -> list[str]:
        return await self.kv.zrangebyscore(index_key(state), 0, float(cutoff_us), limit=limit)

    async def list_recent(self, limit: int = 100) -> list[str]:
        return await self.kv.zrange(RECENT_KEY, 0, limit - 1, desc=True)

    # ------------------------------------------------------------------
    # deadlines
    # ------------------------------------------------------------------
    def register_deadline_ops(self, job_id: str, deadline_unix_ms: int) -> list[tuple]:
        return [("zadd", DEADLINE_KEY, job_id, float(deadline_unix_ms))]

    async def register_deadline(self, job_id: str, deadline_unix_ms: int) -> None:
        await self.kv.zadd(DEADLINE_KEY, job_id, float(deadline_unix_ms))

    async def expired_deadlines(self, now_ms: int, limit: int = 100) -> list[str]:
        return await self.kv.zrangebyscore(DEADLINE_KEY, 0, float(now_ms), limit=limit)

    async def clear_deadline(self, job_id: str) -> None:
        await self.kv.zrem(DEADLINE_KEY, job_id)

    # ------------------------------------------------------------------
    # events / traces
    # ------------------------------------------------------------------
    async def append_event(self, job_id: str, event: str, **kw: Any) -> None:
        ev = {"ts_us": now_us(), "event": event, **kw}
        await self.kv.pipe_execute({}, [
            ("rpush", events_key(job_id), pack_record(ev)),
            ("ltrim", events_key(job_id), -EVENTS_CAP, -1),
        ])

    async def events(self, job_id: str) -> list[dict]:
        # unpack_record reads both the msgpack entries this build
        # writes and legacy JSON entries from pre-ISSUE-6 AOF/KV data
        return [unpack_record(b) for b in await self.kv.lrange(events_key(job_id))]

    def add_to_trace_ops(self, trace_id: str, job_id: str) -> list[tuple]:
        return [("sadd", trace_key(trace_id), job_id)] if trace_id else []

    async def add_to_trace(self, trace_id: str, job_id: str) -> None:
        if trace_id:
            await self.kv.sadd(trace_key(trace_id), job_id)

    async def trace(self, trace_id: str) -> set[str]:
        return await self.kv.smembers(trace_key(trace_id))

    # ------------------------------------------------------------------
    # tenant concurrency
    # ------------------------------------------------------------------
    def tenant_active_add_ops(self, tenant_id: str, job_id: str) -> list[tuple]:
        return [("zadd", f"job:tenant_active:{tenant_id}", job_id, float(now_us()))]

    async def tenant_active_add(self, tenant_id: str, job_id: str) -> int:
        key = f"job:tenant_active:{tenant_id}"
        await self.kv.zadd(key, job_id, float(now_us()))
        return await self.kv.zcard(key)

    async def tenant_active_remove(self, tenant_id: str, job_id: str) -> None:
        await self.kv.zrem(f"job:tenant_active:{tenant_id}", job_id)

    async def tenant_active_count(self, tenant_id: str) -> int:
        return await self.kv.zcard(f"job:tenant_active:{tenant_id}")

    # ------------------------------------------------------------------
    # idempotency + locks
    # ------------------------------------------------------------------
    async def try_set_idempotency_key(
        self, scope: str, key: str, job_id: str, ttl_s: float = 24 * 3600
    ) -> tuple[bool, str]:
        """Reserve ``key`` in ``scope``; returns (reserved, existing_job_id)."""
        k = f"idem:{scope}:{key}"
        ok = await self.kv.setnx(k, job_id.encode(), ttl_s)
        if ok:
            return True, job_id
        cur = await self.kv.get(k)
        return False, cur.decode() if cur else ""

    async def acquire_job_lock(self, job_id: str, owner: str, ttl_s: float = 30.0) -> bool:
        return await self.kv.setnx(f"lock:job:{job_id}", owner.encode(), ttl_s)

    async def release_job_lock(self, job_id: str, owner: str) -> None:
        # atomic compare-and-delete: one round trip, and no window where a
        # TTL-expired-and-reacquired lock could be deleted out from under
        # its new owner between the read and the delete
        await self.kv.del_eq(f"lock:job:{job_id}", owner.encode())

    # ------------------------------------------------------------------
    # persisted requests (for replays + approvals)
    # ------------------------------------------------------------------
    def put_request_ops(self, req: JobRequest) -> list[tuple]:
        return [("set", request_key(req.job_id), req.to_wire(), self.meta_ttl_s)]

    async def put_request(self, req: JobRequest) -> None:
        await self.kv.set(request_key(req.job_id), req.to_wire(), self.meta_ttl_s)

    async def get_request(self, job_id: str) -> Optional[JobRequest]:
        b = await self.kv.get(request_key(job_id))
        return JobRequest.from_wire(b) if b else None

    # ------------------------------------------------------------------
    # safety decisions + approvals
    # ------------------------------------------------------------------
    def put_safety_decision_ops(self, rec: SafetyDecisionRecord) -> list[tuple]:
        rec.decided_at_us = rec.decided_at_us or now_us()
        return [(
            "set", f"job:safety:{rec.job_id}",
            pack_record(rec.__dict__), self.meta_ttl_s,
        )]

    async def put_safety_decision(self, rec: SafetyDecisionRecord) -> None:
        rec.decided_at_us = rec.decided_at_us or now_us()
        await self.kv.set(
            f"job:safety:{rec.job_id}", pack_record(rec.__dict__), self.meta_ttl_s
        )

    async def get_safety_decision(self, job_id: str) -> Optional[SafetyDecisionRecord]:
        b = await self.kv.get(f"job:safety:{job_id}")
        return SafetyDecisionRecord(**unpack_record(b)) if b else None

    async def put_approval(self, rec: ApprovalRecord) -> None:
        rec.decided_at_us = rec.decided_at_us or now_us()
        await self.kv.set(
            f"job:approval:{rec.job_id}", pack_record(rec.__dict__), self.meta_ttl_s
        )

    async def get_approval(self, job_id: str) -> Optional[ApprovalRecord]:
        b = await self.kv.get(f"job:approval:{job_id}")
        return ApprovalRecord(**unpack_record(b)) if b else None

    # ------------------------------------------------------------------
    async def cancel_job(self, job_id: str) -> bool:
        """Move a non-terminal job to CANCELLED; False if terminal/unknown."""
        snap = await self.watch_meta(job_id)
        if not snap.state or snap.is_terminal:
            return False
        try:
            await self.set_state(job_id, JobState.CANCELLED, event="cancel", snap=snap)
            return True
        except IllegalTransition:
            return False
