"""State store abstraction (the framework's Redis-equivalent).

All control-plane state lives behind this interface: job metadata hashes,
state indexes (sorted sets), event logs (lists), pointers (``ctx:``/``res:``/
``art:`` strings), locks (set-if-absent), and optimistic transactions
(version-checked multi-key commits — the WATCH/MULTI equivalent the
reference job store builds on, ``core/infra/memory/job_store.go``).

Implementations:
  * :class:`MemoryKV` — in-process asyncio store with TTLs and per-key
    versions.  Used by tests (the miniredis analogue) and by single-process
    deployments.
  * ``cordum_tpu.infra.statebus.StateBusClient`` — TCP client to the
    standalone statebus server for multi-process deployments.

Pointer scheme: ``kv://<key>`` (reference uses ``redis://<key>``,
``core/infra/memory/redis_store.go:139-158``).
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Iterable, Optional

POINTER_SCHEME = "kv://"


def pointer_for_key(key: str) -> str:
    return POINTER_SCHEME + key


def key_from_pointer(ptr: str) -> str:
    for scheme in (POINTER_SCHEME, "redis://"):
        if ptr.startswith(scheme):
            return ptr[len(scheme):]
    return ptr


class TxnConflict(Exception):
    """Optimistic transaction lost the race; caller retries."""


# Mutating ops a Pipeline may buffer (superset of the old commit() op table).
# frozenset: membership checks sit on the scheduler hot path (one per
# buffered op), and a tuple scan was measurable at bench job rates.
PIPELINE_OPS = frozenset((
    "set", "delete", "hset", "hdel", "zadd", "zrem", "rpush", "ltrim",
    "sadd", "expire", "del_eq",
))


class Pipeline:
    """Buffered multi-op batch with optional version watches.

    Ops are queued client-side and applied in ONE backend round trip:
    ``MemoryKV`` executes the whole batch inside a single lock acquisition;
    ``StateBusKV`` ships it as a single ``PIPE`` wire frame that the server
    applies atomically (the Redis MULTI/EXEC + pipelining equivalent the
    reference job store leans on for its hot path).

    ``watch(key, version)`` turns the batch into an optimistic transaction:
    it applies iff every watched key still carries the given version
    (version 0 = key absent).  ``execute()`` returns True on success and
    False on conflict; after a successful execute, ``new_versions`` maps
    each watched key to its post-commit version so chained transactions on
    the same key need no re-read round trip.

    Ops are validated (by name) before anything is applied — an unknown op
    rejects the WHOLE batch with ``ValueError`` and leaves state untouched.
    """

    __slots__ = ("_kv", "_watches", "_ops", "new_versions")

    def __init__(self, kv: "KV") -> None:
        self._kv = kv
        self._watches: dict[str, int] = {}
        self._ops: list[tuple] = []
        self.new_versions: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._ops)

    @property
    def ops(self) -> list[tuple]:
        return list(self._ops)

    def watch(self, key: str, version: int) -> "Pipeline":
        self._watches[key] = version
        return self

    def op(self, name: str, *args: Any) -> "Pipeline":
        if name not in PIPELINE_OPS:
            raise ValueError(f"op {name!r} is not pipelineable")
        self._ops.append((name, *args))
        return self

    def extend(self, ops: Iterable[tuple]) -> "Pipeline":
        for o in ops:
            self.op(*o)
        return self

    # buffered op builders ------------------------------------------------
    def set(self, key: str, value: bytes, ttl_s: Optional[float] = None) -> "Pipeline":
        return self.op("set", key, value, ttl_s)

    def delete(self, *keys: str) -> "Pipeline":
        return self.op("delete", *keys)

    def del_eq(self, key: str, expect: bytes) -> "Pipeline":
        return self.op("del_eq", key, expect)

    def hset(self, key: str, mapping: dict[str, bytes]) -> "Pipeline":
        return self.op("hset", key, mapping)

    def hdel(self, key: str, *fields: str) -> "Pipeline":
        return self.op("hdel", key, *fields)

    def zadd(self, key: str, member: str, score: float) -> "Pipeline":
        return self.op("zadd", key, member, score)

    def zrem(self, key: str, *members: str) -> "Pipeline":
        return self.op("zrem", key, *members)

    def rpush(self, key: str, *values: bytes) -> "Pipeline":
        return self.op("rpush", key, *values)

    def ltrim(self, key: str, start: int, stop: int) -> "Pipeline":
        return self.op("ltrim", key, start, stop)

    def sadd(self, key: str, *members: str) -> "Pipeline":
        return self.op("sadd", key, *members)

    def expire(self, key: str, ttl_s: float) -> "Pipeline":
        return self.op("expire", key, ttl_s)

    async def execute(self) -> bool:
        ok, versions = await self._kv.pipe_execute(dict(self._watches), list(self._ops))
        self.new_versions = versions
        return ok


class KV:
    """Async key-value interface.  Values are bytes; hashes map str->bytes."""

    #: bound by services that want `cordum_kv_roundtrips_total{op}` /
    #: `cordum_kv_pipeline_size` emitted (see infra/metrics.py)
    metrics: Any = None

    def bind_metrics(self, metrics: Any) -> None:
        self.metrics = metrics

    def _observe_op(self, op: str, pipeline_size: int = 0) -> None:
        m = self.metrics
        if m is not None:
            m.kv_roundtrips.inc(op=op)
            if pipeline_size:
                m.kv_pipeline_size.observe(float(pipeline_size))

    # strings -------------------------------------------------------------
    async def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    async def set(self, key: str, value: bytes, ttl_s: Optional[float] = None) -> None:
        raise NotImplementedError

    async def setnx(self, key: str, value: bytes, ttl_s: Optional[float] = None) -> bool:
        raise NotImplementedError

    async def delete(self, *keys: str) -> int:
        raise NotImplementedError

    async def del_eq(self, key: str, expect: bytes) -> bool:
        """Delete ``key`` iff its current value equals ``expect`` (atomic
        compare-and-delete — the owner-checked lock release in one round
        trip instead of get+delete)."""
        raise NotImplementedError

    async def expire(self, key: str, ttl_s: float) -> bool:
        raise NotImplementedError

    async def keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    # hashes --------------------------------------------------------------
    async def hset(self, key: str, mapping: dict[str, bytes]) -> None:
        raise NotImplementedError

    async def hget(self, key: str, field: str) -> Optional[bytes]:
        raise NotImplementedError

    async def hgetall(self, key: str) -> dict[str, bytes]:
        raise NotImplementedError

    async def hdel(self, key: str, *fields: str) -> int:
        raise NotImplementedError

    async def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        raise NotImplementedError

    # sorted sets ---------------------------------------------------------
    async def zadd(self, key: str, member: str, score: float) -> None:
        raise NotImplementedError

    async def zrem(self, key: str, *members: str) -> int:
        raise NotImplementedError

    async def zrange(
        self, key: str, start: int = 0, stop: int = -1, desc: bool = False
    ) -> list[str]:
        raise NotImplementedError

    async def zrangebyscore(
        self, key: str, min_score: float, max_score: float, limit: int = 0
    ) -> list[str]:
        raise NotImplementedError

    async def zcard(self, key: str) -> int:
        raise NotImplementedError

    async def zscore(self, key: str, member: str) -> Optional[float]:
        raise NotImplementedError

    # lists ---------------------------------------------------------------
    async def rpush(self, key: str, *values: bytes) -> int:
        raise NotImplementedError

    async def lrange(self, key: str, start: int = 0, stop: int = -1) -> list[bytes]:
        raise NotImplementedError

    async def ltrim(self, key: str, start: int, stop: int) -> None:
        raise NotImplementedError

    async def llen(self, key: str) -> int:
        raise NotImplementedError

    # sets ----------------------------------------------------------------
    async def sadd(self, key: str, *members: str) -> int:
        raise NotImplementedError

    async def smembers(self, key: str) -> set[str]:
        raise NotImplementedError

    # transactions --------------------------------------------------------
    async def version(self, key: str) -> int:
        """Monotonic per-key version (bumped on every mutation); 0 if absent."""
        raise NotImplementedError

    async def watch_read(self, key: str) -> tuple[int, dict[str, bytes]]:
        """Atomic (version, hash-contents) snapshot — one round trip for the
        optimistic read-modify-write loop."""
        ver = await self.version(key)
        return ver, await self.hgetall(key)

    async def commit(
        self,
        watches: dict[str, int],
        ops: list[tuple],
    ) -> bool:
        """Atomically apply `ops` iff every watched key still has the given
        version.  Each op is ``(method_name, *args)``.  Returns False on
        conflict (the WATCH-abort equivalent)."""
        raise NotImplementedError

    # pipelining ----------------------------------------------------------
    def pipeline(self) -> Pipeline:
        """Start a buffered multi-op batch (see :class:`Pipeline`)."""
        return Pipeline(self)

    def pipe_group(self, key: str) -> int:
        """Grouping hint for CROSS-KEY pipelined commits: keys mapping to the
        same group may be folded into one ``pipe_execute`` and commit
        atomically (the scheduler's tick batching relies on this).  Single-
        server stores put every key in group 0; the partitioned client
        returns the key's partition index."""
        return 0

    async def pipe_execute(
        self, watches: dict[str, int], ops: list[tuple]
    ) -> tuple[bool, dict[str, int]]:
        """Apply a pipeline batch in one round trip.  Returns ``(ok,
        new_versions)`` where ``new_versions`` maps each watched key to its
        post-commit version (chained optimistic transactions read-free)."""
        raise NotImplementedError

    async def ping(self) -> bool:
        return True

    async def close(self) -> None:
        return None


class _Entry:
    __slots__ = ("value", "expires_at", "version")

    def __init__(self, value: Any, expires_at: Optional[float], version: int) -> None:
        self.value = value
        self.expires_at = expires_at
        self.version = version


class MemoryKV(KV):
    """In-process store with TTL and per-key version counters."""

    def __init__(self) -> None:
        self._data: dict[str, _Entry] = {}
        self._lock = asyncio.Lock()
        self._global_version = 0
        # bound-method op table: resolved once here instead of a name →
        # attr-name → getattr chain per op inside every pipelined commit
        self._bound_ops = {name: getattr(self, attr) for name, attr in self._OPS.items()}

    # internal helpers (caller holds lock) --------------------------------
    def _live(self, key: str) -> Optional[_Entry]:
        e = self._data.get(key)
        if e is None:
            return None
        if e.expires_at is not None and e.expires_at <= time.monotonic():
            del self._data[key]
            return None
        return e

    def _bump(self, key: str, value: Any, ttl_s: Optional[float] = None, keep_ttl: bool = False) -> _Entry:
        self._global_version += 1
        prev = self._data.get(key)
        expires_at = None
        if ttl_s is not None:
            expires_at = time.monotonic() + ttl_s
        elif keep_ttl and prev is not None:
            expires_at = prev.expires_at
        e = _Entry(value, expires_at, self._global_version)
        self._data[key] = e
        return e

    def _touch(self, e: _Entry) -> None:
        """Bump the version of an in-place-mutated container (no copy —
        containers can be large: indexes, event logs)."""
        self._global_version += 1
        e.version = self._global_version

    def _container(self, key: str, factory) -> _Entry:
        e = self._live(key)
        if e is None or not isinstance(e.value, type(factory())):
            e = self._bump(key, factory())
        return e

    # strings -------------------------------------------------------------
    async def get(self, key: str) -> Optional[bytes]:
        async with self._lock:
            e = self._live(key)
            return e.value if e is not None and isinstance(e.value, bytes) else None

    async def set(self, key: str, value: bytes, ttl_s: Optional[float] = None) -> None:
        async with self._lock:
            self._set_op(key, value, ttl_s)

    async def setnx(self, key: str, value: bytes, ttl_s: Optional[float] = None) -> bool:
        async with self._lock:
            if self._live(key) is not None:
                return False
            self._bump(key, value, ttl_s)
            return True

    async def delete(self, *keys: str) -> int:
        async with self._lock:
            return self._delete_op(*keys)

    async def del_eq(self, key: str, expect: bytes) -> bool:
        async with self._lock:
            return bool(self._del_eq_op(key, expect))

    async def expire(self, key: str, ttl_s: float) -> bool:
        async with self._lock:
            e = self._live(key)
            if e is None:
                return False
            e.expires_at = time.monotonic() + ttl_s
            return True

    async def keys(self, prefix: str = "") -> list[str]:
        async with self._lock:
            return [k for k in list(self._data) if self._live(k) is not None and k.startswith(prefix)]

    # hashes --------------------------------------------------------------
    async def hset(self, key: str, mapping: dict[str, bytes]) -> None:
        async with self._lock:
            self._hset_op(key, mapping)

    async def hget(self, key: str, field: str) -> Optional[bytes]:
        async with self._lock:
            e = self._live(key)
            if e is None or not isinstance(e.value, dict):
                return None
            return e.value.get(field)

    async def hgetall(self, key: str) -> dict[str, bytes]:
        async with self._lock:
            e = self._live(key)
            if e is None or not isinstance(e.value, dict):
                return {}
            return dict(e.value)

    async def hdel(self, key: str, *fields: str) -> int:
        async with self._lock:
            return self._hdel_op(key, *fields)

    async def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        async with self._lock:
            e = self._container(key, dict)
            cur = int(e.value.get(field, b"0")) + amount
            e.value[field] = str(cur).encode()
            self._touch(e)
            return cur

    # sorted sets ---------------------------------------------------------
    async def zadd(self, key: str, member: str, score: float) -> None:
        async with self._lock:
            self._zadd_op(key, member, score)

    async def zrem(self, key: str, *members: str) -> int:
        async with self._lock:
            return self._zrem_op(key, *members)

    async def zrange(self, key: str, start: int = 0, stop: int = -1, desc: bool = False) -> list[str]:
        async with self._lock:
            e = self._live(key)
            if e is None or not isinstance(e.value, dict):
                return []
            items = sorted(e.value.items(), key=lambda kv: (kv[1], kv[0]), reverse=desc)
            members = [m for m, _ in items]
            if stop == -1:
                return members[start:]
            return members[start : stop + 1]

    async def zrangebyscore(self, key: str, min_score: float, max_score: float, limit: int = 0) -> list[str]:
        async with self._lock:
            e = self._live(key)
            if e is None or not isinstance(e.value, dict):
                return []
            items = sorted(
                ((m, s) for m, s in e.value.items() if min_score <= s <= max_score),
                key=lambda kv: (kv[1], kv[0]),
            )
            members = [m for m, _ in items]
            return members[:limit] if limit else members

    async def zcard(self, key: str) -> int:
        async with self._lock:
            e = self._live(key)
            return len(e.value) if e is not None and isinstance(e.value, dict) else 0

    async def zscore(self, key: str, member: str) -> Optional[float]:
        async with self._lock:
            e = self._live(key)
            if e is None or not isinstance(e.value, dict):
                return None
            return e.value.get(member)

    # lists ---------------------------------------------------------------
    async def rpush(self, key: str, *values: bytes) -> int:
        async with self._lock:
            return self._rpush_op(key, *values)

    async def lrange(self, key: str, start: int = 0, stop: int = -1) -> list[bytes]:
        async with self._lock:
            e = self._live(key)
            if e is None or not isinstance(e.value, list):
                return []
            lst = e.value
            if stop == -1:
                return list(lst[start:] if start >= 0 else lst[start:])
            if start < 0:
                start = max(0, len(lst) + start)
            return list(lst[start : stop + 1])

    async def ltrim(self, key: str, start: int, stop: int) -> None:
        async with self._lock:
            self._ltrim_op(key, start, stop)

    async def llen(self, key: str) -> int:
        async with self._lock:
            e = self._live(key)
            return len(e.value) if e is not None and isinstance(e.value, list) else 0

    # sets ----------------------------------------------------------------
    async def sadd(self, key: str, *members: str) -> int:
        async with self._lock:
            return self._sadd_op(key, *members)

    async def smembers(self, key: str) -> set[str]:
        async with self._lock:
            e = self._live(key)
            return set(e.value) if e is not None and isinstance(e.value, set) else set()

    # transactions --------------------------------------------------------
    async def version(self, key: str) -> int:
        async with self._lock:
            e = self._live(key)
            return e.version if e is not None else 0

    async def watch_read(self, key: str) -> tuple[int, dict[str, bytes]]:
        async with self._lock:
            e = self._live(key)
            if e is None:
                return 0, {}
            h = dict(e.value) if isinstance(e.value, dict) else {}
            return e.version, h

    # op appliers used by commit(); all assume lock held
    def _set_op(self, key: str, value: bytes, ttl_s: Optional[float] = None) -> None:
        self._bump(key, value, ttl_s)

    def _delete_op(self, *keys: str) -> int:
        n = 0
        for k in keys:
            if self._live(k) is not None:
                del self._data[k]
                n += 1
        return n

    def _del_eq_op(self, key: str, expect: bytes) -> int:
        e = self._live(key)
        if e is not None and e.value == expect:
            del self._data[key]
            return 1
        return 0

    def _hdel_op(self, key: str, *fields: str) -> int:
        e = self._live(key)
        if e is None or not isinstance(e.value, dict):
            return 0
        n = 0
        for f in fields:
            if f in e.value:
                del e.value[f]
                n += 1
        if n:
            self._touch(e)
        return n

    def _ltrim_op(self, key: str, start: int, stop: int) -> None:
        e = self._live(key)
        if e is None or not isinstance(e.value, list):
            return
        lst = e.value
        e.value = lst[start:] if stop == -1 else lst[start : stop + 1]
        self._touch(e)

    def _sadd_op(self, key: str, *members: str) -> int:
        e = self._container(key, set)
        before = len(e.value)
        e.value.update(members)
        self._touch(e)
        return len(e.value) - before

    def _hset_op(self, key: str, mapping: dict[str, bytes]) -> None:
        e = self._container(key, dict)
        e.value.update(mapping)
        self._touch(e)

    def _zadd_op(self, key: str, member: str, score: float) -> None:
        e = self._container(key, dict)
        e.value[member] = score
        self._touch(e)

    def _zrem_op(self, key: str, *members: str) -> int:
        e = self._live(key)
        if e is None or not isinstance(e.value, dict):
            return 0
        n = 0
        for m in members:
            if m in e.value:
                del e.value[m]
                n += 1
        if n:
            self._touch(e)
        return n

    def _rpush_op(self, key: str, *values: bytes) -> int:
        e = self._container(key, list)
        e.value.extend(values)
        self._touch(e)
        return len(e.value)

    def _expire_op(self, key: str, ttl_s: float) -> None:
        e = self._live(key)
        if e is not None:
            e.expires_at = time.monotonic() + ttl_s

    _OPS = {
        "set": "_set_op",
        "delete": "_delete_op",
        "del_eq": "_del_eq_op",
        "hset": "_hset_op",
        "hdel": "_hdel_op",
        "zadd": "_zadd_op",
        "zrem": "_zrem_op",
        "rpush": "_rpush_op",
        "ltrim": "_ltrim_op",
        "sadd": "_sadd_op",
        "expire": "_expire_op",
    }

    def _pipe_locked(
        self, watches: dict[str, int], ops: list[tuple]
    ) -> tuple[bool, dict[str, int]]:
        """Caller holds the lock.  Validates op names BEFORE applying so an
        unknown op rejects the whole batch (never a partial application),
        then checks watches and applies.  Returns post-commit versions of
        the watched keys."""
        bound = self._bound_ops
        appliers = []
        for op in ops:
            applier = bound.get(op[0])
            if applier is None:
                raise ValueError(f"unknown pipeline op {op[0]!r}")
            appliers.append((applier, op))
        for key, ver in watches.items():
            e = self._live(key)
            cur = e.version if e is not None else 0
            if cur != ver:
                return False, {}
        for applier, op in appliers:
            applier(*op[1:])
        versions: dict[str, int] = {}
        for key in watches:
            e = self._live(key)
            versions[key] = e.version if e is not None else 0
        return True, versions

    async def commit(self, watches: dict[str, int], ops: list[tuple]) -> bool:
        async with self._lock:
            ok, _ = self._pipe_locked(watches, ops)
            return ok

    # replication snapshot (infra/replication.py) -------------------------
    async def snapshot(self) -> bytes:
        """Full-state dump for replica bootstrap: every live entry with its
        VERSION preserved, so a replica loaded from a snapshot and then fed
        the primary's op stream stays byte-for-byte version-identical —
        clients that fail over mid-pipeline keep their watched versions
        valid instead of conflicting on the first post-failover commit."""
        import msgpack

        async with self._lock:
            now = time.monotonic()
            items = []
            for k, e in self._data.items():
                if e.expires_at is not None and e.expires_at <= now:
                    continue
                tag, v = ("set", sorted(e.value)) if isinstance(e.value, set) else ("raw", e.value)
                ttl = None if e.expires_at is None else e.expires_at - now
                items.append([k, tag, v, e.version, ttl])
            return msgpack.packb([self._global_version, items], use_bin_type=True)

    async def load_snapshot(self, blob: bytes) -> None:
        """Replace the whole store with a :meth:`snapshot` dump (replica
        bootstrap / rejoin-after-divergence).  TTLs resume from now."""
        import msgpack

        gv, items = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        async with self._lock:
            self._data.clear()
            now = time.monotonic()
            for k, tag, v, ver, ttl in items:
                if tag == "set":
                    v = set(v)
                self._data[k] = _Entry(v, None if ttl is None else now + ttl, int(ver))
            self._global_version = int(gv)

    async def pipe_execute(
        self, watches: dict[str, int], ops: list[tuple]
    ) -> tuple[bool, dict[str, int]]:
        self._observe_op("pipe", pipeline_size=len(ops))
        async with self._lock:
            return self._pipe_locked(watches, ops)


# Per-op round-trip accounting: every public MemoryKV op takes the store lock
# exactly once, so it is the in-process analogue of one wire round trip —
# instrumented uniformly so `cordum_kv_roundtrips_total{op}` means the same
# thing it means for StateBusKV (one TCP request) and bench.py can compute
# kv_roundtrips_per_job against either backend.
_COUNTED_OPS = (
    "get", "set", "setnx", "delete", "del_eq", "expire", "keys",
    "hset", "hget", "hgetall", "hdel", "hincrby",
    "zadd", "zrem", "zrange", "zrangebyscore", "zcard", "zscore",
    "rpush", "lrange", "ltrim", "llen", "sadd", "smembers",
    "version", "watch_read", "commit",
)


def _counted(name: str, fn: Any) -> Any:
    async def method(self: MemoryKV, *args: Any, **kwargs: Any) -> Any:
        self._observe_op(name)
        return await fn(self, *args, **kwargs)

    method.__name__ = name
    method.__doc__ = fn.__doc__
    return method


for _name in _COUNTED_OPS:
    setattr(MemoryKV, _name, _counted(_name, getattr(MemoryKV, _name)))
