"""Multi-tenant open-loop traffic generator — the storm harness's engine.

Models the "millions of users" scenario in miniature (ROADMAP item 1;
docs/ADMISSION.md §Storm harness): each :class:`TenantSpec` drives an
**open-loop** Poisson arrival process of *sessions* — arrivals do not wait
for completions, so offered load stays at the configured rate no matter how
slow the system gets (the property that makes overload benchmarks honest;
a closed-loop driver self-throttles and hides collapse).

Per tenant the rate can be shaped:

* **bursts** — every ``burst_every_s`` the rate multiplies by
  ``burst_factor`` for ``burst_len_s`` (retry-storm / thundering-herd);
* **diurnal ramp** — a sine of period ``diurnal_period_s`` and relative
  amplitude ``diurnal_amp`` modulates the base rate (the day/night curve,
  compressed);
* **sessions with think time** — a session submits ``session_turns`` jobs
  spaced ``think_time_s`` apart (conversation turns), all sharing one
  ``session_id`` so scheduler session affinity engages.

The generator owns arrivals ONLY.  The caller's ``submit`` callback does
the actual work (drive the gateway admission path, publish to the bus, ...)
and returns quickly; completion/latency tracking stays with the caller.
Determinism: ``rng`` is an injectable ``random.Random`` and all pacing uses
the injectable monotonic ``clock``.
"""
from __future__ import annotations

import asyncio
import math
import random
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

# submit(spec, session_id, turn_index) -> awaited per arrival; the return
# value is ignored by the generator (the caller tracks outcomes)
SubmitFn = Callable[["TenantSpec", str, int], Awaitable[Any]]


@dataclass
class TenantSpec:
    """One tenant's traffic shape."""

    name: str
    job_class: str = "BATCH"  # JobRequest.priority
    op: str = "echo"  # payload op (keys into the capacity matrix)
    topic: str = "job.storm"
    rate_rps: float = 10.0  # mean session arrival rate
    burst_factor: float = 1.0
    burst_every_s: float = 0.0  # 0 = no bursts
    burst_len_s: float = 1.0
    diurnal_period_s: float = 0.0  # 0 = flat
    diurnal_amp: float = 0.0  # relative amplitude (0..1)
    session_turns: int = 1  # jobs per session
    think_time_s: float = 0.0  # gap between a session's turns
    payload: dict = field(default_factory=dict)

    def rate_at(self, t: float) -> float:
        """Offered session rate at elapsed time ``t`` (bursts + diurnal)."""
        rate = self.rate_rps
        if self.diurnal_period_s > 0 and self.diurnal_amp > 0:
            rate *= 1.0 + self.diurnal_amp * math.sin(
                2 * math.pi * t / self.diurnal_period_s
            )
        if self.burst_every_s > 0 and (
            t % self.burst_every_s < self.burst_len_s
        ):
            rate *= max(1.0, self.burst_factor)
        return max(0.0, rate)


class LoadGen:
    """Drive every tenant's arrival process for ``duration_s`` seconds."""

    def __init__(
        self,
        submit: SubmitFn,
        tenants: list[TenantSpec],
        *,
        duration_s: float,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.submit = submit
        self.tenants = tenants
        self.duration_s = duration_s
        self.rng = rng or random.Random(49374)
        self.clock = clock
        self.sessions_started: dict[str, int] = {}
        self.turns_submitted: dict[str, int] = {}
        self._session_seq = 0

    async def run(self) -> dict:
        """Run all tenants to completion; returns per-tenant arrival counts
        (``{"sessions": {...}, "turns": {...}}``)."""
        tasks = [
            asyncio.ensure_future(self._drive(spec)) for spec in self.tenants
        ]
        session_tasks: set[asyncio.Task] = set()
        self._session_tasks = session_tasks
        try:
            await asyncio.gather(*tasks)
            # let in-flight multi-turn sessions finish their think cycles
            while session_tasks:
                await asyncio.gather(*list(session_tasks),
                                     return_exceptions=True)
        finally:
            for t in [*tasks, *session_tasks]:
                if not t.done():
                    t.cancel()
        return {
            "sessions": dict(self.sessions_started),
            "turns": dict(self.turns_submitted),
        }

    async def _drive(self, spec: TenantSpec) -> None:
        """One tenant's open-loop arrival process."""
        start = self.clock()
        while True:
            t = self.clock() - start
            if t >= self.duration_s:
                return
            rate = spec.rate_at(t)
            if rate <= 0:
                await asyncio.sleep(0.05)
                continue
            # exponential inter-arrival → Poisson process at the shaped rate
            await asyncio.sleep(self.rng.expovariate(rate))
            if self.clock() - start >= self.duration_s:
                return
            self._session_seq += 1
            sid = f"{spec.name}-s{self._session_seq}"
            self.sessions_started[spec.name] = (
                self.sessions_started.get(spec.name, 0) + 1
            )
            if spec.session_turns <= 1:
                await self._turn(spec, sid, 0)
            else:
                # sessions run concurrently with the arrival process (open
                # loop): a slow fleet does NOT slow new session arrivals
                task = asyncio.ensure_future(self._session(spec, sid))
                self._session_tasks.add(task)
                task.add_done_callback(self._session_tasks.discard)

    async def _session(self, spec: TenantSpec, sid: str) -> None:
        for turn in range(spec.session_turns):
            if turn:
                await asyncio.sleep(spec.think_time_s)
            await self._turn(spec, sid, turn)

    async def _turn(self, spec: TenantSpec, sid: str, turn: int) -> None:
        self.turns_submitted[spec.name] = (
            self.turns_submitted.get(spec.name, 0) + 1
        )
        await self.submit(spec, sid, turn)
