"""Shared/exclusive resource locks with TTL and owner counts
(reference ``core/infra/locks/store.go:8-32`` + redis impl)."""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from ..utils.ids import now_s
from .kv import KV


@dataclass
class LockInfo:
    resource: str = ""
    mode: str = "exclusive"  # exclusive | shared
    owners: dict[str, float] = field(default_factory=dict)  # owner -> expires_at (unix s)


def lock_key(resource: str) -> str:
    return f"lock:res:{resource}"


class LockStore:
    def __init__(self, kv: KV) -> None:
        self.kv = kv

    async def _load(self, resource: str) -> Optional[LockInfo]:
        b = await self.kv.get(lock_key(resource))
        if not b:
            return None
        info = LockInfo(**json.loads(b))
        now = now_s()
        info.owners = {o: exp for o, exp in info.owners.items() if exp > now}
        if not info.owners:
            return None
        return info

    async def _store(self, info: LockInfo) -> None:
        max_ttl = max(info.owners.values()) - now_s() if info.owners else 0
        if max_ttl <= 0:
            await self.kv.delete(lock_key(info.resource))
            return
        await self.kv.set(lock_key(info.resource), json.dumps(info.__dict__).encode(), max_ttl)

    async def acquire(
        self, resource: str, owner: str, *, mode: str = "exclusive", ttl_s: float = 30.0
    ) -> bool:
        info = await self._load(resource)
        exp = now_s() + ttl_s
        if info is None:
            await self._store(LockInfo(resource=resource, mode=mode, owners={owner: exp}))
            return True
        if owner in info.owners:  # re-entrant renew
            info.owners[owner] = exp
            await self._store(info)
            return True
        if info.mode == "shared" and mode == "shared":
            info.owners[owner] = exp
            await self._store(info)
            return True
        return False

    async def release(self, resource: str, owner: str) -> bool:
        info = await self._load(resource)
        if info is None or owner not in info.owners:
            return False
        del info.owners[owner]
        await self._store(info)
        return True

    async def renew(self, resource: str, owner: str, ttl_s: float = 30.0) -> bool:
        info = await self._load(resource)
        if info is None or owner not in info.owners:
            return False
        info.owners[owner] = now_s() + ttl_s
        await self._store(info)
        return True

    async def get(self, resource: str) -> Optional[LockInfo]:
        return await self._load(resource)

    async def list(self) -> list[LockInfo]:
        out = []
        for k in await self.kv.keys("lock:res:"):
            info = await self._load(k[len("lock:res:"):])
            if info:
                out.append(info)
        return out
