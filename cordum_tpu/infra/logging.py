"""Leveled key/value logger with optional JSON mode
(reference ``core/infra/logging/logging.go``; ``CORDUM_LOG_FORMAT=json``)."""
from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any

_root = logging.getLogger("cordum")


class _KVFormatter(logging.Formatter):
    def __init__(self, json_mode: bool) -> None:
        super().__init__()
        self.json_mode = json_mode

    def format(self, record: logging.LogRecord) -> str:
        kv = getattr(record, "kv", {})
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
        if self.json_mode:
            d = {
                "ts": ts,
                "level": record.levelname.lower(),
                "logger": record.name,
                "msg": record.getMessage(),
                **kv,
            }
            return json.dumps(d, default=str)
        pairs = " ".join(f"{k}={v}" for k, v in kv.items())
        return f"{ts} {record.levelname:<5} {record.name} {record.getMessage()}" + (
            f" {pairs}" if pairs else ""
        )


def setup(level: str = "") -> None:
    lvl = (level or os.environ.get("CORDUM_LOG_LEVEL", "INFO")).upper()
    json_mode = os.environ.get("CORDUM_LOG_FORMAT", "") == "json"
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(_KVFormatter(json_mode))
    _root.handlers[:] = [h]
    _root.setLevel(lvl)
    _root.propagate = False


def _log(level: int, msg: str, **kv: Any) -> None:
    _root.log(level, msg, extra={"kv": kv})


def debug(msg: str, **kv: Any) -> None:
    _log(logging.DEBUG, msg, **kv)


def info(msg: str, **kv: Any) -> None:
    _log(logging.INFO, msg, **kv)


def warn(msg: str, **kv: Any) -> None:
    _log(logging.WARNING, msg, **kv)


def error(msg: str, **kv: Any) -> None:
    _log(logging.ERROR, msg, **kv)


async def join_task(task: Any, *, name: str) -> None:
    """Await a just-cancelled background task.  Cancellation is the expected
    outcome; any other exception is a real crash that must not vanish in a
    ``stop()`` (CL002) — it is logged with the task name."""
    import asyncio

    try:
        await task
    except asyncio.CancelledError:
        pass
    except Exception as e:  # noqa: BLE001 - logged, never swallowed
        error("background task crashed during shutdown", task=name, err=str(e))
