"""Pointer-based payload store: "pass pointers, not blobs".

Contexts at ``ctx:<job_id>``, results at ``res:<job_id>``, pointers
``kv://ctx:<job_id>`` (reference ``core/infra/memory/redis_store.go:26-159``,
pointer scheme :139-158; data TTL default 24h).
"""
from __future__ import annotations

import json
from typing import Any, Optional

from .kv import KV, key_from_pointer, pointer_for_key

DEFAULT_DATA_TTL_S = 24 * 3600.0


class MemoryStore:
    def __init__(self, kv: KV, *, data_ttl_s: float = DEFAULT_DATA_TTL_S) -> None:
        self.kv = kv
        self.data_ttl_s = data_ttl_s

    @staticmethod
    def context_key(job_id: str) -> str:
        return f"ctx:{job_id}"

    @staticmethod
    def result_key(job_id: str) -> str:
        return f"res:{job_id}"

    async def put_context(self, job_id: str, payload: Any) -> str:
        key = self.context_key(job_id)
        await self.kv.set(key, json.dumps(payload).encode(), self.data_ttl_s)
        return pointer_for_key(key)

    async def get_context(self, ptr_or_job_id: str) -> Optional[Any]:
        return await self._get(ptr_or_job_id, self.context_key)

    async def put_result(self, job_id: str, payload: Any) -> str:
        key = self.result_key(job_id)
        await self.kv.set(key, json.dumps(payload).encode(), self.data_ttl_s)
        return pointer_for_key(key)

    async def get_result(self, ptr_or_job_id: str) -> Optional[Any]:
        return await self._get(ptr_or_job_id, self.result_key)

    async def get_pointer(self, ptr: str) -> Optional[Any]:
        b = await self.kv.get(key_from_pointer(ptr))
        return json.loads(b) if b is not None else None

    async def _get(self, ref: str, default_key) -> Optional[Any]:
        key = key_from_pointer(ref) if "://" in ref or ":" in ref else default_key(ref)
        b = await self.kv.get(key)
        return json.loads(b) if b is not None else None
