"""Prometheus-style metrics: counters, gauges, histograms with text
exposition (reference ``core/infra/metrics/metrics.go``).  Dependency-free;
the gateway/scheduler serve ``render()`` at ``/metrics``.

Thread-safety: ``observe()``/``inc()`` run on worker threads (executor
handlers) while ``render()``/``quantile()`` run on the event loop, so every
read takes the same lock the writers take and works on a snapshot — an
unlocked read can see a histogram's bucket list mid-update and report
totals that never existed.

Two ISSUE 10 additions:

* **Exemplars** — ``Histogram.observe(v, exemplar=trace_id)`` remembers the
  last trace id that landed in each bucket (OpenMetrics-style), rendered as
  ``name_bucket{le="..."} N # {trace_id="..."} value ts`` so a p99 spike in
  ``cordum_job_e2e_seconds`` links straight to an offending trace.  When no
  explicit exemplar is passed, the registered provider (the tracer's active
  span context, wired by ``cordum_tpu.obs``) is consulted.
* **Label-cardinality guard** — a family that sees more than
  ``max_label_sets`` distinct label sets (default 1000, env
  ``CORDUM_METRICS_MAX_LABEL_SETS``) logs once and folds further new sets
  into one ``{overflow="true"}`` series instead of growing unbounded
  (bucket keys derived from job ids would otherwise explode the telemetry
  snapshots).
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Mapping, Optional

from ..utils.ids import now_us

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

DEFAULT_MAX_LABEL_SETS = int(os.environ.get("CORDUM_METRICS_MAX_LABEL_SETS", "1000"))
_OVERFLOW_KEY: tuple[tuple[str, str], ...] = (("overflow", "true"),)

# ambient exemplar source: (trace_id, span_id) of the active span; set by
# cordum_tpu.obs at import so metrics stays importable without the tracer
_exemplar_provider: Optional[Callable[[], tuple[str, str]]] = None
_exemplars_enabled = True


def set_exemplar_provider(fn: Optional[Callable[[], tuple[str, str]]]) -> None:
    global _exemplar_provider
    _exemplar_provider = fn


def set_exemplars_enabled(on: bool) -> None:
    """Global exemplar kill-switch (bench overhead pairs toggle it)."""
    global _exemplars_enabled
    _exemplars_enabled = on


def _log_overflow(name: str, limit: int) -> None:
    from . import logging as logx  # lazy: keep the module import-light

    logx.warn(
        "metric family exceeded its label-set budget; folding new series "
        "into {overflow=\"true\"}",
        metric=name, max_label_sets=limit,
    )


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash first, then
    double-quote and newline (exposition format spec)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def format_exemplar(ex: Optional[tuple[str, float, int]]) -> str:
    """OpenMetrics-style exemplar suffix for one bucket line (`` # {trace_id=
    "..."} value ts``); empty string when the bucket has none."""
    if not ex:
        return ""
    tid, value, ts_us = ex
    return (f' # {{trace_id="{_escape_label_value(tid)}"}} '
            f"{value} {ts_us / 1e6:.3f}")


def _fmt_le(bound: float) -> str:
    """Histogram ``le`` bound as a plain float literal (``repr()`` of an
    int-typed bucket rendered ``1`` vs ``1.0`` and float noise rendered as
    full 17-digit repr; conformance parsers want canonical float text)."""
    f = float(bound)
    if f == int(f):
        return f"{f:.1f}"  # 1.0, 2.0 — the canonical Prometheus spelling
    return f"{f:g}"


class Counter:
    def __init__(self, name: str, help_: str = "",
                 max_label_sets: int = 0) -> None:
        self.name = name
        self.help = help_
        self.max_label_sets = max_label_sets or DEFAULT_MAX_LABEL_SETS
        self._overflowed = False
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _guard_key(
        self, key: tuple[tuple[str, str], ...],
        existing: Mapping[tuple[tuple[str, str], ...], object],
    ) -> tuple[tuple[str, str], ...]:
        """Cardinality guard (call under ``_lock``): a NEW label set beyond
        the family budget folds into the ``{overflow="true"}`` series."""
        if key in existing or len(existing) < self.max_label_sets:
            return key
        if not self._overflowed:
            self._overflowed = True
            _log_overflow(self.name, self.max_label_sets)
        return _OVERFLOW_KEY

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            key = self._guard_key(key, self._values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum over every label combination (bench: round-trips per job)."""
        with self._lock:
            return sum(self._values.values())

    def _snapshot(self) -> list[tuple[tuple[tuple[str, str], ...], float]]:
        with self._lock:
            return sorted(self._values.items())

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        items = self._snapshot()
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        if not items:
            out.append(f"{self.name} 0")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            key = self._guard_key(key, self._values)
            self._values[key] = value

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, v in self._snapshot():
            out.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple[float, ...] = _DEFAULT_BUCKETS,
                 max_label_sets: int = 0) -> None:
        self.name = name
        self.help = help_
        self.buckets = buckets
        self.max_label_sets = max_label_sets or DEFAULT_MAX_LABEL_SETS
        self._overflowed = False
        self._counts: dict[tuple[tuple[str, str], ...], list[int]] = {}
        self._sums: dict[tuple[tuple[str, str], ...], float] = {}
        self._totals: dict[tuple[tuple[str, str], ...], int] = {}
        # per-series exemplars: bucket index (len(buckets) = +Inf) → the last
        # (trace_id, value, ts_us) observation that landed in that bucket
        self._exemplars: dict[
            tuple[tuple[str, str], ...], dict[int, tuple[str, float, int]]
        ] = {}
        self._lock = threading.Lock()

    def _guard_key(
        self, key: tuple[tuple[str, str], ...]
    ) -> tuple[tuple[str, str], ...]:
        if key in self._totals or len(self._totals) < self.max_label_sets:
            return key
        if not self._overflowed:
            self._overflowed = True
            _log_overflow(self.name, self.max_label_sets)
        return _OVERFLOW_KEY

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        if exemplar is None and _exemplars_enabled and _exemplar_provider is not None:
            try:
                exemplar = _exemplar_provider()[0]
            except Exception:  # noqa: BLE001 - exemplars must never fail the observe
                exemplar = ""
        with self._lock:
            key = self._guard_key(key)
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            idx = len(self.buckets)  # +Inf
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    if i < idx:
                        idx = i
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
            if exemplar and _exemplars_enabled:
                self._exemplars.setdefault(key, {})[idx] = (
                    str(exemplar), value, now_us()
                )

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Approximate quantile from bucket boundaries (observability only)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            total = self._totals.get(key, 0)
            if not total:
                return None
            counts = list(self._counts[key])
        target = q * total
        for i, c in enumerate(counts):
            if c >= target:
                return self.buckets[i]
        return self.buckets[-1]

    def _snapshot(self) -> list[tuple[tuple[tuple[str, str], ...], list[int], float, int]]:
        with self._lock:
            return [
                (key, list(self._counts[key]), self._sums[key], self._totals[key])
                for key in sorted(self._totals)
            ]

    def exemplar_snapshot(
        self,
    ) -> dict[tuple[tuple[str, str], ...], dict[int, tuple[str, float, int]]]:
        """Per-series exemplar map snapshot (bucket index → (trace_id,
        value, ts_us)) — the telemetry exporter ships it fleet-ward."""
        with self._lock:
            return {k: dict(v) for k, v in self._exemplars.items()}

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        snap = self._snapshot()
        exs = self.exemplar_snapshot()
        for key, counts, sum_, total in snap:
            labels = dict(key)
            series_ex = exs.get(key) or {}
            for i, b in enumerate(self.buckets):
                bl = dict(labels)
                bl["le"] = _fmt_le(b)
                out.append(
                    f"{self.name}_bucket{_fmt_labels(bl)} {counts[i]}"
                    + format_exemplar(series_ex.get(i))
                )
            bl = dict(labels)
            bl["le"] = "+Inf"
            out.append(
                f"{self.name}_bucket{_fmt_labels(bl)} {total}"
                + format_exemplar(series_ex.get(len(self.buckets)))
            )
            out.append(f"{self.name}_sum{_fmt_labels(labels)} {sum_}")
            out.append(f"{self.name}_count{_fmt_labels(labels)} {total}")
        return out


class Metrics:
    """Shared metric families for the whole control plane."""

    def __init__(self) -> None:
        self.jobs_received = Counter("cordum_jobs_received_total", "Jobs received by scheduler")
        self.jobs_dispatched = Counter("cordum_jobs_dispatched_total", "Jobs dispatched")
        self.jobs_completed = Counter("cordum_jobs_completed_total", "Jobs reaching terminal state")
        self.jobs_denied = Counter("cordum_jobs_safety_denied_total", "Jobs denied by safety kernel")
        self.jobs_dlq = Counter("cordum_jobs_dlq_total", "Jobs dead-lettered")
        self.http_requests = Counter("cordum_http_requests_total", "Gateway HTTP requests")
        self.http_latency = Histogram("cordum_http_request_seconds", "Gateway HTTP latency")
        self.dispatch_latency = Histogram(
            "cordum_dispatch_seconds", "submit->dispatch latency"
        )
        self.e2e_latency = Histogram("cordum_job_e2e_seconds", "submit->result latency")
        self.stage_seconds = Histogram(
            "cordum_stage_seconds",
            "Per-stage pipeline latency from flight-recorder spans",
        )
        self.spans_collected = Counter(
            "cordum_spans_collected_total", "Spans persisted by the collector"
        )
        self.policy_evals = Counter("cordum_policy_evals_total", "Safety kernel evaluations")
        self.workflow_steps = Counter("cordum_workflow_steps_total", "Workflow steps dispatched")
        # agentic workflow plane (docs/WORKFLOWS.md): run starts/terminals
        # (status=STARTED|SUCCEEDED|FAILED|CANCELLED), per-step wall-clock
        # latency (dispatch → terminal result, run trace as exemplar), live
        # non-terminal runs (set by the reconciler's status-index sweep),
        # and the reconciler pass cost itself
        self.workflow_runs = Counter(
            "cordum_workflow_runs_total", "Workflow runs started / finished by status"
        )
        self.workflow_step_seconds = Histogram(
            "cordum_workflow_step_seconds",
            "Workflow step latency: dispatch to terminal result",
            buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
        )
        self.workflow_active_runs = Gauge(
            "cordum_workflow_active_runs", "Runs in a non-terminal status"
        )
        self.workflow_reconcile_seconds = Histogram(
            "cordum_workflow_reconcile_seconds",
            "Workflow reconciler pass duration",
        )
        self.workers_live = Gauge("cordum_workers_live", "Live workers in registry")
        self.tpu_duty_cycle = Gauge("cordum_tpu_duty_cycle", "Reported TPU duty cycle per worker")
        # micro-batching (cordum_tpu/batching): rows-per-flush distribution,
        # live queued rows per (op, bucket), flush count
        self.batch_size = Histogram(
            "cordum_batch_size",
            "Rows per flushed micro-batch",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
        )
        self.batch_queue_depth = Gauge(
            "cordum_batch_queue_depth", "Rows waiting in micro-batch queues"
        )
        self.batch_flushes = Counter(
            "cordum_batch_flushes_total", "Micro-batch flushes executed"
        )
        # KV pipelining (infra/kv.py): every public KV op is one round trip
        # (one TCP request under StateBusKV, one lock acquisition under
        # MemoryKV); pipelined commits batch N mutations into one `pipe` op
        self.kv_roundtrips = Counter(
            "cordum_kv_roundtrips_total",
            "KV operations issued (each is one round-trip under StateBusKV)",
        )
        self.kv_pipeline_size = Histogram(
            "cordum_kv_pipeline_size",
            "Ops folded into each pipelined KV commit",
            buckets=(1.0, 2.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 48.0, 64.0),
        )
        self.statebus_op_seconds = Histogram(
            "cordum_statebus_op_seconds",
            "Server-side statebus per-op execution latency",
        )
        # control-plane sharding (ISSUE 5): per-shard ownership throughput,
        # cross-shard forwarding, submit backlog, and the per-connection
        # write-coalescing batch sizes on the statebus wire
        self.shard_scheduled = Counter(
            "cordum_shard_scheduled_total",
            "Jobs scheduled, labeled by owning scheduler shard",
        )
        self.shard_forwarded = Counter(
            "cordum_shard_forwarded_total",
            "Unstamped messages forwarded to the owning shard's partition subject",
        )
        self.shard_queue_depth = Gauge(
            "cordum_shard_partition_queue_depth",
            "Submits in flight (queued + processing) on this shard",
        )
        self.statebus_coalesced_batch = Histogram(
            "cordum_statebus_coalesced_batch",
            "Wire frames folded into one coalesced statebus socket write",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
        )
        # statebus replication + failover (infra/replication.py, ISSUE 8):
        # primary-side lag per replica, stream volume, attach modes, sync-ack
        # degradations, promotions; client-side reconnect/failover causes
        self.statebus_repl_lag_ops = Gauge(
            "cordum_statebus_replication_lag_ops",
            "Committed records the labeled replica has not acked yet",
        )
        self.statebus_repl_lag_bytes = Gauge(
            "cordum_statebus_replication_lag_bytes",
            "Replication stream bytes the labeled replica has not acked yet",
        )
        self.statebus_repl_records = Counter(
            "cordum_statebus_repl_records_total",
            "Record frames shipped to replicas",
        )
        self.statebus_repl_syncs = Counter(
            "cordum_statebus_repl_syncs_total",
            "Replica attach handshakes, by catch-up mode "
            "(incremental backlog replay vs full snapshot re-seed)",
        )
        self.statebus_sync_ack_timeouts = Counter(
            "cordum_statebus_sync_ack_timeouts_total",
            "Sync-mode commits that degraded to async because no replica "
            "acked within the sync timeout",
        )
        self.statebus_promotions = Counter(
            "cordum_statebus_promotions_total",
            "Replica promotions to primary, by trigger "
            "(admin | primary-dead | primary-goaway)",
        )
        self.statebus_reconnects = Counter(
            "cordum_statebus_reconnects_total",
            "Client reconnect/failover completions, by loss reason "
            "(connection_lost | goaway | ping_timeout)",
        )
        self.inflight_nudges = Counter(
            "cordum_sched_inflight_nudges_total",
            "DISPATCHED/RUNNING jobs re-delivered to their worker to "
            "recover dispatches/results lost to a statebus failover window",
        )
        # scheduler tick batching (ISSUE 6): submits drained per scheduler
        # loop tick into one selection pass + grouped pipelined commits
        self.sched_tick_batch = Histogram(
            "cordum_sched_tick_batch_size",
            "Submits coalesced into one scheduler tick batch",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
        )
        self.sched_tick_fallbacks = Counter(
            "cordum_sched_tick_fallback_total",
            "Batched submits diverted to the per-job slow path (conflict, "
            "duplicate-in-tick, or non-ALLOW decision)",
        )
        # serving subsystem (cordum_tpu/serving): continuous-batching decode
        self.serving_batch_occupancy = Histogram(
            "cordum_serving_batch_occupancy",
            "Sessions riding one continuous-batching decode step",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
        )
        self.serving_inter_token = Histogram(
            "cordum_serving_inter_token_seconds",
            "Wall time per decode step (inter-token latency)",
        )
        self.serving_admitted = Counter(
            "cordum_serving_sessions_admitted_total",
            "Sessions admitted into the decode loop",
        )
        self.serving_retired = Counter(
            "cordum_serving_sessions_retired_total",
            "Sessions retired from the decode loop, by reason",
        )
        self.serving_sessions = Gauge(
            "cordum_serving_active_sessions",
            "Sessions currently in the decode set",
        )
        self.serving_kv_pages_in_use = Gauge(
            "cordum_serving_kv_pages_in_use",
            "KV cache pages currently allocated to sessions",
        )
        self.serving_compiles = Counter(
            "cordum_serving_compile_total",
            "XLA programs compiled by the serving backend, by entry point "
            "(the ragged mixed prefill+decode entry compiles exactly once "
            "per process — a higher count is the bucket-recompile cliff "
            "coming back)",
        )
        self.session_affinity = Counter(
            "cordum_session_affinity_total",
            "Session-keyed routing outcomes (hit = routed to the worker "
            "holding the session's KV pages; evicted = the entry was "
            "invalidated because its worker deregistered, drained, or "
            "missed heartbeats)",
        )
        # serving session failover (docs/SERVING.md §Migration, drain, and
        # failover): live KV-page migration between workers + scheduler-side
        # session re-dispatch after worker death or a requeue request
        self.serving_migrations = Counter(
            "cordum_serving_migrations_total",
            "Live KV-page session migrations, by role (out = this worker "
            "shipped the session; in = adopted it) and outcome",
        )
        self.serving_migration_pause = Histogram(
            "cordum_serving_migration_pause_seconds",
            "Decode pause per migration (freeze -> target commit): only the "
            "final freeze-and-delta chunk stops the session's tokens",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5),
        )
        # prefill/decode disaggregation (docs/SERVING.md §Disaggregation):
        # why migrations fail, post-prefill hand-off outcomes, and the
        # decode rebalancer's command/move accounting
        self.serving_migration_failures = Counter(
            "cordum_serving_migration_failures_total",
            "Failed session migrations by reason (refused | timeout | io | "
            "session_gone | no_session | unknown)",
        )
        self.serving_handoffs = Counter(
            "cordum_serving_handoffs_total",
            "Post-prefill session hand-offs to a decode worker, by outcome "
            "(ok = moved on the first target; retried_ok = the jittered "
            "next-best retry landed it; no_peer = decode continued locally; "
            "failed = every target refused, decode continued locally)",
        )
        self.serving_rebalances = Counter(
            "cordum_serving_rebalance_total",
            "Decode-rebalancer activity by stage (commanded = the governor "
            "asked a hot worker to shed; moved = a session migrated toward "
            "headroom; failed = the move failed and decode continued on the "
            "hot worker; no_sessions = nothing movable, e.g. every "
            "candidate was cooldown-immune)",
        )
        # prefix cache + session tiering (docs/SERVING.md §Prefix cache and
        # tiering, ISSUE 18): shared-prefix admission outcomes, pages the
        # radix cache retains, CoW activity, and the hibernate/restore flow
        # that tiers idle resident sessions to the host-RAM cold arena
        self.serving_prefix = Counter(
            "cordum_serving_prefix_total",
            "Prefix-cache admission outcomes (hit = the session's prompt "
            "matched cached full pages and skipped their prefill; miss = "
            "admitted cold)",
        )
        self.serving_prefix_tokens = Counter(
            "cordum_serving_prefix_tokens_total",
            "Prompt tokens whose prefill was skipped via shared-prefix "
            "KV pages",
        )
        self.serving_prefix_pages = Gauge(
            "cordum_serving_prefix_cached_pages",
            "Physical arena pages currently retained (warm) by the prefix "
            "cache",
        )
        self.serving_prefix_evictions = Counter(
            "cordum_serving_prefix_evictions_total",
            "Cached-prefix pages dropped, by reason (capacity = LRU-evicted "
            "under admission exhaustion; stale = replaced by a fresher "
            "registration)",
        )
        self.serving_cow_copies = Counter(
            "cordum_serving_cow_copies_total",
            "Copy-on-write page duplications (a session wrote into a page "
            "another table still maps)",
        )
        self.serving_hibernate = Counter(
            "cordum_serving_hibernate_total",
            "Session-tiering transitions, by event (hibernated = pages "
            "exported to the cold arena and released; restored = pages "
            "re-imported on the next turn; dropped = cold state discarded)",
        )
        self.serving_hibernate_pause = Histogram(
            "cordum_serving_hibernate_pause_seconds",
            "Wall time a turn waits on a cold-arena restore (page alloc + "
            "scatter) before its prefill can start",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5),
        )
        self.serving_resident_sessions = Gauge(
            "cordum_serving_resident_sessions",
            "Conversations with restorable KV state on this worker, by tier "
            "(warm = pages resident in the device arena; cold = records in "
            "the host-RAM cold arena)",
        )
        # speculative decoding (docs/SERVING.md §Speculative decoding,
        # ISSUE 19): the self-drafted verify loop inside the ragged step —
        # tokens proposed, tokens the model verified, and rejected drafts
        # whose write positions were rolled back
        self.serving_spec_drafted = Counter(
            "cordum_serving_spec_drafted_total",
            "Speculative tokens proposed into draft verification rows",
        )
        self.serving_spec_accepted = Counter(
            "cordum_serving_spec_accepted_total",
            "Drafted tokens the ragged step verified and kept (the bonus "
            "token each verified row also samples is not counted here)",
        )
        self.serving_spec_rolled_back = Counter(
            "cordum_serving_spec_rolled_back_total",
            "Drafted tokens rejected by verification — their page write "
            "positions rolled back so the KV arena never serves them",
        )
        self.session_failovers = Counter(
            "cordum_sched_session_failovers_total",
            "In-flight jobs re-dispatched to a new worker, by reason "
            "(worker_dead | requeue_requested)",
        )
        # fleet telemetry plane (cordum_tpu/obs, ISSUE 9): retention-cap
        # drops made observable, per-class SLO measurement, exporter flow,
        # and the runtime profiler's loop/GC health
        self.spans_dropped = Counter(
            "cordum_spans_dropped_total",
            "Spans dropped by the collector's retention caps, by reason "
            "(per_trace_cap | trace_evicted | trace_purged)",
        )
        self.telemetry_snapshots = Counter(
            "cordum_telemetry_snapshots_total",
            "Telemetry snapshots published by this process's exporter",
        )
        self.telemetry_dropped = Counter(
            "cordum_telemetry_snapshots_dropped_total",
            "Telemetry snapshots lost, by reason (publish_error | "
            "decode_error | instance_evicted)",
        )
        self.jobs_by_class = Counter(
            "cordum_jobs_completed_by_class_total",
            "Terminal jobs by SLO job class (JobRequest.priority) and status",
        )
        # overload resilience (docs/ADMISSION.md): gateway load shedding,
        # per-(op, class) admission headroom, the brownout ladder tier, and
        # scheduler-side batch preemption under interactive SLO pressure
        self.gateway_shed = Counter(
            "cordum_gateway_shed_total",
            "Submissions rejected 429 by the gateway, by reason "
            "(rate_limit | tenant_quota | capacity | capacity_interactive | "
            "queue_depth | brownout_*) and job class",
        )
        self.admission_headroom = Gauge(
            "cordum_admission_headroom",
            "Measured capacity minus EWMA offered rate per (op, job_class) "
            "— negative means the class is being shed analytically",
        )
        self.admission_tier = Gauge(
            "cordum_admission_brownout_tier",
            "Admission brownout ladder tier (0 = normal, 1 = shed batch, "
            "2 = also shed best-effort ops, 3 = bounded-queue interactive)",
        )
        self.preemptions = Counter(
            "cordum_preemptions_total",
            "Batch-job preemptions under interactive SLO pressure, by stage "
            "(requested = governor asked a worker; requeued = the worker "
            "handed the job back; redispatched = the job was re-dispatched "
            "attempts-exempt after the hold-off)",
        )
        # gang scheduling (docs/GANG.md): mesh-aware all-or-nothing
        # placement of multi-chip SPMD/MPMD jobs
        self.gang_admissions = Counter(
            "cordum_gang_admissions_total",
            "Gang admission outcomes (reserved = all members reserved "
            "at once; queued = parked in the exhaustion FIFO)",
        )
        self.gang_completed = Counter(
            "cordum_gang_completed_total",
            "Gangs that finished, by status (succeeded | failed)",
        )
        self.gang_aborts = Counter(
            "cordum_gang_aborts_total",
            "Whole-gang aborts, by reason (member_failed | worker_dead | "
            "rendezvous_timeout | preempted | cancelled | ...)",
        )
        self.gang_partial_reservations = Counter(
            "cordum_gang_partial_reservations_total",
            "Ledger invariant violations: a gang observed holding fewer "
            "devices than its full reservation (MUST stay 0 — all-or-"
            "nothing admission is the design contract)",
        )
        self.gang_queue_depth = Gauge(
            "cordum_gang_queue_depth",
            "Gangs waiting in the exhaustion FIFO for devices to free",
        )
        self.gang_reserved_workers = Gauge(
            "cordum_gang_reserved_workers",
            "Workers currently reserved by running gangs",
        )
        self.gang_size = Histogram(
            "cordum_gang_size",
            "Members per dispatched gang",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
        )
        self.gang_rendezvous_seconds = Histogram(
            "cordum_gang_rendezvous_seconds",
            "Worker-side wait from member dispatch to barrier passage",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
        )
        # sharded serving gangs (docs/SERVING.md §Sharded serving): one
        # session set running tensor-parallel over a gang of workers
        self.serving_gang_steps = Counter(
            "cordum_serving_gang_steps_total",
            "Ragged steps on serving-gang members, by role (lead = rank "
            "0's sampled step + broadcast; replay = a follower replaying "
            "the broadcast batch against its head shard)",
        )
        self.serving_gang_members = Gauge(
            "cordum_serving_gang_members",
            "Members of the serving gang this worker currently belongs "
            "to (0 = not serving in a gang), labeled by gang id",
        )
        self.serving_gang_stream_tokens = Counter(
            "cordum_serving_gang_stream_tokens_total",
            "Tokens streamed to clients by serving-gang rank 0 — the ONLY "
            "rank that may publish stream packets (rank-0 ownership rule)",
        )
        self.slo_burn_rate = Gauge(
            "cordum_slo_burn_rate",
            "SLO error-budget burn rate per objective and window "
            "(1.0 = burning exactly the budget)",
        )
        self.eventloop_lag = Histogram(
            "cordum_eventloop_lag_seconds",
            "Event-loop scheduling lag sampled by the runtime profiler",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
        )
        self.slow_ticks = Counter(
            "cordum_slow_ticks_total",
            "Profiler ticks whose event-loop lag exceeded the slow-tick "
            "threshold (each dumps the running task stacks to the log)",
        )
        self.gc_pauses = Counter(
            "cordum_gc_pauses_total", "GC collections observed, by generation"
        )
        self.gc_pause_seconds = Histogram(
            "cordum_gc_pause_seconds",
            "Stop-the-world GC pause durations",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25),
        )
        self._families = [
            self.jobs_received,
            self.jobs_dispatched,
            self.jobs_completed,
            self.jobs_denied,
            self.jobs_dlq,
            self.http_requests,
            self.http_latency,
            self.dispatch_latency,
            self.e2e_latency,
            self.stage_seconds,
            self.spans_collected,
            self.policy_evals,
            self.workflow_steps,
            self.workflow_runs,
            self.workflow_step_seconds,
            self.workflow_active_runs,
            self.workflow_reconcile_seconds,
            self.workers_live,
            self.tpu_duty_cycle,
            self.batch_size,
            self.batch_queue_depth,
            self.batch_flushes,
            self.kv_roundtrips,
            self.kv_pipeline_size,
            self.statebus_op_seconds,
            self.shard_scheduled,
            self.shard_forwarded,
            self.shard_queue_depth,
            self.statebus_coalesced_batch,
            self.statebus_repl_lag_ops,
            self.statebus_repl_lag_bytes,
            self.statebus_repl_records,
            self.statebus_repl_syncs,
            self.statebus_sync_ack_timeouts,
            self.statebus_promotions,
            self.statebus_reconnects,
            self.inflight_nudges,
            self.sched_tick_batch,
            self.sched_tick_fallbacks,
            self.serving_batch_occupancy,
            self.serving_inter_token,
            self.serving_admitted,
            self.serving_retired,
            self.serving_sessions,
            self.serving_kv_pages_in_use,
            self.serving_compiles,
            self.session_affinity,
            self.serving_migrations,
            self.serving_migration_pause,
            self.serving_prefix,
            self.serving_prefix_tokens,
            self.serving_prefix_pages,
            self.serving_prefix_evictions,
            self.serving_cow_copies,
            self.serving_hibernate,
            self.serving_hibernate_pause,
            self.serving_resident_sessions,
            self.serving_spec_drafted,
            self.serving_spec_accepted,
            self.serving_spec_rolled_back,
            self.session_failovers,
            self.spans_dropped,
            self.telemetry_snapshots,
            self.telemetry_dropped,
            self.jobs_by_class,
            self.gateway_shed,
            self.admission_headroom,
            self.admission_tier,
            self.preemptions,
            self.gang_admissions,
            self.gang_completed,
            self.gang_aborts,
            self.gang_partial_reservations,
            self.gang_queue_depth,
            self.gang_reserved_workers,
            self.gang_size,
            self.gang_rendezvous_seconds,
            self.slo_burn_rate,
            self.eventloop_lag,
            self.slow_ticks,
            self.gc_pauses,
            self.gc_pause_seconds,
        ]

    def render(self) -> str:
        lines: list[str] = []
        for fam in self._families:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """The whole registry in the compact fleet-telemetry snapshot format
        (msgpack-friendly plain lists/dicts; docs/OBSERVABILITY.md §Fleet
        telemetry)::

            {"counters":   {name: [[{label: value}, value], ...]},
             "gauges":     {name: [[{label: value}, value], ...]},
             "histograms": {name: {"buckets": [...],
                                   "series": [[{..}, [counts], sum, total]]}}}

        Gauges are separated from counters because they merge differently
        across the fleet (counters sum; gauges keep their instance).
        """
        counters: dict[str, list] = {}
        gauges: dict[str, list] = {}
        hists: dict[str, dict] = {}
        for fam in self._families:
            if isinstance(fam, Histogram):
                hists[fam.name] = {
                    "buckets": list(fam.buckets),
                    "series": [
                        [dict(key), counts, sum_, total]
                        for key, counts, sum_, total in fam._snapshot()
                    ],
                }
                exs = fam.exemplar_snapshot()
                if exs:
                    # str bucket indices: msgpack/JSON-safe either way
                    hists[fam.name]["exemplars"] = [
                        [dict(key), {str(i): list(ex) for i, ex in m.items()}]
                        for key, m in exs.items()
                    ]
            elif isinstance(fam, Gauge):
                gauges[fam.name] = [[dict(k), v] for k, v in fam._snapshot()]
            else:
                counters[fam.name] = [[dict(k), v] for k, v in fam._snapshot()]
        return {"counters": counters, "gauges": gauges, "histograms": hists}
