"""Worker registry: in-memory heartbeat map with TTL expiry
(reference ``core/controlplane/scheduler/registry_memory.go:11-113``).

TPU delta: workers carry slice telemetry (chip_count, topology, duty cycle,
HBM, device health) used by the slice-aware strategy.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..protocol.types import Heartbeat

DEFAULT_TTL_S = 30.0


@dataclass
class WorkerInfo:
    heartbeat: Heartbeat
    last_seen: float = field(default_factory=time.monotonic)


class WorkerRegistry:
    def __init__(self, ttl_s: float = DEFAULT_TTL_S) -> None:
        self.ttl_s = ttl_s
        self._workers: dict[str, WorkerInfo] = {}
        self.version = 0  # bumped on every mutation (packed-scan cache key)

    def update(self, hb: Heartbeat) -> None:
        if hb.worker_id:
            self._workers[hb.worker_id] = WorkerInfo(hb, time.monotonic())
            self.version += 1

    def remove(self, worker_id: str) -> None:
        if self._workers.pop(worker_id, None) is not None:
            self.version += 1

    def expire(self) -> list[str]:
        """Drop workers whose heartbeat is older than TTL; returns dropped ids."""
        cutoff = time.monotonic() - self.ttl_s
        dead = [wid for wid, info in self._workers.items() if info.last_seen < cutoff]
        for wid in dead:
            del self._workers[wid]
        if dead:
            self.version += 1
        return dead

    def get(self, worker_id: str) -> Optional[Heartbeat]:
        info = self._workers.get(worker_id)
        if info is None or info.last_seen < time.monotonic() - self.ttl_s:
            return None
        return info.heartbeat

    def snapshot(self) -> dict[str, Heartbeat]:
        """Live worker map (TTL applied, dict copied — safe for strategy scans)."""
        cutoff = time.monotonic() - self.ttl_s
        return {
            wid: info.heartbeat
            for wid, info in self._workers.items()
            if info.last_seen >= cutoff
        }

    def snapshot_json(self) -> dict:
        return {
            "workers": {wid: hb.to_dict() for wid, hb in self.snapshot().items()},
            "count": len(self._workers),
        }
