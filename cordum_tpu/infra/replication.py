"""Statebus primary/replica replication (docs/PROTOCOL.md §Replication).

The partitioned statebus made each partition the single point of durability
(one process, one AOF).  This module removes that: a **primary** ships every
committed AOF record — the PIPE frame is the atomic unit — to attached
**replicas** over the existing frame protocol, replicas apply + ack them,
and on primary failure a replica is promoted (admin ``promote`` frame or
automatic takeover on heartbeat timeout) while clients walk the partition's
replica set and fail over.

Replication stream model (Redis-style replication id ≈ ``epoch``):

* ``offset`` — count of committed data records since genesis.  The primary
  numbers every record; replicas adopt the primary's numbering, so equal
  (epoch, offset) ⇒ byte-identical state (versions included — snapshots
  preserve per-key versions so failed-over clients keep valid watches).
* ``epoch`` — bumped on every promotion and persisted in the AOF (a
  ``repl_meta`` record).  A rejoining server whose epoch differs from the
  current primary's has a potentially divergent history and is re-seeded
  with a full snapshot; same-epoch replicas catch up incrementally from
  the primary's record backlog.
* **ack modes** — async by default (commit acks the client immediately;
  loss on primary death is bounded to the unacked replication window);
  ``sync_replication`` makes every commit wait for one replica ack before
  the client sees ``ok``, so an acked commit can never be lost to a single
  node failure.  A replica that stops acking degrades sync→async after
  ``sync_timeout_s`` (counted) rather than holding the partition hostage.

Promotion is exclusive: promotion bumps the epoch, and a returning old
primary probes its peer set at startup — finding a live primary with a
higher epoch, it demotes itself to replica (its unreplicated tail, if any,
is discarded by the snapshot re-seed: exactly the async-mode loss window).

Wire additions (all ride the existing ``[len][msgpack]`` framing):

==================================  =======================================
``[rid,"repl_sync",id,epoch,off]``  replica handshake → ``["incremental",
                                    epoch, offset]`` or ``["snapshot",
                                    epoch, offset]`` (snapshot pushed next)
``[0,"repl",offset,record]``        one committed record (primary→replica)
``[0,"repl_snap",epoch,off,blob]``  full state snapshot (primary→replica)
``[0,"repl_hb",epoch,offset]``      primary liveness + lag beacon
``[0,"repl_ack",offset]``           replica applied-through ack
``[rid,"promote"]``                 admin promotion (replica → primary)
``[rid,"role"]``                    role/offset/epoch/lag status
``[0,"goaway"]``                    graceful shutdown: fail over NOW
==================================  =======================================
"""
from __future__ import annotations

import asyncio
import collections
import time
from typing import Any, Optional, TYPE_CHECKING

import msgpack

from . import logging as logx
from .frames import FrameWriter, encode_frame, read_frame

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (statebus imports us)
    from .statebus import StateBusServer

#: committed records the primary retains for incremental replica catch-up;
#: a replica further behind than this is re-seeded with a full snapshot
DEFAULT_BACKLOG = 4096

#: how long a sync-mode commit waits for a replica ack before degrading to
#: async for that commit (counted via cordum_statebus_sync_ack_timeouts_total)
SYNC_ACK_TIMEOUT_S = 5.0


def pack_record(op: str, args: tuple) -> bytes:
    """One AOF/replication record: the same msgpack entry the AOF stores."""
    return msgpack.packb([op, *args], use_bin_type=True)


def unpack_record(rec: bytes) -> list:
    return msgpack.unpackb(rec, raw=False, strict_map_key=False)


class _ReplicaSession:
    """Primary-side state for one attached replica connection."""

    __slots__ = ("replica_id", "fw", "acked_offset", "sent_offset",
                 "sent_bytes", "acked_bytes", "lag_published_at")

    def __init__(self, replica_id: str, fw: FrameWriter) -> None:
        self.replica_id = replica_id
        self.fw = fw
        self.acked_offset = 0
        self.sent_offset = 0
        self.sent_bytes = 0
        self.acked_bytes = 0
        self.lag_published_at = 0.0


class ReplicationState:
    """Primary-side replication bookkeeping, owned by a StateBusServer.

    Always active (even with zero replicas): ``offset`` numbers every
    committed record and the backlog retains the recent tail, so a replica
    may attach at any time and catch up incrementally.
    """

    def __init__(self, server: "StateBusServer", *, backlog: int = DEFAULT_BACKLOG,
                 sync_timeout_s: float = SYNC_ACK_TIMEOUT_S) -> None:
        self.server = server
        self.epoch = 0
        self.offset = 0
        self.bytes_total = 0
        self.sync_timeout_s = sync_timeout_s
        # (offset, record_bytes, cumulative_bytes) ring of the recent tail
        self.backlog: collections.deque[tuple[int, bytes, int]] = (
            collections.deque(maxlen=backlog))
        self.sessions: dict[Any, _ReplicaSession] = {}  # writer → session
        self._waiters: list[tuple[int, asyncio.Future]] = []

    # -- primary commit path -------------------------------------------
    @property
    def replica_count(self) -> int:
        return len(self.sessions)

    def advance(self, rec: bytes) -> int:
        """Number a freshly committed record and fan it out to replicas.

        Called synchronously right after the engine applied the mutation
        (no awaits in between — offset order IS commit order)."""
        self.offset += 1
        self.bytes_total += len(rec)
        self.backlog.append((self.offset, rec, self.bytes_total))
        if self.sessions:
            frame = encode_frame([0, "repl", self.offset, rec])
            for w, sess in list(self.sessions.items()):
                try:
                    sess.fw.send(frame)
                    sess.sent_offset = self.offset
                    sess.sent_bytes = self.bytes_total
                except ConnectionError:
                    self.detach(w)
            m = self.server.metrics
            m.statebus_repl_records.inc(amount=float(len(self.sessions) or 1))
        return self.offset

    def covers(self, offset: int) -> bool:
        """Can a replica at ``offset`` catch up from the backlog alone?"""
        return offset >= self.offset - len(self.backlog)

    def records_after(self, offset: int) -> list[bytes]:
        return [encode_frame([0, "repl", off, rec])
                for off, rec, _ in self.backlog if off > offset]

    # -- replica sessions ----------------------------------------------
    def attach(self, writer: Any, replica_id: str, fw: FrameWriter,
               start_offset: int) -> _ReplicaSession:
        sess = _ReplicaSession(replica_id or f"replica-{id(writer):x}", fw)
        sess.acked_offset = start_offset
        self.sessions[writer] = sess
        self._update_lag(sess)
        return sess

    def detach(self, writer: Any) -> None:
        sess = self.sessions.pop(writer, None)
        if sess is not None:
            logx.warn("replica detached", replica=sess.replica_id,
                      acked=sess.acked_offset, primary_offset=self.offset)

    def on_ack(self, writer: Any, offset: int) -> None:
        sess = self.sessions.get(writer)
        if sess is None:
            return
        sess.acked_offset = max(sess.acked_offset, int(offset))
        # cumulative bytes at the acked offset: backlog offsets are dense
        # and sequential, so the entry is at a computable index — O(1)-ish
        # deque access near the tail, never a scan (acks arrive once per
        # record on the hot path; an ack older than the backlog pins
        # lag_bytes at the full sent window)
        if self.backlog:
            first = self.offset - len(self.backlog) + 1
            idx = sess.acked_offset - first
            if 0 <= idx < len(self.backlog):
                sess.acked_bytes = self.backlog[idx][2]
        self._update_lag(sess)
        if self._waiters:
            still = []
            for target, fut in self._waiters:
                if sess.acked_offset >= target:
                    if not fut.done():
                        fut.set_result(True)
                else:
                    still.append((target, fut))
            self._waiters = still

    def _update_lag(self, sess: _ReplicaSession) -> None:
        # throttled: acks arrive once per committed record, and labeled
        # gauge sets are not free — lag is an observability surface, so a
        # 50ms-stale reading is fine (caught-up sessions always publish,
        # keeping the gauge exact at zero lag)
        now = time.monotonic()
        if sess.acked_offset < self.offset and now - sess.lag_published_at < 0.05:
            return
        sess.lag_published_at = now
        m = self.server.metrics
        m.statebus_repl_lag_ops.set(
            float(self.offset - sess.acked_offset), replica=sess.replica_id)
        m.statebus_repl_lag_bytes.set(
            float(max(0, self.bytes_total - (sess.acked_bytes or 0))),
            replica=sess.replica_id)

    def min_acked(self) -> int:
        if not self.sessions:
            return self.offset
        return min(s.acked_offset for s in self.sessions.values())

    # -- sync-ack mode --------------------------------------------------
    async def wait_synced(self, offset: int) -> bool:
        """Block a sync-mode commit until ONE replica acked ``offset``.

        Degrades (returns False, counted) after ``sync_timeout_s`` so a
        dead replica cannot make the partition unavailable for writes."""
        if not self.sessions:
            return False
        for sess in self.sessions.values():
            if sess.acked_offset >= offset:
                return True
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append((offset, fut))
        try:
            await asyncio.wait_for(fut, self.sync_timeout_s)
            return True
        except asyncio.TimeoutError:
            self.server.metrics.statebus_sync_ack_timeouts.inc()
            logx.warn("sync replication ack timed out; commit proceeds async",
                      offset=offset, replicas=len(self.sessions))
            return False

    def fail_waiters(self) -> None:
        for _, fut in self._waiters:
            if not fut.done():
                fut.set_result(False)
        self._waiters = []

    def status(self) -> dict:
        return {
            "epoch": self.epoch,
            "offset": self.offset,
            "replicas": [
                {"id": s.replica_id, "acked_offset": s.acked_offset,
                 "lag_ops": self.offset - s.acked_offset}
                for s in self.sessions.values()
            ],
        }


class ReplicaLink:
    """Replica-side pump: dial the primary, hand-shake at our (epoch,
    offset), apply the record stream, ack, and watch for primary death.

    Primary-dead detection: no frame (record, heartbeat or snapshot) inside
    ``heartbeat_timeout_s`` — including time spent failing to reconnect —
    promotes this server when ``auto_promote`` is set; a GOAWAY from a
    gracefully stopping primary promotes immediately.
    """

    def __init__(self, server: "StateBusServer", host: str, port: int, *,
                 replica_id: str = "", auto_promote: bool = True,
                 heartbeat_timeout_s: float = 3.0) -> None:
        self.server = server
        self.host = host
        self.port = port
        self.replica_id = replica_id or f"{server.host}:{server.port}"
        self.auto_promote = auto_promote
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.connected = asyncio.Event()
        self.primary_offset = 0
        self.last_sync_mode = ""  # "incremental" | "snapshot" (tests/status)
        self._last_seen = time.monotonic()
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._stop.clear()
        self._last_seen = time.monotonic()
        self._task = asyncio.ensure_future(self._run())

    # cordum: single-flight -- sole caller is the owning runner's shutdown path; the cancel/await/None teardown is idempotent
    async def stop(self) -> None:
        self._stop.set()
        task, self._task = self._task, None
        if task is None or task is asyncio.current_task():
            return
        # Cancel-until-dead: on 3.10 a cancel landing exactly as wait_for's
        # inner read completes is swallowed (bpo-42130) and the pump keeps
        # running — possibly into server.promote(), which needs the very
        # _role_lock our caller holds while joining us.  Re-cancel until the
        # task actually finishes so the join below cannot deadlock.
        while not task.done():
            task.cancel()
            await asyncio.wait([task], timeout=0.1)
        await logx.join_task(task, name="replica-link")

    # -- internals ------------------------------------------------------
    def _dead_for(self) -> float:
        return time.monotonic() - self._last_seen

    async def _maybe_promote(self, reason: str) -> bool:
        if not self.auto_promote:
            return False
        await self.server.promote(reason=reason)
        return True

    async def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set() and self.server.role == "replica":
            writer = None
            try:
                reader, writer = await asyncio.open_connection(self.host, self.port)
                fw = FrameWriter(writer)
                try:
                    await self._pump(reader, fw)
                finally:
                    await fw.close()
            except asyncio.CancelledError:
                raise
            except (OSError, ConnectionError):
                pass
            except Exception:
                logx.error("replica link failed; retrying")
            finally:
                self.connected.clear()
                if writer is not None:
                    writer.close()
            if self._stop.is_set() or self.server.role != "replica":
                return
            if self._dead_for() > self.heartbeat_timeout_s:
                if await self._maybe_promote("primary-dead"):
                    return
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 1.0)

    async def _pump(self, reader: asyncio.StreamReader, fw: FrameWriter) -> None:
        repl = self.server.repl
        fw.send(encode_frame([1, "repl_sync", self.replica_id,
                              repl.epoch, repl.offset]))
        # the handshake reply precedes any stream push on this connection
        frame = await asyncio.wait_for(read_frame(reader),
                                       max(self.heartbeat_timeout_s, 5.0))
        if frame is None:
            raise ConnectionError("primary hung up during handshake")
        if frame[0] == 1 and frame[1] == "err":
            # peer is not (yet) a primary — back off and retry; promotion
            # or peer recovery will flip it
            raise ConnectionError(f"repl_sync rejected: {frame[2]}")
        mode, p_epoch, p_offset = frame[2]
        self.last_sync_mode = mode
        self.primary_offset = int(p_offset)
        if mode == "incremental":
            # same history: adopt the primary's epoch (first sync only)
            await self.server.adopt_epoch(int(p_epoch))
        self._last_seen = time.monotonic()
        self.connected.set()
        logx.info("replica link established", primary=f"{self.host}:{self.port}",
                  mode=mode, offset=repl.offset, primary_offset=p_offset)
        while not self._stop.is_set():
            try:
                frame = await asyncio.wait_for(read_frame(reader), 0.25)
            except asyncio.TimeoutError:
                if self._dead_for() > self.heartbeat_timeout_s:
                    if await self._maybe_promote("primary-dead"):
                        return
                    raise ConnectionError("primary heartbeat timeout")
                continue
            if frame is None:
                raise ConnectionError("primary connection lost")
            self._last_seen = time.monotonic()
            kind = frame[1] if len(frame) > 1 else ""
            if frame[0] == 0 and kind == "repl":
                _, _, offset, rec = frame
                await self.server.apply_replicated(rec, int(offset))
                fw.send(encode_frame([0, "repl_ack", self.server.repl.offset]))
            elif frame[0] == 0 and kind == "repl_snap":
                _, _, epoch, offset, blob = frame
                await self.server.load_replicated_snapshot(
                    int(epoch), int(offset), blob)
                fw.send(encode_frame([0, "repl_ack", self.server.repl.offset]))
            elif frame[0] == 0 and kind == "repl_hb":
                self.primary_offset = int(frame[3])
            elif frame[0] == 0 and kind == "goaway":
                # graceful primary shutdown: promote NOW instead of waiting
                # out the heartbeat timeout
                if await self._maybe_promote("primary-goaway"):
                    return
                raise ConnectionError("primary sent goaway")
            # replies to stray requests and unknown pushes are ignored


async def admin_call(host: str, port: int, op: str, *args: Any,
                     timeout_s: float = 1.0) -> Optional[Any]:
    """One-shot request against a statebus endpoint on a fresh connection.

    Returns the ``ok`` result or None when the endpoint is unreachable,
    unresponsive, or answered ``err`` — used by startup peer probing
    (split-brain demotion) and ``cordumctl statebus status|promote``.
    """
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout_s)
    except (OSError, asyncio.TimeoutError):
        return None
    try:
        writer.write(encode_frame([1, op, *args]))
        await asyncio.wait_for(writer.drain(), timeout_s)
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            frame = await asyncio.wait_for(read_frame(reader), remaining)
            if frame is None:
                return None
            if frame[0] == 1:
                return frame[2] if frame[1] == "ok" else None
    except (OSError, ConnectionError, asyncio.TimeoutError):
        return None
    finally:
        writer.close()


async def probe_role(host: str, port: int, *, timeout_s: float = 1.0) -> Optional[dict]:
    """One-shot ``role`` query ({role, epoch, offset, ...}) or None."""
    doc = await admin_call(host, port, "role", timeout_s=timeout_s)
    return doc if isinstance(doc, dict) else None


def parse_endpoint(url: str) -> tuple[str, int]:
    """``statebus://host:port`` (scheme optional) → ``(host, port)``."""
    hostport = url.split("://", 1)[-1]
    host, _, port = hostport.partition(":")
    return host or "127.0.0.1", int(port or 7420)


def parse_replica_set(url: str) -> list[tuple[str, int]]:
    """One partition's ``|``-separated replica set → endpoint list.

    ``statebus://h:7420|statebus://h:7520`` lists the primary first; clients
    walk the list on connection loss until they find the current primary.
    """
    return [parse_endpoint(u.strip()) for u in url.split("|") if u.strip()]
