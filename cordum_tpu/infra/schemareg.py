"""JSON Schema registry (reference ``core/infra/schema/registry.go`` —
schemas in the KV under ``schema:<id>`` with a capped index; validation via
jsonschema)."""
from __future__ import annotations

import json
from typing import Any, Optional

import jsonschema

from .kv import KV

MAX_SCHEMAS = 500
INDEX_KEY = "schema:index"


class SchemaError(Exception):
    pass


class SchemaRegistry:
    def __init__(self, kv: KV) -> None:
        self.kv = kv

    async def put(self, schema_id: str, schema: dict[str, Any]) -> None:
        jsonschema.Draft202012Validator.check_schema(schema)
        existing = await self.kv.zcard(INDEX_KEY)
        known = await self.kv.get(f"schema:{schema_id}")
        if known is None and existing >= MAX_SCHEMAS:
            raise SchemaError(f"schema registry full ({MAX_SCHEMAS})")
        await self.kv.set(f"schema:{schema_id}", json.dumps(schema).encode())
        from ..utils.ids import now_us

        await self.kv.zadd(INDEX_KEY, schema_id, float(now_us()))

    async def get(self, schema_id: str) -> Optional[dict[str, Any]]:
        b = await self.kv.get(f"schema:{schema_id}")
        return json.loads(b) if b else None

    async def delete(self, schema_id: str) -> bool:
        n = await self.kv.delete(f"schema:{schema_id}")
        await self.kv.zrem(INDEX_KEY, schema_id)
        return n > 0

    async def list(self) -> list[str]:
        return await self.kv.zrange(INDEX_KEY)

    async def validate_id(self, schema_id: str, value: Any) -> list[str]:
        """Validate value against a registered schema; [] = valid."""
        schema = await self.get(schema_id)
        if schema is None:
            raise SchemaError(f"unknown schema {schema_id!r}")
        return self.validate_map(schema, value)

    @staticmethod
    def validate_map(schema: dict[str, Any], value: Any) -> list[str]:
        v = jsonschema.Draft202012Validator(schema)
        return [e.message for e in v.iter_errors(value)]
