"""``secret://`` reference detection + recursive redaction
(reference ``core/infra/secrets/secrets.go:8-36``; feeds the
``secrets_present`` label consumed by the safety kernel)."""
from __future__ import annotations

from typing import Any

SECRET_PREFIX = "secret://"
REDACTED = "[redacted:secret-ref]"


def contains_secret_refs(value: Any) -> bool:
    if isinstance(value, str):
        return SECRET_PREFIX in value
    if isinstance(value, dict):
        return any(contains_secret_refs(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(contains_secret_refs(v) for v in value)
    return False


def redact_secret_refs(value: Any) -> Any:
    if isinstance(value, str):
        return REDACTED if SECRET_PREFIX in value else value
    if isinstance(value, dict):
        return {k: redact_secret_refs(v) for k, v in value.items()}
    if isinstance(value, list):
        return [redact_secret_refs(v) for v in value]
    return value
