"""Statebus: the framework's standalone state + message-bus server.

The reference control plane outsources state to Redis and messaging to NATS
(SURVEY §2.2).  This environment has neither client library — and a
TPU-native deployment wants one less moving part anyway — so the framework
ships its own: a single asyncio TCP server speaking a msgpack-framed
protocol that provides BOTH

  * the full :class:`~cordum_tpu.infra.kv.KV` surface (strings, hashes,
    z-sets, lists, sets, TTLs, versioned optimistic ``commit``) backed by
    the in-process :class:`MemoryKV` engine, with optional append-only-file
    persistence (every mutating op logged; replayed on restart — the
    "crash-safe state" guarantee), and
  * pub/sub with NATS-style wildcard subjects and queue groups
    (:class:`StateBusBus` delivers into local handlers with the same
    RetryAfter redelivery semantics as the loopback bus).

Wire format: ``[4-byte BE length][msgpack array]``.
Requests:  ``[req_id, op, *args]`` → ``[req_id, "ok"|"err", result]``.
Server pushes: ``[0, "msg", sid, subject, packet_bytes]``.
"""
from __future__ import annotations

import asyncio
import itertools
import os
import struct
import time
from typing import Any, Optional

import msgpack

from ..protocol.types import BusPacket
from ..utils.globmatch import subject_match
from . import logging as logx
from .bus import (
    Bus,
    DEDUP_WINDOW_S,
    MAX_NAK_DELAY_S,
    MAX_REDELIVERIES,
    RetryAfter,
    Subscription,
    compute_msg_id,
)
from .kv import KV, MemoryKV
from .metrics import Metrics

_LEN = struct.Struct(">I")


def _read_bytes(path: str) -> bytes:
    """Sync AOF read; callers run it via asyncio.to_thread (CL003)."""
    with open(path, "rb") as f:  # cordumlint: disable=CL003 -- runs via asyncio.to_thread
        return f.read()

# KV ops forwarded verbatim to the MemoryKV engine (name → is_mutation)
_KV_OPS = {
    "get": False, "set": True, "setnx": True, "delete": True, "del_eq": True,
    "expire": True,
    "keys": False, "hset": True, "hget": False, "hgetall": False, "hdel": True,
    "hincrby": True, "zadd": True, "zrem": True, "zrange": False,
    "zrangebyscore": False, "zcard": False, "zscore": False, "rpush": True,
    "lrange": False, "ltrim": True, "llen": False, "sadd": True,
    "smembers": False, "version": False, "watch_read": False, "commit": True,
    "ping": False,
}


def _encode(obj: Any) -> bytes:
    b = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(b)) + b


async def _read_frame(reader: asyncio.StreamReader) -> Optional[list]:
    try:
        head = await reader.readexactly(4)
        (n,) = _LEN.unpack(head)
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


def _plain(v: Any) -> Any:
    """msgpack-safe: sets → sorted lists."""
    if isinstance(v, set):
        return sorted(v)
    return v


class StateBusServer:
    """The server process: KV engine + subscription routing + AOF."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7420, *, aof_path: str = "") -> None:
        self.host = host
        self.port = port
        self.kv = MemoryKV()
        self.aof_path = aof_path
        self._aof = None
        self._last_fsync = 0.0
        self._server: Optional[asyncio.base_events.Server] = None
        # sid → (writer, pattern, queue)
        self._subs: dict[int, tuple[asyncio.StreamWriter, str, Optional[str]]] = {}
        self._sid = itertools.count(1)
        self._rr: dict[tuple[str, str], int] = {}
        self._dedup: dict[str, float] = {}
        self._writers: set[asyncio.StreamWriter] = set()
        self._write_locks: dict[asyncio.StreamWriter, asyncio.Lock] = {}
        # server-side observability: per-op execution latency + pipeline
        # sizes; rendered via the `metrics` wire op (cordum_statebus_op_seconds)
        self.metrics = Metrics()

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        if self.aof_path:
            await self._replay_aof()
            self._aof = await asyncio.to_thread(open, self.aof_path, "ab")
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logx.info("statebus listening", host=self.host, port=self.port, aof=self.aof_path or "off")

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        # Close client writers BEFORE wait_closed: Python 3.12's
        # Server.wait_closed() waits for connection handlers to finish, and
        # handlers block reading from clients that never hang up.
        for w in list(self._writers):
            w.close()
        if self._server:
            await self._server.wait_closed()
            self._server = None
        if self._aof:
            self._aof.flush()
            self._aof.close()
            self._aof = None

    async def _replay_aof(self) -> None:
        if not os.path.exists(self.aof_path):
            return
        n = 0
        raw = await asyncio.to_thread(_read_bytes, self.aof_path)
        unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
        unpacker.feed(raw)
        for entry in unpacker:
            op, args = entry[0], entry[1:]
            try:
                await getattr(self.kv, op)(*args)
                n += 1
            except Exception:
                logx.warn("aof replay skipped bad entry", op=op)
        logx.info("aof replayed", entries=n)

    def _log_aof(self, op: str, args: tuple) -> None:
        if self._aof is not None:
            self._aof.write(msgpack.packb([op, *args], use_bin_type=True))
            # flush before the op is acked: process-crash durability (an
            # fsync interval below bounds power-loss exposure)
            self._aof.flush()
            now = time.monotonic()
            if now - self._last_fsync > 0.2:
                os.fsync(self._aof.fileno())
                self._last_fsync = now

    # -- connection handling -------------------------------------------
    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        self._write_locks[writer] = asyncio.Lock()
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                asyncio.ensure_future(self._dispatch(frame, writer))
        finally:
            self._writers.discard(writer)
            self._write_locks.pop(writer, None)
            dead = [sid for sid, (w, _, _) in self._subs.items() if w is writer]
            for sid in dead:
                del self._subs[sid]
            writer.close()

    async def _send(self, writer: asyncio.StreamWriter, obj: list) -> None:
        lock = self._write_locks.get(writer)
        if lock is None:
            return
        async with lock:
            writer.write(_encode(obj))
            await writer.drain()

    async def _dispatch(self, frame: list, writer: asyncio.StreamWriter) -> None:
        req_id, op, *args = frame
        try:
            if op in _KV_OPS:
                t0 = time.perf_counter()
                result = await getattr(self.kv, op)(*args)
                if _KV_OPS[op]:
                    self._log_aof(op, tuple(args))
                self.metrics.statebus_op_seconds.observe(
                    time.perf_counter() - t0, op=op
                )
                await self._send(writer, [req_id, "ok", _plain(result)])
            elif op == "pipe":
                # one wire frame = one atomic multi-op batch (the whole point
                # of the pipeline layer: N mutations, ONE round trip)
                watches, ops = args
                t0 = time.perf_counter()
                ok, versions = await self.kv.pipe_execute(watches, ops)
                self._log_aof("pipe_execute", (watches, ops))
                self.metrics.statebus_op_seconds.observe(
                    time.perf_counter() - t0, op="pipe"
                )
                self.metrics.kv_pipeline_size.observe(float(len(ops)))
                await self._send(writer, [req_id, "ok", [ok, versions]])
            elif op == "metrics":
                await self._send(writer, [req_id, "ok", self.metrics.render()])
            elif op == "sub":
                pattern, queue = args
                sid = next(self._sid)
                self._subs[sid] = (writer, pattern, queue or None)
                await self._send(writer, [req_id, "ok", sid])
            elif op == "unsub":
                self._subs.pop(args[0], None)
                await self._send(writer, [req_id, "ok", True])
            elif op == "pub":
                subject, packet_bytes = args
                await self._route(subject, packet_bytes)
                await self._send(writer, [req_id, "ok", True])
            else:
                await self._send(writer, [req_id, "err", f"unknown op {op!r}"])
        except Exception as e:  # noqa: BLE001
            try:
                await self._send(writer, [req_id, "err", str(e)])
            except Exception as send_err:  # noqa: BLE001 - peer already gone
                logx.debug("could not deliver error reply", err=str(send_err))

    async def _route(self, subject: str, packet_bytes: bytes) -> None:
        from ..protocol import subjects as subj

        if subj.is_durable_subject(subject):
            try:
                pkt = BusPacket.from_wire(packet_bytes)
                mid = compute_msg_id(subject, pkt)
            except Exception:
                mid = ""
            if mid:
                now = time.monotonic()
                if len(self._dedup) > 16384:
                    for k in list(itertools.islice(self._dedup, 8192)):
                        del self._dedup[k]
                seen = self._dedup.get(mid)
                if seen is not None and now - seen < DEDUP_WINDOW_S:
                    return
                self._dedup[mid] = now
        plain: list[tuple[int, asyncio.StreamWriter]] = []
        groups: dict[tuple[str, str], list[tuple[int, asyncio.StreamWriter]]] = {}
        for sid, (w, pattern, queue) in self._subs.items():
            if not subject_match(pattern, subject):
                continue
            if queue is None:
                plain.append((sid, w))
            else:
                groups.setdefault((pattern, queue), []).append((sid, w))
        for key, members in groups.items():
            members.sort()
            i = self._rr.get(key, 0)
            plain.append(members[i % len(members)])
            self._rr[key] = i + 1
        for sid, w in plain:
            try:
                await self._send(w, [0, "msg", sid, subject, packet_bytes])
            except Exception as e:  # noqa: BLE001 - one dead peer must not stop fanout
                logx.debug("dropping subscriber mid-fanout", sid=sid, err=str(e))


class StateBusConn:
    """Shared TCP connection: request/response + push routing.

    Auto-reconnects with exponential backoff when the connection drops
    (reference NATS behavior: infinite reconnect, ``nats.go:59``).  In-flight
    calls fail with :class:`ConnectionError`; subsequent calls wait for the
    reconnect (bounded by their timeout) and succeed; subscriptions are
    re-issued server-side on every reconnect, so one statebus blip no longer
    wedges a service until restart.
    """

    def __init__(self, host: str, port: int, *, reconnect: bool = True,
                 max_backoff_s: float = 2.0) -> None:
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._req_id = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._handlers: dict[int, Any] = {}  # server sid → async handler(subject, bytes)
        self._reader_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._closed = False
        self._reconnect = reconnect
        self._max_backoff_s = max_backoff_s
        self._connected = asyncio.Event()
        self._reconnect_task: Optional[asyncio.Task] = None
        # client-side subscription registry (survives reconnects):
        # local id → {pattern, queue, handler, sid}
        self._local_sid = itertools.count(1)
        self._subs: dict[int, dict] = {}
        self.reconnect_count = 0
        # connection epoch: bumped on every successful dial; server sids are
        # only meaningful within the epoch that created them (a restarted
        # server reuses low sids, so a stale unsub could kill the wrong sub)
        self._epoch = 0

    async def connect(self) -> None:
        await self._dial()

    async def _dial(self) -> None:
        if self._reader_task is not None and not self._reader_task.done():
            # a reader for a dead/obsolete connection must not linger (its
            # tail would spawn a second reconnect loop → duplicate dials)
            self._reader_task.cancel()
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._epoch += 1
        self._reader_task = asyncio.ensure_future(self._read_loop())
        self._connected.set()

    async def close(self) -> None:
        self._closed = True
        self._connected.set()  # release any call() waiting on reconnect
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        # deliberate close: resolve pending calls quietly (no orphan-task spam)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_result(None)
        self._pending.clear()

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await _read_frame(self._reader)
                if frame is None:
                    break
                if frame[0] == 0 and frame[1] == "msg":
                    _, _, sid, subject, packet_bytes = frame
                    handler = self._handlers.get(sid)
                    if handler is not None:
                        asyncio.ensure_future(handler(subject, packet_bytes))
                    continue
                req_id, status, result = frame
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    if status == "ok":
                        fut.set_result(result)
                    else:
                        fut.set_exception(RuntimeError(f"statebus: {result}"))
        except asyncio.CancelledError:
            raise  # deliberate teardown (close/_dial); no recovery tail
        except Exception:
            # ANY reader failure (OSError ETIMEDOUT, corrupt frame, decode
            # error) must fall into the recovery tail below — otherwise the
            # client wedges with _connected still set and no reconnect
            logx.warn("statebus read loop failed; treating as connection loss")
        # connection lost: fail in-flight calls, then (unless deliberately
        # closed) start the reconnect loop
        self._connected.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("statebus connection lost"))
        self._pending.clear()
        if not self._closed and self._reconnect:
            t = self._reconnect_task
            if t is None or t.done():  # never two concurrent reconnect loops
                logx.warn("statebus connection lost; reconnecting",
                          host=self.host, port=self.port)
                self._reconnect_task = asyncio.ensure_future(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        backoff = 0.05
        while not self._closed:
            try:
                await self._dial()
                await self._resubscribe()
                self.reconnect_count += 1
                logx.info("statebus reconnected", host=self.host, port=self.port,
                          subs=len(self._subs))
                return
            except (OSError, ConnectionError):
                # dial refused OR the fresh connection died mid-resubscribe —
                # either way this same loop retries (the dead reader task is
                # cancelled by the next _dial, so no second loop spawns)
                self._connected.clear()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self._max_backoff_s)

    async def _resubscribe(self) -> None:
        """Re-issue every registered subscription on the fresh connection."""
        self._handlers.clear()
        # snapshot: _connected is already set, so a concurrent subscribe()
        # may insert into _subs while we await — iterating the live dict
        # would raise and kill the reconnect task
        for entry in list(self._subs.values()):
            sid = await self._call_now("sub", entry["pattern"], entry["queue"] or "")
            entry["sid"] = sid
            entry["epoch"] = self._epoch
            self._handlers[sid] = entry["handler"]

    # -- subscriptions (registry survives reconnects) -------------------
    async def subscribe(self, pattern: str, queue: str, handler) -> int:
        local = next(self._local_sid)
        # register in _subs only AFTER the server ack: a subscribe that rides
        # a reconnect must not ALSO be issued by _resubscribe (double sid →
        # every message delivered twice)
        sid = await self.call("sub", pattern, queue or "")
        self._subs[local] = {"pattern": pattern, "queue": queue,
                             "handler": handler, "sid": sid, "epoch": self._epoch}
        self._handlers[sid] = handler
        return local

    async def unsubscribe(self, local: int) -> None:
        entry = self._subs.pop(local, None)
        if entry is None:
            return
        sid = entry.get("sid")
        if sid is not None:
            self._handlers.pop(sid, None)
            if entry.get("epoch") != self._epoch or not self._connected.is_set():
                # sid belongs to a dead connection (a restarted server reuses
                # sids, so sending it could kill a live sub), or we're
                # disconnected (server already dropped the sub; the entry is
                # out of _subs so _resubscribe won't revive it)
                return
            try:
                # _call_now (not call): must never ride a reconnect, where the
                # epoch would have moved on under us
                await self._call_now("unsub", sid, timeout_s=2.0)
            except (ConnectionError, RuntimeError):
                pass  # server side cleans up on disconnect anyway

    # -- calls ----------------------------------------------------------
    async def call(self, op: str, *args: Any, timeout_s: float = 15.0) -> Any:
        if self._closed:
            raise ConnectionError("statebus connection closed")
        remaining = timeout_s
        if not self._connected.is_set():
            # disconnected: wait (bounded) for the reconnect loop to win;
            # the wait spends the caller's budget — total latency stays
            # bounded by timeout_s, not 2x
            t0 = time.monotonic()
            try:
                await asyncio.wait_for(self._connected.wait(), timeout_s)
            except asyncio.TimeoutError:
                raise ConnectionError(
                    f"statebus call {op!r}: not connected after {timeout_s}s"
                )
            if self._closed:
                raise ConnectionError("statebus connection closed")
            remaining = max(0.05, timeout_s - (time.monotonic() - t0))
        return await self._call_now(op, *args, timeout_s=remaining)

    async def _call_now(self, op: str, *args: Any, timeout_s: float = 15.0) -> Any:
        req_id = next(self._req_id)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            async with self._lock:
                self._writer.write(_encode([req_id, op, *args]))
                await self._writer.drain()
        except (ConnectionError, OSError) as e:
            self._pending.pop(req_id, None)
            raise ConnectionError(f"statebus call {op!r} failed: {e}")
        try:
            # bounded wait: a half-open TCP connection (host died without
            # FIN/RST) must surface as an error, not wedge the service
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            raise ConnectionError(f"statebus call {op!r} timed out after {timeout_s}s")


def _maybe_bytes(v: Any) -> Any:
    return v


class StateBusKV(KV):
    """KV interface over a statebus connection."""

    def __init__(self, conn: StateBusConn) -> None:
        self.conn = conn

    async def close(self) -> None:
        await self.conn.close()


def _make_kv_method(op: str) -> Any:
    import inspect

    sig = inspect.signature(getattr(MemoryKV, op))

    async def method(self: "StateBusKV", *args: Any, **kwargs: Any) -> Any:
        if kwargs:  # server applies ops positionally: bind kwargs through
            bound = sig.bind(self, *args, **kwargs)
            bound.apply_defaults()
            args = bound.args[1:]
            if bound.kwargs:
                args = (*args, *bound.kwargs.values())
        self._observe_op(op)
        result = await self.conn.call(op, *args)
        if op == "smembers" and isinstance(result, list):
            return set(result)
        if op == "hgetall" and isinstance(result, dict):
            return {k if isinstance(k, str) else k.decode(): v for k, v in result.items()}
        if op == "watch_read" and isinstance(result, (list, tuple)):
            ver, h = result
            return ver, {k if isinstance(k, str) else k.decode(): v for k, v in (h or {}).items()}
        return result

    method.__name__ = op
    return method


for _op in _KV_OPS:
    if _op != "commit":
        setattr(StateBusKV, _op, _make_kv_method(_op))


async def _commit(self, watches: dict[str, int], ops: list[tuple]) -> bool:
    self._observe_op("commit")
    return await self.conn.call("commit", watches, [list(o) for o in ops])


async def _pipe_execute(
    self, watches: dict[str, int], ops: list[tuple]
) -> tuple[bool, dict[str, int]]:
    """One PIPE wire frame: the whole batch rides a single request and gets
    a single ``[ok, new_versions]`` reply — N ops, one TCP round trip."""
    self._observe_op("pipe", pipeline_size=len(ops))
    ok, versions = await self.conn.call("pipe", watches, [list(o) for o in ops])
    return bool(ok), {
        k if isinstance(k, str) else k.decode(): v for k, v in (versions or {}).items()
    }


async def _server_metrics(self) -> str:
    """Server-side metrics exposition (cordum_statebus_op_seconds etc.)."""
    return str(await self.conn.call("metrics"))


StateBusKV.commit = _commit  # type: ignore[assignment]
StateBusKV.pipe_execute = _pipe_execute  # type: ignore[assignment]
StateBusKV.server_metrics = _server_metrics  # type: ignore[attr-defined]


class StateBusBus(Bus):
    """Bus interface over a statebus connection, with client-side RetryAfter
    redelivery (at-least-once on durable subjects)."""

    def __init__(self, conn: StateBusConn) -> None:
        self.conn = conn

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        await self.conn.call("pub", subject, pkt.to_wire())

    async def subscribe(self, pattern: str, handler, *, queue: Optional[str] = None) -> Subscription:
        from ..protocol import subjects as subj

        async def deliver(subject: str, packet_bytes: bytes) -> None:
            # iterative redelivery (NOT recursive): a hot NAK cycle must not
            # grow the stack across MAX_REDELIVERIES, and the requested delay
            # is capped so one bad handler can't park a delivery task forever
            attempt = 1
            while True:
                try:
                    await handler(subject, BusPacket.from_wire(packet_bytes))
                    return
                except RetryAfter as ra:
                    if not subj.is_durable_subject(subject) or attempt >= MAX_REDELIVERIES:
                        logx.warn("dropping message after retries", subject=subject)
                        return
                    attempt += 1
                    await asyncio.sleep(min(max(ra.delay_s, 0.0), MAX_NAK_DELAY_S))
                except Exception:
                    logx.error("bus handler error", subject=subject)
                    return

        local = await self.conn.subscribe(pattern, queue or "", deliver)

        def _unsub() -> None:
            asyncio.ensure_future(self.conn.unsubscribe(local))

        return Subscription(_unsub)

    async def ping(self) -> bool:
        try:
            return bool(await self.conn.call("ping"))
        except Exception:
            return False


async def connect(url: str = "") -> tuple[StateBusKV, StateBusBus, StateBusConn]:
    """Parse ``statebus://host:port`` (env CORDUM_STATEBUS_URL) and connect."""
    url = url or os.environ.get("CORDUM_STATEBUS_URL", "statebus://127.0.0.1:7420")
    hostport = url.split("://", 1)[-1]
    host, _, port = hostport.partition(":")
    conn = StateBusConn(host or "127.0.0.1", int(port or 7420))
    await conn.connect()
    return StateBusKV(conn), StateBusBus(conn), conn
