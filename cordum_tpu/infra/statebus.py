"""Statebus: the framework's standalone state + message-bus server.

The reference control plane outsources state to Redis and messaging to NATS
(SURVEY §2.2).  This environment has neither client library — and a
TPU-native deployment wants one less moving part anyway — so the framework
ships its own: a single asyncio TCP server speaking a msgpack-framed
protocol that provides BOTH

  * the full :class:`~cordum_tpu.infra.kv.KV` surface (strings, hashes,
    z-sets, lists, sets, TTLs, versioned optimistic ``commit``) backed by
    the in-process :class:`MemoryKV` engine, with optional append-only-file
    persistence (every mutating op logged; replayed on restart — the
    "crash-safe state" guarantee), and
  * pub/sub with NATS-style wildcard subjects and queue groups
    (:class:`StateBusBus` delivers into local handlers with the same
    RetryAfter redelivery semantics as the loopback bus).

Wire format: ``[4-byte BE length][msgpack array]``.
Requests:  ``[req_id, op, *args]`` → ``[req_id, "ok"|"err", result]``.
Server pushes: ``[0, "msg", sid, subject, packet_bytes]``.
"""
from __future__ import annotations

import asyncio
import itertools
import os
import struct
import time
from typing import Any, Optional

import msgpack

from ..protocol.partition import partition_of
from ..protocol.types import BusPacket
from ..utils.globmatch import subject_match
from . import logging as logx
from .bus import (
    Bus,
    DEDUP_WINDOW_S,
    MAX_NAK_DELAY_S,
    MAX_REDELIVERIES,
    RetryAfter,
    Subscription,
    compute_msg_id,
)
from .kv import KV, MemoryKV
from .metrics import Metrics

_LEN = struct.Struct(">I")


def _read_bytes(path: str) -> bytes:
    """Sync AOF read; callers run it via asyncio.to_thread (CL003)."""
    with open(path, "rb") as f:  # cordumlint: disable=CL003 -- runs via asyncio.to_thread
        return f.read()

# KV ops forwarded verbatim to the MemoryKV engine (name → is_mutation)
_KV_OPS = {
    "get": False, "set": True, "setnx": True, "delete": True, "del_eq": True,
    "expire": True,
    "keys": False, "hset": True, "hget": False, "hgetall": False, "hdel": True,
    "hincrby": True, "zadd": True, "zrem": True, "zrange": False,
    "zrangebyscore": False, "zcard": False, "zscore": False, "rpush": True,
    "lrange": False, "ltrim": True, "llen": False, "sadd": True,
    "smembers": False, "version": False, "watch_read": False, "commit": True,
    "ping": False,
}


def _encode(obj: Any) -> bytes:
    b = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(b)) + b


class _FrameWriter:
    """Per-connection write coalescer.

    ``send()`` enqueues a frame synchronously; one flusher task drains the
    accumulated batch per wakeup.  N replies (or N pipelined requests)
    produced in one event-loop tick cost ONE socket write + drain instead
    of N lock/write/drain cycles — without this, pipelined commits arriving
    from many scheduler shards interleave into tiny writes and the
    per-frame ``drain()`` syscalls dominate the statebus hot path.
    Batch sizes surface as ``cordum_statebus_coalesced_batch``.
    """

    __slots__ = ("_writer", "_buf", "_wake", "_task", "_metrics", "_closed")

    def __init__(self, writer: asyncio.StreamWriter, metrics: Optional[Metrics] = None) -> None:
        self._writer = writer
        self._buf: list[bytes] = []
        self._wake = asyncio.Event()
        self._metrics = metrics
        self._closed = False
        self._task = asyncio.ensure_future(self._run())

    def send(self, frame: bytes) -> None:
        if self._closed:
            raise ConnectionError("statebus frame writer closed")
        self._buf.append(frame)
        self._wake.set()

    async def _run(self) -> None:
        try:
            while not self._closed:
                await self._wake.wait()
                self._wake.clear()
                if not self._buf:
                    continue
                buf, self._buf = self._buf, []
                if self._metrics is not None:
                    self._metrics.statebus_coalesced_batch.observe(float(len(buf)))
                self._writer.write(buf[0] if len(buf) == 1 else b"".join(buf))
                # drain AFTER the batch: backpressure throttles the flusher
                # (and everything queued behind it), never individual sends
                await self._writer.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError):
            # peer gone mid-flush: subsequent send() raises; the owning
            # connection's read loop drives recovery/teardown
            self._closed = True

    async def close(self) -> None:
        self._closed = True
        self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, ConnectionError, OSError):
            pass


async def _read_frame(reader: asyncio.StreamReader) -> Optional[list]:
    try:
        head = await reader.readexactly(4)
        (n,) = _LEN.unpack(head)
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


def _plain(v: Any) -> Any:
    """msgpack-safe: sets → sorted lists."""
    if isinstance(v, set):
        return sorted(v)
    return v


class StateBusServer:
    """The server process: KV engine + subscription routing + AOF."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7420, *, aof_path: str = "") -> None:
        self.host = host
        self.port = port
        self.kv = MemoryKV()
        self.aof_path = aof_path
        self._aof = None
        self._last_fsync = 0.0
        self._server: Optional[asyncio.base_events.Server] = None
        # sid → (writer, pattern, queue)
        self._subs: dict[int, tuple[asyncio.StreamWriter, str, Optional[str]]] = {}
        self._sid = itertools.count(1)
        self._rr: dict[tuple[str, str], int] = {}
        self._dedup: dict[str, float] = {}
        self._writers: set[asyncio.StreamWriter] = set()
        self._fws: dict[asyncio.StreamWriter, _FrameWriter] = {}
        # server-side observability: per-op execution latency + pipeline
        # sizes; rendered via the `metrics` wire op (cordum_statebus_op_seconds)
        self.metrics = Metrics()

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        if self.aof_path:
            await self._replay_aof()
            self._aof = await asyncio.to_thread(open, self.aof_path, "ab")
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logx.info("statebus listening", host=self.host, port=self.port, aof=self.aof_path or "off")

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        # Close client writers BEFORE wait_closed: Python 3.12's
        # Server.wait_closed() waits for connection handlers to finish, and
        # handlers block reading from clients that never hang up.
        for w in list(self._writers):
            w.close()
        if self._server:
            await self._server.wait_closed()
            self._server = None
        if self._aof:
            self._aof.flush()
            self._aof.close()
            self._aof = None

    async def _replay_aof(self) -> None:
        if not os.path.exists(self.aof_path):
            return
        n = 0
        raw = await asyncio.to_thread(_read_bytes, self.aof_path)
        unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
        unpacker.feed(raw)
        for entry in unpacker:
            op, args = entry[0], entry[1:]
            try:
                await getattr(self.kv, op)(*args)
                n += 1
            except Exception:
                logx.warn("aof replay skipped bad entry", op=op)
        logx.info("aof replayed", entries=n)

    def _log_aof(self, op: str, args: tuple) -> None:
        if self._aof is not None:
            self._aof.write(msgpack.packb([op, *args], use_bin_type=True))
            # flush before the op is acked: process-crash durability (an
            # fsync interval below bounds power-loss exposure)
            self._aof.flush()
            now = time.monotonic()
            if now - self._last_fsync > 0.2:
                os.fsync(self._aof.fileno())
                self._last_fsync = now

    # -- connection handling -------------------------------------------
    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        fw = _FrameWriter(writer, self.metrics)
        self._fws[writer] = fw
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                # inline dispatch (no per-frame task): KV ops are pure memory
                # and replies are buffered, so a frame costs no task churn
                # and a connection's ops apply in arrival order
                await self._dispatch(frame, writer)
        finally:
            self._writers.discard(writer)
            self._fws.pop(writer, None)
            await fw.close()
            dead = [sid for sid, (w, _, _) in self._subs.items() if w is writer]
            for sid in dead:
                del self._subs[sid]
            writer.close()

    async def _send(self, writer: asyncio.StreamWriter, obj: list) -> None:
        fw = self._fws.get(writer)
        if fw is None:
            return
        try:
            fw.send(_encode(obj))
        except ConnectionError:
            pass  # peer mid-teardown; its handler cleans up

    async def _dispatch(self, frame: list, writer: asyncio.StreamWriter) -> None:
        req_id, op, *args = frame
        try:
            if op in _KV_OPS:
                t0 = time.perf_counter()
                result = await getattr(self.kv, op)(*args)
                if _KV_OPS[op]:
                    self._log_aof(op, tuple(args))
                self.metrics.statebus_op_seconds.observe(
                    time.perf_counter() - t0, op=op
                )
                await self._send(writer, [req_id, "ok", _plain(result)])
            elif op == "pipe":
                # one wire frame = one atomic multi-op batch (the whole point
                # of the pipeline layer: N mutations, ONE round trip)
                watches, ops = args
                t0 = time.perf_counter()
                ok, versions = await self.kv.pipe_execute(watches, ops)
                self._log_aof("pipe_execute", (watches, ops))
                self.metrics.statebus_op_seconds.observe(
                    time.perf_counter() - t0, op="pipe"
                )
                self.metrics.kv_pipeline_size.observe(float(len(ops)))
                await self._send(writer, [req_id, "ok", [ok, versions]])
            elif op == "metrics":
                await self._send(writer, [req_id, "ok", self.metrics.render()])
            elif op == "sub":
                pattern, queue = args
                sid = next(self._sid)
                self._subs[sid] = (writer, pattern, queue or None)
                await self._send(writer, [req_id, "ok", sid])
            elif op == "unsub":
                self._subs.pop(args[0], None)
                await self._send(writer, [req_id, "ok", True])
            elif op == "pub":
                subject, packet_bytes = args
                await self._route(subject, packet_bytes)
                await self._send(writer, [req_id, "ok", True])
            else:
                await self._send(writer, [req_id, "err", f"unknown op {op!r}"])
        except Exception as e:  # noqa: BLE001
            try:
                await self._send(writer, [req_id, "err", str(e)])
            except Exception as send_err:  # noqa: BLE001 - peer already gone
                logx.debug("could not deliver error reply", err=str(send_err))

    async def _route(self, subject: str, packet_bytes: bytes) -> None:
        from ..protocol import subjects as subj

        if subj.is_durable_subject(subject):
            try:
                pkt = BusPacket.from_wire(packet_bytes)
                mid = compute_msg_id(subject, pkt)
            except Exception:
                mid = ""
            if mid:
                now = time.monotonic()
                if len(self._dedup) > 16384:
                    for k in list(itertools.islice(self._dedup, 8192)):
                        del self._dedup[k]
                seen = self._dedup.get(mid)
                if seen is not None and now - seen < DEDUP_WINDOW_S:
                    return
                self._dedup[mid] = now
        plain: list[tuple[int, asyncio.StreamWriter]] = []
        groups: dict[tuple[str, str], list[tuple[int, asyncio.StreamWriter]]] = {}
        for sid, (w, pattern, queue) in self._subs.items():
            if not subject_match(pattern, subject):
                continue
            if queue is None:
                plain.append((sid, w))
            else:
                groups.setdefault((pattern, queue), []).append((sid, w))
        for key, members in groups.items():
            members.sort()
            i = self._rr.get(key, 0)
            plain.append(members[i % len(members)])
            self._rr[key] = i + 1
        for sid, w in plain:
            try:
                await self._send(w, [0, "msg", sid, subject, packet_bytes])
            except Exception as e:  # noqa: BLE001 - one dead peer must not stop fanout
                logx.debug("dropping subscriber mid-fanout", sid=sid, err=str(e))


class StateBusConn:
    """Shared TCP connection: request/response + push routing.

    Auto-reconnects with exponential backoff when the connection drops
    (reference NATS behavior: infinite reconnect, ``nats.go:59``).  In-flight
    calls fail with :class:`ConnectionError`; subsequent calls wait for the
    reconnect (bounded by their timeout) and succeed; subscriptions are
    re-issued server-side on every reconnect, so one statebus blip no longer
    wedges a service until restart.
    """

    def __init__(self, host: str, port: int, *, reconnect: bool = True,
                 max_backoff_s: float = 2.0) -> None:
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._req_id = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._handlers: dict[int, Any] = {}  # server sid → async handler(subject, bytes)
        self._reader_task: Optional[asyncio.Task] = None
        self._fw: Optional[_FrameWriter] = None
        self._closed = False
        self._reconnect = reconnect
        self._max_backoff_s = max_backoff_s
        self._connected = asyncio.Event()
        self._reconnect_task: Optional[asyncio.Task] = None
        # client-side subscription registry (survives reconnects):
        # local id → {pattern, queue, handler, sid}
        self._local_sid = itertools.count(1)
        self._subs: dict[int, dict] = {}
        self.reconnect_count = 0
        # connection epoch: bumped on every successful dial; server sids are
        # only meaningful within the epoch that created them (a restarted
        # server reuses low sids, so a stale unsub could kill the wrong sub)
        self._epoch = 0

    async def connect(self) -> None:
        await self._dial()

    async def _dial(self) -> None:
        if self._reader_task is not None and not self._reader_task.done():
            # a reader for a dead/obsolete connection must not linger (its
            # tail would spawn a second reconnect loop → duplicate dials)
            self._reader_task.cancel()
        if self._fw is not None:
            await self._fw.close()
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._fw = _FrameWriter(self._writer)
        self._epoch += 1
        self._reader_task = asyncio.ensure_future(self._read_loop())
        self._connected.set()

    async def close(self) -> None:
        self._closed = True
        self._connected.set()  # release any call() waiting on reconnect
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._fw is not None:
            await self._fw.close()
        if self._writer:
            self._writer.close()
        # deliberate close: resolve pending calls quietly (no orphan-task spam)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_result(None)
        self._pending.clear()

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await _read_frame(self._reader)
                if frame is None:
                    break
                if frame[0] == 0 and frame[1] == "msg":
                    _, _, sid, subject, packet_bytes = frame
                    handler = self._handlers.get(sid)
                    if handler is not None:
                        asyncio.ensure_future(handler(subject, packet_bytes))
                    continue
                req_id, status, result = frame
                fut = self._pending.pop(req_id, None)
                if fut is not None and not fut.done():
                    if status == "ok":
                        fut.set_result(result)
                    else:
                        fut.set_exception(RuntimeError(f"statebus: {result}"))
        except asyncio.CancelledError:
            raise  # deliberate teardown (close/_dial); no recovery tail
        except Exception:
            # ANY reader failure (OSError ETIMEDOUT, corrupt frame, decode
            # error) must fall into the recovery tail below — otherwise the
            # client wedges with _connected still set and no reconnect
            logx.warn("statebus read loop failed; treating as connection loss")
        # connection lost: fail in-flight calls, then (unless deliberately
        # closed) start the reconnect loop
        self._connected.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("statebus connection lost"))
        self._pending.clear()
        if not self._closed and self._reconnect:
            t = self._reconnect_task
            if t is None or t.done():  # never two concurrent reconnect loops
                logx.warn("statebus connection lost; reconnecting",
                          host=self.host, port=self.port)
                self._reconnect_task = asyncio.ensure_future(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        backoff = 0.05
        while not self._closed:
            try:
                await self._dial()
                await self._resubscribe()
                self.reconnect_count += 1
                logx.info("statebus reconnected", host=self.host, port=self.port,
                          subs=len(self._subs))
                return
            except (OSError, ConnectionError):
                # dial refused OR the fresh connection died mid-resubscribe —
                # either way this same loop retries (the dead reader task is
                # cancelled by the next _dial, so no second loop spawns)
                self._connected.clear()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self._max_backoff_s)

    async def _resubscribe(self) -> None:
        """Re-issue every registered subscription on the fresh connection."""
        self._handlers.clear()
        # snapshot: _connected is already set, so a concurrent subscribe()
        # may insert into _subs while we await — iterating the live dict
        # would raise and kill the reconnect task
        for entry in list(self._subs.values()):
            sid = await self._call_now("sub", entry["pattern"], entry["queue"] or "")
            entry["sid"] = sid
            entry["epoch"] = self._epoch
            self._handlers[sid] = entry["handler"]

    # -- subscriptions (registry survives reconnects) -------------------
    async def subscribe(self, pattern: str, queue: str, handler) -> int:
        local = next(self._local_sid)
        # register in _subs only AFTER the server ack: a subscribe that rides
        # a reconnect must not ALSO be issued by _resubscribe (double sid →
        # every message delivered twice)
        sid = await self.call("sub", pattern, queue or "")
        self._subs[local] = {"pattern": pattern, "queue": queue,
                             "handler": handler, "sid": sid, "epoch": self._epoch}
        self._handlers[sid] = handler
        return local

    async def unsubscribe(self, local: int) -> None:
        entry = self._subs.pop(local, None)
        if entry is None:
            return
        sid = entry.get("sid")
        if sid is not None:
            self._handlers.pop(sid, None)
            if entry.get("epoch") != self._epoch or not self._connected.is_set():
                # sid belongs to a dead connection (a restarted server reuses
                # sids, so sending it could kill a live sub), or we're
                # disconnected (server already dropped the sub; the entry is
                # out of _subs so _resubscribe won't revive it)
                return
            try:
                # _call_now (not call): must never ride a reconnect, where the
                # epoch would have moved on under us
                await self._call_now("unsub", sid, timeout_s=2.0)
            except (ConnectionError, RuntimeError):
                pass  # server side cleans up on disconnect anyway

    # -- calls ----------------------------------------------------------
    async def call(self, op: str, *args: Any, timeout_s: float = 15.0) -> Any:
        if self._closed:
            raise ConnectionError("statebus connection closed")
        remaining = timeout_s
        if not self._connected.is_set():
            # disconnected: wait (bounded) for the reconnect loop to win;
            # the wait spends the caller's budget — total latency stays
            # bounded by timeout_s, not 2x
            t0 = time.monotonic()
            try:
                await asyncio.wait_for(self._connected.wait(), timeout_s)
            except asyncio.TimeoutError:
                raise ConnectionError(
                    f"statebus call {op!r}: not connected after {timeout_s}s"
                )
            if self._closed:
                raise ConnectionError("statebus connection closed")
            remaining = max(0.05, timeout_s - (time.monotonic() - t0))
        return await self._call_now(op, *args, timeout_s=remaining)

    async def _call_now(self, op: str, *args: Any, timeout_s: float = 15.0) -> Any:
        req_id = next(self._req_id)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            # coalesced write: the frame enqueues synchronously and rides the
            # connection's next batched flush — concurrent in-flight calls
            # (engine submit_concurrency) share one socket write per tick
            self._fw.send(_encode([req_id, op, *args]))
        except (AttributeError, ConnectionError, OSError) as e:
            self._pending.pop(req_id, None)
            raise ConnectionError(f"statebus call {op!r} failed: {e}")
        try:
            # bounded wait: a half-open TCP connection (host died without
            # FIN/RST) must surface as an error, not wedge the service
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            raise ConnectionError(f"statebus call {op!r} timed out after {timeout_s}s")


def _maybe_bytes(v: Any) -> Any:
    return v


class StateBusKV(KV):
    """KV interface over a statebus connection."""

    def __init__(self, conn: StateBusConn) -> None:
        self.conn = conn

    async def close(self) -> None:
        await self.conn.close()


def _make_kv_method(op: str) -> Any:
    import inspect

    sig = inspect.signature(getattr(MemoryKV, op))

    async def method(self: "StateBusKV", *args: Any, **kwargs: Any) -> Any:
        if kwargs:  # server applies ops positionally: bind kwargs through
            bound = sig.bind(self, *args, **kwargs)
            bound.apply_defaults()
            args = bound.args[1:]
            if bound.kwargs:
                args = (*args, *bound.kwargs.values())
        self._observe_op(op)
        result = await self.conn.call(op, *args)
        if op == "smembers" and isinstance(result, list):
            return set(result)
        if op == "hgetall" and isinstance(result, dict):
            return {k if isinstance(k, str) else k.decode(): v for k, v in result.items()}
        if op == "watch_read" and isinstance(result, (list, tuple)):
            ver, h = result
            return ver, {k if isinstance(k, str) else k.decode(): v for k, v in (h or {}).items()}
        return result

    method.__name__ = op
    return method


for _op in _KV_OPS:
    if _op != "commit":
        setattr(StateBusKV, _op, _make_kv_method(_op))


async def _commit(self, watches: dict[str, int], ops: list[tuple]) -> bool:
    self._observe_op("commit")
    return await self.conn.call("commit", watches, [list(o) for o in ops])


async def _pipe_execute(
    self, watches: dict[str, int], ops: list[tuple]
) -> tuple[bool, dict[str, int]]:
    """One PIPE wire frame: the whole batch rides a single request and gets
    a single ``[ok, new_versions]`` reply — N ops, one TCP round trip."""
    self._observe_op("pipe", pipeline_size=len(ops))
    ok, versions = await self.conn.call("pipe", watches, [list(o) for o in ops])
    return bool(ok), {
        k if isinstance(k, str) else k.decode(): v for k, v in (versions or {}).items()
    }


async def _server_metrics(self) -> str:
    """Server-side metrics exposition (cordum_statebus_op_seconds etc.)."""
    return str(await self.conn.call("metrics"))


StateBusKV.commit = _commit  # type: ignore[assignment]
StateBusKV.pipe_execute = _pipe_execute  # type: ignore[assignment]
StateBusKV.server_metrics = _server_metrics  # type: ignore[attr-defined]


class StateBusBus(Bus):
    """Bus interface over a statebus connection, with client-side RetryAfter
    redelivery (at-least-once on durable subjects)."""

    def __init__(self, conn: StateBusConn) -> None:
        self.conn = conn

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        await self.conn.call("pub", subject, pkt.to_wire())

    async def subscribe(self, pattern: str, handler, *, queue: Optional[str] = None) -> Subscription:
        from ..protocol import subjects as subj

        async def deliver(subject: str, packet_bytes: bytes) -> None:
            # iterative redelivery (NOT recursive): a hot NAK cycle must not
            # grow the stack across MAX_REDELIVERIES, and the requested delay
            # is capped so one bad handler can't park a delivery task forever
            attempt = 1
            while True:
                try:
                    await handler(subject, BusPacket.from_wire(packet_bytes))
                    return
                except RetryAfter as ra:
                    if not subj.is_durable_subject(subject) or attempt >= MAX_REDELIVERIES:
                        logx.warn("dropping message after retries", subject=subject)
                        return
                    attempt += 1
                    await asyncio.sleep(min(max(ra.delay_s, 0.0), MAX_NAK_DELAY_S))
                except Exception:
                    logx.error("bus handler error", subject=subject)
                    return

        local = await self.conn.subscribe(pattern, queue or "", deliver)

        def _unsub() -> None:
            asyncio.ensure_future(self.conn.unsubscribe(local))

        return Subscription(_unsub)

    async def ping(self) -> bool:
        try:
            return bool(await self.conn.call("ping"))
        except Exception:
            return False


async def connect(url: str = "") -> tuple[StateBusKV, StateBusBus, StateBusConn]:
    """Parse ``statebus://host:port`` (env CORDUM_STATEBUS_URL) and connect."""
    url = url or os.environ.get("CORDUM_STATEBUS_URL", "statebus://127.0.0.1:7420")
    hostport = url.split("://", 1)[-1]
    host, _, port = hostport.partition(":")
    conn = StateBusConn(host or "127.0.0.1", int(port or 7420))
    await conn.connect()
    return StateBusKV(conn), StateBusBus(conn), conn


# ---------------------------------------------------------------------------
# partitioned statebus: N independent servers, clients route by keyspace
# ---------------------------------------------------------------------------

# Keys whose trailing segment is the routing id: every key of one job (or
# trace) lands on ONE partition, which is what keeps pipelined commits —
# always watched on job:meta:<id> — atomic on a single server.
_ID_ROUTED_PREFIXES = (
    "job:meta:", "job:events:", "job:request:", "job:safety:",
    "job:approval:", "lock:job:", "trace:spans:",
)

# Shared index containers whose members are job ids.  They are mutated
# INSIDE job-routed pipes, so each partition holds the slice for the ids it
# owns: standalone writes route by member, reads fan out and merge.
_MEMBER_ROUTED_EXACT = frozenset(("job:recent", "job:deadline"))
_MEMBER_ROUTED_PREFIXES = ("job:index:", "job:tenant_active:", "trace:")


def _route_key(key: str) -> str:
    for p in _ID_ROUTED_PREFIXES:
        if key.startswith(p):
            return key[len(p):] or key
    return key


def _member_routed(key: str) -> bool:
    if key in _MEMBER_ROUTED_EXACT:
        return True
    if key.startswith("trace:spans:"):
        return False  # id-routed (collector span ring buffers + their index)
    return key.startswith(_MEMBER_ROUTED_PREFIXES)


class PartitionedKV(KV):
    """KV facade over N statebus partitions (docs/PROTOCOL.md §Partitioning).

    Point ops route by :func:`_route_key` hash; member-routed index
    containers write to ``partition_of(member)`` and merge reads across
    every partition (union / sum; cross-partition ordering of merged
    listings is approximate — they are observability surfaces).  A pipeline
    executes atomically on the partition of its first watched key, which by
    construction is the job's home partition for every control-plane pipe.
    """

    def __new__(cls, parts: list[KV]) -> Any:
        parts = list(parts)
        if len(parts) == 1:
            # identity dispatch chosen at construction: an unsharded store
            # IS its single backend — no routing layer, no per-op branching
            # on the 1×1 hot path (ISSUE 6)
            return parts[0]
        return super().__new__(cls)

    def __init__(self, parts: list[KV]) -> None:
        self.parts = list(parts)
        self.n = len(self.parts)

    def bind_metrics(self, metrics: Any) -> None:
        self.metrics = metrics
        for p in self.parts:
            p.bind_metrics(metrics)

    def _one(self, key: str) -> KV:
        if self._member_is_global(key):
            return self.parts[0]  # deterministic home for member-routed point ops
        return self.parts[partition_of(_route_key(key), self.n)]

    @staticmethod
    def _member_is_global(key: str) -> bool:
        return _member_routed(key)

    def _by_member(self, member: str) -> KV:
        return self.parts[partition_of(member, self.n)]

    # strings -------------------------------------------------------------
    async def get(self, key):
        return await self._one(key).get(key)

    async def set(self, key, value, ttl_s=None):
        return await self._one(key).set(key, value, ttl_s)

    async def setnx(self, key, value, ttl_s=None):
        return await self._one(key).setnx(key, value, ttl_s)

    async def delete(self, *keys):
        grouped: dict[int, list[str]] = {}
        for k in keys:
            if self._member_is_global(k):
                for i in range(self.n):  # slices live on every partition
                    grouped.setdefault(i, []).append(k)
            else:
                grouped.setdefault(partition_of(_route_key(k), self.n), []).append(k)
        counts = await asyncio.gather(
            *(self.parts[i].delete(*ks) for i, ks in grouped.items())
        )
        return sum(counts)

    async def del_eq(self, key, expect):
        return await self._one(key).del_eq(key, expect)

    async def expire(self, key, ttl_s):
        if self._member_is_global(key):
            oks = await asyncio.gather(*(p.expire(key, ttl_s) for p in self.parts))
            return any(oks)
        return await self._one(key).expire(key, ttl_s)

    async def keys(self, prefix=""):
        lists = await asyncio.gather(*(p.keys(prefix) for p in self.parts))
        return sorted({k for ks in lists for k in ks})

    # hashes --------------------------------------------------------------
    async def hset(self, key, mapping):
        return await self._one(key).hset(key, mapping)

    async def hget(self, key, field):
        return await self._one(key).hget(key, field)

    async def hgetall(self, key):
        return await self._one(key).hgetall(key)

    async def hdel(self, key, *fields):
        return await self._one(key).hdel(key, *fields)

    async def hincrby(self, key, field, amount=1):
        return await self._one(key).hincrby(key, field, amount)

    # sorted sets ---------------------------------------------------------
    async def zadd(self, key, member, score):
        if self._member_is_global(key):
            return await self._by_member(member).zadd(key, member, score)
        return await self._one(key).zadd(key, member, score)

    async def zrem(self, key, *members):
        if self._member_is_global(key):
            grouped: dict[int, list[str]] = {}
            for m in members:
                grouped.setdefault(partition_of(m, self.n), []).append(m)
            counts = await asyncio.gather(
                *(self.parts[i].zrem(key, *ms) for i, ms in grouped.items())
            )
            return sum(counts)
        return await self._one(key).zrem(key, *members)

    async def zrange(self, key, start=0, stop=-1, desc=False):
        if not self._member_is_global(key):
            return await self._one(key).zrange(key, start, stop, desc)
        # merged listing: fetch each partition's slice of the requested
        # range and concatenate (per-partition order exact, cross-partition
        # approximate — observability surfaces only)
        per_stop = -1 if stop == -1 else stop
        lists = await asyncio.gather(
            *(p.zrange(key, 0, per_stop, desc) for p in self.parts)
        )
        merged = [m for ms in lists for m in ms]
        if stop == -1:
            return merged[start:]
        return merged[start: stop + 1]

    async def zrangebyscore(self, key, min_score, max_score, limit=0):
        if not self._member_is_global(key):
            return await self._one(key).zrangebyscore(key, min_score, max_score, limit)
        lists = await asyncio.gather(
            *(p.zrangebyscore(key, min_score, max_score, limit) for p in self.parts)
        )
        merged = [m for ms in lists for m in ms]
        return merged[:limit] if limit else merged

    async def zcard(self, key):
        if not self._member_is_global(key):
            return await self._one(key).zcard(key)
        return sum(await asyncio.gather(*(p.zcard(key) for p in self.parts)))

    async def zscore(self, key, member):
        if self._member_is_global(key):
            return await self._by_member(member).zscore(key, member)
        return await self._one(key).zscore(key, member)

    # lists ---------------------------------------------------------------
    async def rpush(self, key, *values):
        return await self._one(key).rpush(key, *values)

    async def lrange(self, key, start=0, stop=-1):
        return await self._one(key).lrange(key, start, stop)

    async def ltrim(self, key, start, stop):
        return await self._one(key).ltrim(key, start, stop)

    async def llen(self, key):
        return await self._one(key).llen(key)

    # sets ----------------------------------------------------------------
    async def sadd(self, key, *members):
        if self._member_is_global(key):
            grouped: dict[int, list[str]] = {}
            for m in members:
                grouped.setdefault(partition_of(m, self.n), []).append(m)
            counts = await asyncio.gather(
                *(self.parts[i].sadd(key, *ms) for i, ms in grouped.items())
            )
            return sum(counts)
        return await self._one(key).sadd(key, *members)

    async def smembers(self, key):
        if not self._member_is_global(key):
            return await self._one(key).smembers(key)
        sets = await asyncio.gather(*(p.smembers(key) for p in self.parts))
        out: set[str] = set()
        for s in sets:
            out |= s
        return out

    # transactions --------------------------------------------------------
    async def version(self, key):
        return await self._one(key).version(key)

    async def watch_read(self, key):
        return await self._one(key).watch_read(key)

    def pipe_group(self, key: str) -> int:
        """Keys on the same partition may share one grouped pipe commit."""
        if self._member_is_global(key):
            return 0
        return partition_of(_route_key(key), self.n)

    def _pipe_part(self, watches: dict[str, int], ops: list[tuple]) -> KV:
        for key in watches:
            return self._one(key)
        for op in ops:
            if len(op) > 1 and isinstance(op[1], str):
                return self._one(op[1])
        return self.parts[0]

    async def commit(self, watches, ops):
        return await self._pipe_part(watches, ops).commit(watches, ops)

    async def pipe_execute(self, watches, ops):
        return await self._pipe_part(watches, ops).pipe_execute(watches, ops)

    async def ping(self):
        oks = await asyncio.gather(*(p.ping() for p in self.parts))
        return all(oks)

    async def close(self):
        await asyncio.gather(*(p.close() for p in self.parts), return_exceptions=True)


class PartitionedBus(Bus):
    """Bus facade over N statebus partitions.

    A concrete subject always lives on ONE partition (hash of the subject
    string), so queue-group and dedupe semantics stay exact per subject;
    wildcard patterns are subscribed on every partition.  Hashing spreads
    the partitioned lifecycle subjects (``sys.job.submit.<p>`` …) across
    brokers so no single event loop serializes the fleet's messaging.
    """

    def __new__(cls, buses: list[Bus]) -> Any:
        buses = list(buses)
        if len(buses) == 1:
            return buses[0]  # identity dispatch: see PartitionedKV.__new__
        return super().__new__(cls)

    def __init__(self, buses: list[Bus]) -> None:
        self.buses = list(buses)
        self.n = len(self.buses)

    def _bus_for(self, subject: str) -> Bus:
        return self.buses[partition_of(subject, self.n)]

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        await self._bus_for(subject).publish(subject, pkt)

    def has_listener(self, subject: str) -> bool:
        return self._bus_for(subject).has_listener(subject)

    async def subscribe(self, pattern: str, handler, *, queue: Optional[str] = None) -> Subscription:
        if "*" in pattern or ">" in pattern:
            subs = await asyncio.gather(
                *(b.subscribe(pattern, handler, queue=queue) for b in self.buses)
            )

            def _unsub_all() -> None:
                for s in subs:
                    s.unsubscribe()

            return Subscription(_unsub_all)
        return await self._bus_for(pattern).subscribe(pattern, handler, queue=queue)

    async def ping(self) -> bool:
        oks = await asyncio.gather(*(b.ping() for b in self.buses))
        return all(oks)


class ConnGroup:
    """Close-handle over the N connections behind a partitioned client."""

    def __init__(self, conns: list[StateBusConn]) -> None:
        self.conns = list(conns)

    async def close(self) -> None:
        await asyncio.gather(*(c.close() for c in self.conns), return_exceptions=True)


async def connect_partitioned(url: str = "") -> tuple[KV, Bus, ConnGroup]:
    """Connect to one or more statebus partitions.

    ``url`` is a comma-separated list of ``statebus://host:port`` endpoints
    (env ``CORDUM_STATEBUS_URL``); a single endpoint degrades to the plain
    unpartitioned client, so every service binary can use this entry point.
    """
    url = url or os.environ.get("CORDUM_STATEBUS_URL", "statebus://127.0.0.1:7420")
    endpoints = [u.strip() for u in url.split(",") if u.strip()]
    if len(endpoints) <= 1:
        kv, bus, conn = await connect(endpoints[0] if endpoints else "")
        return kv, bus, ConnGroup([conn])
    kvs: list[KV] = []
    buses: list[Bus] = []
    conns: list[StateBusConn] = []
    for ep in endpoints:
        kv, bus, conn = await connect(ep)
        kvs.append(kv)
        buses.append(bus)
        conns.append(conn)
    return PartitionedKV(kvs), PartitionedBus(buses), ConnGroup(conns)
