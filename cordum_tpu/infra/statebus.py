"""Statebus: the framework's standalone state + message-bus server.

The reference control plane outsources state to Redis and messaging to NATS
(SURVEY §2.2).  This environment has neither client library — and a
TPU-native deployment wants one less moving part anyway — so the framework
ships its own: a single asyncio TCP server speaking a msgpack-framed
protocol that provides BOTH

  * the full :class:`~cordum_tpu.infra.kv.KV` surface (strings, hashes,
    z-sets, lists, sets, TTLs, versioned optimistic ``commit``) backed by
    the in-process :class:`MemoryKV` engine, with optional append-only-file
    persistence (every mutating op logged; replayed on restart — the
    "crash-safe state" guarantee), and
  * pub/sub with NATS-style wildcard subjects and queue groups
    (:class:`StateBusBus` delivers into local handlers with the same
    RetryAfter redelivery semantics as the loopback bus).

Wire format: ``[4-byte BE length][msgpack array]``.
Requests:  ``[req_id, op, *args]`` → ``[req_id, "ok"|"err", result]``.
Server pushes: ``[0, "msg", sid, subject, packet_bytes]`` plus the
replication/failover pushes in :mod:`cordum_tpu.infra.replication`.

Replication & failover (docs/PROTOCOL.md §Replication): a server is a
**primary** (accepts writes, ships committed records to attached replicas)
or a **replica** (read-only, applies the primary's stream, promotes on
primary death or an admin ``promote`` frame).  Clients take a
``|``-separated replica set per partition and walk it on connection loss,
re-issuing subscriptions and retransmitting unacked in-flight frames so a
failover never silently drops a pipelined commit.
"""
from __future__ import annotations

import asyncio
import itertools
import os
import random
import time
from typing import Any, Optional

import msgpack

from ..protocol.partition import partition_of
from ..protocol.types import BusPacket
from ..utils.globmatch import subject_match
from . import logging as logx
from .bus import (
    Bus,
    DEDUP_WINDOW_S,
    MAX_NAK_DELAY_S,
    MAX_REDELIVERIES,
    RetryAfter,
    Subscription,
    compute_msg_id,
)
from .frames import FrameWriter as _FrameWriter, encode_frame as _encode, read_frame as _read_frame
from .kv import KV, MemoryKV
from .metrics import Metrics
from . import syncsan
from .replication import (
    ReplicaLink,
    ReplicationState,
    parse_endpoint,
    parse_replica_set,
    unpack_record,
)


def _read_bytes(path: str) -> bytes:
    """Sync AOF read; callers run it via asyncio.to_thread (CL003)."""
    with open(path, "rb") as f:  # cordumlint: disable=CL003 -- runs via asyncio.to_thread
        return f.read()


def _truncate_file(path: str, size: int) -> None:
    """Sync truncate (AOF tail recovery); runs via asyncio.to_thread."""
    with open(path, "r+b") as f:  # cordumlint: disable=CL003 -- runs via asyncio.to_thread
        f.truncate(size)

# KV ops forwarded verbatim to the MemoryKV engine (name → is_mutation)
_KV_OPS = {
    "get": False, "set": True, "setnx": True, "delete": True, "del_eq": True,
    "expire": True,
    "keys": False, "hset": True, "hget": False, "hgetall": False, "hdel": True,
    "hincrby": True, "zadd": True, "zrem": True, "zrange": False,
    "zrangebyscore": False, "zcard": False, "zscore": False, "rpush": True,
    "lrange": False, "ltrim": True, "llen": False, "sadd": True,
    "smembers": False, "version": False, "watch_read": False, "commit": True,
    "ping": False,
}


def _plain(v: Any) -> Any:
    """msgpack-safe: sets → sorted lists."""
    if isinstance(v, set):
        return sorted(v)
    return v


@syncsan.instrument
class StateBusServer:
    """The server process: KV engine + subscription routing + AOF +
    primary/replica replication (docs/PROTOCOL.md §Replication)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7420, *, aof_path: str = "",
                 replica_of: str = "", peers: tuple = (),
                 sync_replication: bool = False, auto_promote: bool = True,
                 heartbeat_interval_s: float = 1.0,
                 heartbeat_timeout_s: float = 3.0,
                 partition: int = -1) -> None:
        self.host = host
        self.port = port
        # keyspace partition index this server serves (-1 = standalone);
        # rides the telemetry health beacon so the fleet view can group
        # primaries/replicas per partition
        self.partition = partition
        self.kv = MemoryKV()
        self.aof_path = aof_path
        self._aof = None
        self._last_fsync = 0.0
        self._server: Optional[asyncio.base_events.Server] = None
        # sid → (writer, pattern, queue)
        self._subs: dict[int, tuple[asyncio.StreamWriter, str, Optional[str]]] = {}
        self._sid = itertools.count(1)
        self._rr: dict[tuple[str, str], int] = {}
        self._dedup: dict[str, float] = {}
        self._writers: set[asyncio.StreamWriter] = set()
        self._fws: dict[asyncio.StreamWriter, _FrameWriter] = {}
        # server-side observability: per-op execution latency + pipeline
        # sizes; rendered via the `metrics` wire op (cordum_statebus_op_seconds)
        self.metrics = Metrics()
        # replication: every server tracks (epoch, offset) + a record
        # backlog; `replica_of` starts this server as a replica of that
        # endpoint, `peers` is the partition's replica set (used by the
        # startup probe so a returning old primary demotes itself)
        self.role = "replica" if replica_of else "primary"
        self.replica_of = replica_of
        self.peers = tuple(peers)
        self.sync_replication = sync_replication
        self.auto_promote = auto_promote
        self.heartbeat_interval_s = heartbeat_interval_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.repl = ReplicationState(self)
        # promote()/demote()/stop() hold this across their link-teardown
        # awaits so a role transition racing a shutdown (or an auto-promote
        # racing an admin demotion) cannot interleave and double-stop or
        # leak the replica link (CL008)
        self._role_lock = asyncio.Lock()
        self._replica_link: Optional[ReplicaLink] = None  # cordum: guarded-by(_role_lock)
        self._hb_task: Optional[asyncio.Task] = None
        self._last_peer_probe = 0.0
        self._telemetry = None  # TelemetryExporter, created at start()

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        if self.aof_path:
            await self._replay_aof()
            self._aof = await asyncio.to_thread(open, self.aof_path, "ab")
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        logx.info("statebus listening", host=self.host, port=self.port,
                  aof=self.aof_path or "off", role=self.role,
                  epoch=self.repl.epoch, offset=self.repl.offset)
        if self.role == "replica":
            await self._start_link(self.replica_of)
        elif self.peers:
            # returning old primary: a live peer primary with a HIGHER epoch
            # was promoted while we were down — demote to it (exclusive
            # promotion: no split-brain dual-accept)
            await self._probe_peers()
        self._hb_task = asyncio.ensure_future(self._hb_loop())
        # fleet telemetry beacon: the statebus IS the bus, so its exporter
        # routes snapshots straight to this server's own subscribers (the
        # gateway's sys.telemetry.> wildcard subscribes on every partition)
        from ..obs.telemetry import TelemetryExporter

        async def _pub(subject: str, pkt: BusPacket) -> None:
            await self._route(subject, pkt.to_wire())

        self._telemetry = TelemetryExporter(
            "statebus", None, self.metrics,
            instance_id=f"statebus-{self.host}:{self.port}",
            health_fn=self._telemetry_health, publish=_pub,
        )
        await self._telemetry.start()

    async def stop(self, *, graceful: bool = True) -> None:
        async with self._role_lock:
            if self._telemetry is not None:
                exporter, self._telemetry = self._telemetry, None
                await exporter.stop()
            if self._hb_task is not None:
                task, self._hb_task = self._hb_task, None
                task.cancel()
                await logx.join_task(task, name="statebus-repl-hb")
            if self._replica_link is not None:
                await self._replica_link.stop()
                self._replica_link = None
            if graceful:
                # GOAWAY before closing: clients fail over to the next endpoint
                # immediately instead of waiting out call timeouts; an attached
                # replica treats it as primary-dead and promotes NOW.  Direct
                # transport writes (not the coalescer): the transport flushes
                # buffered bytes before FIN on close.
                goaway = _encode([0, "goaway"])
                for w in list(self._writers):
                    try:
                        w.write(goaway)
                    except (ConnectionError, OSError, RuntimeError):
                        pass  # peer already gone
            if self._server:
                self._server.close()
            # Close client writers BEFORE wait_closed: Python 3.12's
            # Server.wait_closed() waits for connection handlers to finish, and
            # handlers block reading from clients that never hang up.
            for w in list(self._writers):
                w.close()
            if self._server:
                await self._server.wait_closed()
                self._server = None
            if self._aof:
                # SIGTERM-path durability: flush AND fsync before exit so a
                # graceful shutdown never loses the tail to the page cache
                self._aof.flush()
                os.fsync(self._aof.fileno())
                self._aof.close()
                self._aof = None

    async def crash(self) -> None:
        """Fault-injection helper (infra/chaos.py): die like a SIGKILLed
        process — no GOAWAY, no graceful drain.  Peers see a bare EOF, and
        any replication frames still in the write coalescers are lost
        (exactly the async-mode loss window)."""
        await self.stop(graceful=False)

    async def _replay_aof(self) -> None:
        if not os.path.exists(self.aof_path):
            return
        n = 0
        raw = await asyncio.to_thread(_read_bytes, self.aof_path)
        unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
        unpacker.feed(raw)
        good = 0  # byte offset of the last COMPLETE, well-formed record
        corrupt = False
        while True:
            try:
                entry = unpacker.unpack()
            except msgpack.OutOfData:
                break  # clean EOF, or a truncated final record (crash mid-write)
            except Exception:  # noqa: BLE001 - garbage bytes mid-stream
                corrupt = True
                break
            if (not isinstance(entry, (list, tuple)) or not entry
                    or not isinstance(entry[0], str)):
                corrupt = True  # decoded, but not a record — trailing garbage
                break
            good = unpacker.tell()
            op, args = entry[0], entry[1:]
            if op == "repl_meta":
                self.repl.epoch = int((args[0] or {}).get("epoch", self.repl.epoch))
                continue
            if op == "repl_snapshot":
                await self.kv.load_snapshot(args[1])
                self.repl.offset = int(args[0])
                n += 1
                continue
            try:
                await getattr(self.kv, op)(*args)
                n += 1
            except Exception:
                logx.warn("aof replay skipped bad entry", op=op)
            # logged data records count toward the replication offset even
            # when the replay apply fails — replicas numbered them too
            self.repl.offset += 1
        if good < len(raw):
            # crash mid-write: recover to the last complete record instead
            # of raising, and truncate so appends continue from a clean tail
            logx.warn("aof tail truncated/corrupt; recovering",
                      path=self.aof_path, dropped_bytes=len(raw) - good,
                      garbage=corrupt)
            await asyncio.to_thread(_truncate_file, self.aof_path, good)
        logx.info("aof replayed", entries=n, offset=self.repl.offset,
                  epoch=self.repl.epoch)

    def _append_aof(self, rec: bytes) -> None:
        if self._aof is not None:
            self._aof.write(rec)
            # flush before the op is acked: process-crash durability (an
            # fsync interval below bounds power-loss exposure)
            self._aof.flush()
            now = time.monotonic()
            if now - self._last_fsync > 0.2:
                os.fsync(self._aof.fileno())
                self._last_fsync = now

    def _commit_record(self, op: str, args: tuple) -> int:
        """Durably log one committed mutation and ship it to replicas.

        One msgpack record serves both the AOF and the replication stream;
        returns the record's replication offset (sync-mode commits wait on
        it before acking the client)."""
        rec = msgpack.packb([op, *args], use_bin_type=True)
        self._append_aof(rec)
        return self.repl.advance(rec)

    # -- replication role management ------------------------------------
    def _persist_epoch(self) -> None:
        if self._aof is not None:
            self._aof.write(msgpack.packb(
                ["repl_meta", {"epoch": self.repl.epoch}], use_bin_type=True))
            self._aof.flush()
            os.fsync(self._aof.fileno())

    async def _start_link(self, primary_url: str) -> None:
        host, port = parse_endpoint(primary_url)
        self._replica_link = ReplicaLink(
            self, host, port, replica_id=f"{self.host}:{self.port}",
            auto_promote=self.auto_promote,
            heartbeat_timeout_s=self.heartbeat_timeout_s)
        await self._replica_link.start()

    async def _probe_peers(self) -> None:
        from .replication import probe_role

        for ep in self.peers:
            host, port = parse_endpoint(ep)
            if (host, port) == (self.host, self.port):
                continue
            doc = await probe_role(host, port, timeout_s=1.0)
            if (doc and doc.get("role") == "primary"
                    and int(doc.get("epoch", 0)) > self.repl.epoch):
                logx.warn("peer primary holds a higher epoch; demoting self",
                          peer=f"{host}:{port}", peer_epoch=doc.get("epoch"),
                          epoch=self.repl.epoch)
                await self.demote(f"statebus://{host}:{port}", reason="peer-epoch")
                return

    async def promote(self, *, reason: str = "admin") -> dict:
        """Replica → primary (admin ``promote`` frame, or automatic takeover
        on primary-dead).  Bumps + persists the epoch so promotion is
        exclusive: a returning old primary sees the higher epoch and
        demotes itself."""
        async with self._role_lock:
            if self.role != "primary":
                link, self._replica_link = self._replica_link, None
                self.role = "primary"
                self.replica_of = ""
                self.repl.epoch += 1
                self._persist_epoch()
                self.metrics.statebus_promotions.inc(reason=reason)
                logx.info("statebus PROMOTED to primary", host=self.host,
                          port=self.port, reason=reason, epoch=self.repl.epoch,
                          offset=self.repl.offset)
                if link is not None:
                    await link.stop()
            return {"role": self.role, "epoch": self.repl.epoch,
                    "offset": self.repl.offset}

    async def demote(self, primary_url: str, *, reason: str = "admin") -> dict:
        """Primary → replica of ``primary_url`` (startup peer probe, or an
        admin demotion).  Ordinary clients get a GOAWAY so they re-walk the
        replica set to the real primary."""
        async with self._role_lock:
            if self._replica_link is not None:
                await self._replica_link.stop()
                self._replica_link = None
            self.role = "replica"
            self.replica_of = primary_url
            self.repl.fail_waiters()
            for w in list(self.repl.sessions):
                self.repl.detach(w)
            goaway = _encode([0, "goaway"])
            for w in list(self._writers):
                try:
                    w.write(goaway)
                except (ConnectionError, OSError, RuntimeError):
                    pass  # peer already gone
            await self._start_link(primary_url)
            logx.info("statebus demoted to replica", primary=primary_url,
                      reason=reason, epoch=self.repl.epoch)
            return {"role": self.role, "epoch": self.repl.epoch,
                    "offset": self.repl.offset}

    async def adopt_epoch(self, epoch: int) -> None:
        """Replica adopting its primary's epoch at incremental handshake."""
        if epoch != self.repl.epoch:
            self.repl.epoch = int(epoch)
            self._persist_epoch()

    async def apply_replicated(self, rec: bytes, offset: int) -> None:
        """Apply one primary record on a replica (ReplicaLink pump)."""
        if self.role != "replica" or offset <= self.repl.offset:
            return  # stale link after promotion, or an overlap duplicate
        entry = unpack_record(rec)
        op, args = entry[0], entry[1:]
        try:
            await getattr(self.kv, op)(*args)
        except Exception:
            logx.warn("replicated record failed to apply", op=op)
        self._append_aof(rec)
        self.repl.offset = int(offset)
        self.repl.bytes_total += len(rec)
        # keep our own backlog current: after promotion, OTHER replicas
        # (including the returning old primary) catch up incrementally
        self.repl.backlog.append((int(offset), rec, self.repl.bytes_total))

    async def load_replicated_snapshot(self, epoch: int, offset: int, blob: bytes) -> None:
        """Re-seed a replica whose history diverged / fell past the backlog."""
        await self.kv.load_snapshot(blob)
        self.repl.epoch = int(epoch)
        self.repl.offset = int(offset)
        self.repl.bytes_total = 0
        self.repl.backlog.clear()
        if self._aof is not None:
            await asyncio.to_thread(self._rewrite_aof_snapshot, int(offset), blob)
        logx.info("replica re-seeded from snapshot", epoch=epoch, offset=offset)

    def _rewrite_aof_snapshot(self, offset: int, blob: bytes) -> None:
        """Sync AOF rewrite after a snapshot load (via asyncio.to_thread):
        the old log described a different history and must not replay."""
        self._aof.truncate(0)
        self._aof.write(msgpack.packb(
            ["repl_meta", {"epoch": self.repl.epoch}], use_bin_type=True))
        self._aof.write(msgpack.packb(
            ["repl_snapshot", offset, blob], use_bin_type=True))
        self._aof.flush()
        os.fsync(self._aof.fileno())

    async def _hb_loop(self) -> None:
        """Primary liveness beacon: replicas promote when it goes quiet.

        The same tick also guards the OTHER split-brain direction: a primary
        whose replicas all detached may have been spuriously failed over (a
        GC pause or event-loop stall reads as primary-dead to the replica,
        which promotes).  With a configured peer set, such a primary probes
        its peers every ``heartbeat_timeout_s`` and demotes itself to a live
        higher-epoch primary — the runtime extension of the startup probe,
        so exclusive promotion holds without waiting for a restart."""
        while True:
            await asyncio.sleep(self.heartbeat_interval_s)
            if self.role != "primary":
                continue
            if self.repl.sessions:
                frame = _encode([0, "repl_hb", self.repl.epoch, self.repl.offset])
                for w, sess in list(self.repl.sessions.items()):
                    try:
                        sess.fw.send(frame)
                    except ConnectionError:
                        self.repl.detach(w)
            elif self.peers:
                now = time.monotonic()
                if now - self._last_peer_probe >= self.heartbeat_timeout_s:
                    self._last_peer_probe = now
                    await self._probe_peers()

    def _telemetry_health(self) -> dict:
        """Beacon fields for the fleet view: replication role/epoch/offset
        plus worst attached-replica lag (primary) or link lag (replica)."""
        doc = {
            "role": f"statebus-{self.role}",
            "partition": self.partition,
            "endpoint": f"{self.host}:{self.port}",
            "epoch": self.repl.epoch,
            "offset": self.repl.offset,
            "sync": self.sync_replication,
            "replicas": len(self.repl.sessions),
        }
        link = self._replica_link
        if link is not None:
            doc["lag_ops"] = max(0, link.primary_offset - self.repl.offset)
        elif self.repl.sessions:
            lags = [r.get("lag_ops", 0) for r in self.repl.status()["replicas"]]
            doc["lag_ops"] = max(lags) if lags else 0
        return doc

    def _role_doc(self) -> dict:
        doc = {
            "role": self.role,
            "epoch": self.repl.epoch,
            "offset": self.repl.offset,
            "sync": self.sync_replication,
            "primary": self.replica_of,
            "endpoint": f"{self.host}:{self.port}",
            "replicas": self.repl.status()["replicas"],
        }
        link = self._replica_link
        if link is not None:
            doc["link_connected"] = link.connected.is_set()
            doc["primary_offset"] = link.primary_offset
            doc["lag_ops"] = max(0, link.primary_offset - self.repl.offset)
        return doc

    # -- connection handling -------------------------------------------
    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        fw = _FrameWriter(writer, self.metrics)
        self._fws[writer] = fw
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                # inline dispatch (no per-frame task): KV ops are pure memory
                # and replies are buffered, so a frame costs no task churn
                # and a connection's ops apply in arrival order
                await self._dispatch(frame, writer)
        finally:
            self._writers.discard(writer)
            self._fws.pop(writer, None)
            await fw.close()
            self.repl.detach(writer)
            if not self.repl.sessions:
                # no replica left to ack: release sync-mode commits now
                # instead of making each wait out its timeout
                self.repl.fail_waiters()
            dead = [sid for sid, (w, _, _) in self._subs.items() if w is writer]
            for sid in dead:
                del self._subs[sid]
            writer.close()

    async def _send(self, writer: asyncio.StreamWriter, obj: list) -> None:
        fw = self._fws.get(writer)
        if fw is None:
            return
        try:
            fw.send(_encode(obj))
        except ConnectionError:
            pass  # peer mid-teardown; its handler cleans up

    async def _dispatch(self, frame: list, writer: asyncio.StreamWriter) -> None:
        req_id, op, *args = frame
        try:
            if req_id == 0:
                # client→server notification (no reply): replica acks
                if op == "repl_ack" and args:
                    self.repl.on_ack(writer, int(args[0]))
                return
            if op in _KV_OPS:
                if _KV_OPS[op] and self.role != "primary":
                    await self._send(writer, [
                        req_id, "err",
                        f"READONLY replica of {self.replica_of or 'unknown'}"])
                    return
                t0 = time.perf_counter()
                result = await getattr(self.kv, op)(*args)
                offset = self._commit_record(op, tuple(args)) if _KV_OPS[op] else 0
                self.metrics.statebus_op_seconds.observe(
                    time.perf_counter() - t0, op=op
                )
                if offset and self.sync_replication and self.repl.sessions:
                    await self.repl.wait_synced(offset)
                await self._send(writer, [req_id, "ok", _plain(result)])
            elif op == "pipe":
                # one wire frame = one atomic multi-op batch (the whole point
                # of the pipeline layer: N mutations, ONE round trip) — and
                # therefore the atomic REPLICATION unit: the batch ships to
                # replicas as a single pipe_execute record
                if self.role != "primary":
                    await self._send(writer, [
                        req_id, "err",
                        f"READONLY replica of {self.replica_of or 'unknown'}"])
                    return
                watches, ops = args
                t0 = time.perf_counter()
                ok, versions = await self.kv.pipe_execute(watches, ops)
                offset = self._commit_record("pipe_execute", (watches, ops))
                self.metrics.statebus_op_seconds.observe(
                    time.perf_counter() - t0, op="pipe"
                )
                self.metrics.kv_pipeline_size.observe(float(len(ops)))
                if self.sync_replication and self.repl.sessions:
                    await self.repl.wait_synced(offset)
                await self._send(writer, [req_id, "ok", [ok, versions]])
            elif op == "metrics":
                await self._send(writer, [req_id, "ok", self.metrics.render()])
            elif op == "role":
                await self._send(writer, [req_id, "ok", self._role_doc()])
            elif op == "promote":
                await self._send(writer, [req_id, "ok",
                                          await self.promote(reason="admin")])
            elif op == "repl_sync":
                await self._handle_repl_sync(req_id, writer, *args)
            elif op == "sub":
                pattern, queue = args
                sid = next(self._sid)
                self._subs[sid] = (writer, pattern, queue or None)
                await self._send(writer, [req_id, "ok", sid])
            elif op == "unsub":
                self._subs.pop(args[0], None)
                await self._send(writer, [req_id, "ok", True])
            elif op == "pub":
                subject, packet_bytes = args
                await self._route(subject, packet_bytes)
                await self._send(writer, [req_id, "ok", True])
            else:
                await self._send(writer, [req_id, "err", f"unknown op {op!r}"])
        except Exception as e:  # noqa: BLE001
            try:
                await self._send(writer, [req_id, "err", str(e)])
            except Exception as send_err:  # noqa: BLE001 - peer already gone
                logx.debug("could not deliver error reply", err=str(send_err))

    async def _route(self, subject: str, packet_bytes: bytes) -> None:
        from ..protocol import subjects as subj

        if subj.is_durable_subject(subject):
            try:
                pkt = BusPacket.from_wire(packet_bytes)
                mid = compute_msg_id(subject, pkt)
            except Exception:
                mid = ""
            if mid:
                now = time.monotonic()
                if len(self._dedup) > 16384:
                    for k in list(itertools.islice(self._dedup, 8192)):
                        del self._dedup[k]
                seen = self._dedup.get(mid)
                if seen is not None and now - seen < DEDUP_WINDOW_S:
                    return
                self._dedup[mid] = now
        plain: list[tuple[int, asyncio.StreamWriter]] = []
        groups: dict[tuple[str, str], list[tuple[int, asyncio.StreamWriter]]] = {}
        for sid, (w, pattern, queue) in self._subs.items():
            if not subject_match(pattern, subject):
                continue
            if queue is None:
                plain.append((sid, w))
            else:
                groups.setdefault((pattern, queue), []).append((sid, w))
        for key, members in groups.items():
            members.sort()
            i = self._rr.get(key, 0)
            plain.append(members[i % len(members)])
            self._rr[key] = i + 1
        for sid, w in plain:
            try:
                await self._send(w, [0, "msg", sid, subject, packet_bytes])
            except Exception as e:  # noqa: BLE001 - one dead peer must not stop fanout
                logx.debug("dropping subscriber mid-fanout", sid=sid, err=str(e))

    async def _handle_repl_sync(self, req_id: int, writer: asyncio.StreamWriter,
                                replica_id: str, epoch: int, offset: int) -> None:
        """Replica attach handshake: incremental catch-up from the record
        backlog when the replica shares our history (same epoch, offset
        within the backlog window), full snapshot re-seed otherwise."""
        if self.role != "primary":
            await self._send(writer, [
                req_id, "err", f"not primary (replica of {self.replica_of})"])
            return
        fw = self._fws.get(writer)
        if fw is None:
            return
        epoch, offset = int(epoch), int(offset)
        if (epoch == self.repl.epoch and offset <= self.repl.offset
                and self.repl.covers(offset)):
            self.repl.attach(writer, replica_id, fw, offset)
            await self._send(writer, [
                req_id, "ok", ["incremental", self.repl.epoch, self.repl.offset]])
            for rec_frame in self.repl.records_after(offset):
                fw.send(rec_frame)
            self.metrics.statebus_repl_syncs.inc(mode="incremental")
            mode = "incremental"
        else:
            # snapshot + offset are captured in one event-loop tick (MemoryKV
            # never holds its lock across an await), so no commit can land
            # between the blob and the offset it claims to represent
            blob = await self.kv.snapshot()
            snap_offset = self.repl.offset
            # acked starts at 0: the replica only counts as caught up (for
            # sync-mode waits) once it confirms the snapshot load itself
            self.repl.attach(writer, replica_id, fw, 0)
            await self._send(writer, [
                req_id, "ok", ["snapshot", self.repl.epoch, snap_offset]])
            fw.send(_encode([0, "repl_snap", self.repl.epoch, snap_offset, blob]))
            self.metrics.statebus_repl_syncs.inc(mode="snapshot")
            mode = "snapshot"
        logx.info("replica attached", replica=replica_id, mode=mode,
                  replica_offset=offset, primary_offset=self.repl.offset)


class _NotPrimary(ConnectionError):
    """Dialed endpoint is a replica; the failover walk tries the next one."""


#: ops never retransmitted across a reconnect: sub/unsub would duplicate or
#: kill the wrong sid (the registry re-issues subs itself), ping/role are
#: liveness probes whose answer is stale by definition after a failover
_NO_RETRANSMIT = frozenset(("sub", "unsub", "ping", "role"))


class StateBusConn:
    """Shared TCP connection: request/response + push routing + failover.

    Auto-reconnects with jittered exponential backoff when the connection
    drops (reference NATS behavior: infinite reconnect, ``nats.go:59``),
    walking the partition's ``|``-separated replica set until it finds the
    current PRIMARY (each dial is role-checked when the set has more than
    one endpoint).  Unacked in-flight request frames are retransmitted on
    the fresh connection — a pipelined commit caught mid-failover is
    re-applied (version watches make the retry conflict, not double-apply,
    when the old primary had committed and replicated it) instead of being
    silently dropped.  Subscriptions are re-issued server-side on every
    reconnect; a server GOAWAY (graceful shutdown/demotion) fails over
    immediately, and an optional ping loop turns black-holed connections
    (host died without FIN/RST) into failovers too.
    """

    def __init__(self, host: str, port: int, *, reconnect: bool = True,
                 max_backoff_s: float = 2.0,
                 endpoints: Optional[list[tuple[str, int]]] = None,
                 ping_interval_s: float = 0.0,
                 verify_primary: Optional[bool] = None) -> None:
        self.endpoints = [tuple(e) for e in (endpoints or [(host, port)])]
        self._ep_i = 0
        self.host, self.port = self.endpoints[0]
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._req_id = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        # unacked in-flight frames, replayed after failover: req_id → (op, frame)
        self._inflight: dict[int, tuple[str, bytes]] = {}
        self._handlers: dict[int, Any] = {}  # server sid → async handler(subject, bytes)
        self._reader_task: Optional[asyncio.Task] = None
        self._fw: Optional[_FrameWriter] = None
        self._closed = False
        self._reconnect = reconnect
        self._max_backoff_s = max_backoff_s
        self._connected = asyncio.Event()
        self._reconnect_task: Optional[asyncio.Task] = None
        # client-side subscription registry (survives reconnects):
        # local id → {pattern, queue, handler, sid}
        self._local_sid = itertools.count(1)
        self._subs: dict[int, dict] = {}
        self.reconnect_count = 0
        # bound via StateBusKV.bind_metrics: cordum_statebus_reconnects_total
        self.metrics: Any = None
        self._loss_reason = "connection_lost"
        # a single-endpoint conn skips the role round trip (standalone
        # servers are always primary); replica sets must verify, or a write
        # could land on a READONLY replica mid-promotion
        self._verify_primary = (len(self.endpoints) > 1
                                if verify_primary is None else verify_primary)
        self._ping_interval_s = ping_interval_s
        self._ping_task: Optional[asyncio.Task] = None
        # connection epoch: bumped on every successful dial; server sids are
        # only meaningful within the epoch that created them (a restarted
        # server reuses low sids, so a stale unsub could kill the wrong sub)
        self._epoch = 0

    async def connect(self) -> None:
        await self._connect_cycle()
        self._connected.set()
        if self._ping_interval_s > 0:
            self._ping_task = asyncio.ensure_future(self._ping_loop())

    async def _dial(self) -> None:
        if self._reader_task is not None and not self._reader_task.done():
            # a reader for a dead/obsolete connection must not linger (its
            # tail would spawn a second reconnect loop → duplicate dials)
            self._reader_task.cancel()
        if self._fw is not None:
            await self._fw.close()
        if self._writer is not None:
            self._writer.close()
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._fw = _FrameWriter(self._writer)
        self._epoch += 1
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _connect_cycle(self) -> None:
        """One walk over the replica set; connects to the first PRIMARY.

        Raises the last failure when no endpoint works this cycle (a
        not-yet-promoted replica counts as a failure — the reconnect loop
        keeps cycling until promotion flips one to primary)."""
        last: Optional[Exception] = None
        for _ in range(len(self.endpoints)):
            self.host, self.port = self.endpoints[self._ep_i]
            try:
                await self._dial()
                if self._verify_primary:
                    doc = await self._call_now("role", timeout_s=3.0)
                    if not isinstance(doc, dict) or doc.get("role") != "primary":
                        raise _NotPrimary(
                            f"{self.host}:{self.port} is not primary")
                return
            except (OSError, ConnectionError) as e:
                last = e
                self._ep_i = (self._ep_i + 1) % len(self.endpoints)
        raise last if last is not None else ConnectionError("no statebus endpoints")

    async def close(self) -> None:
        self._closed = True
        self._connected.set()  # release any call() waiting on reconnect
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._ping_task:
            self._ping_task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._fw is not None:
            await self._fw.close()
        if self._writer:
            self._writer.close()
        # deliberate close: resolve pending calls quietly (no orphan-task spam)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_result(None)
        self._pending.clear()
        self._inflight.clear()

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await _read_frame(self._reader)
                if frame is None:
                    break
                if frame[0] == 0:
                    kind = frame[1] if len(frame) > 1 else ""
                    if kind == "msg":
                        _, _, sid, subject, packet_bytes = frame
                        handler = self._handlers.get(sid)
                        if handler is not None:
                            asyncio.ensure_future(handler(subject, packet_bytes))
                        continue
                    if kind == "goaway":
                        # graceful server shutdown / demotion: fail over NOW
                        self._loss_reason = "goaway"
                        break
                    continue  # unknown push (repl traffic etc.) — not ours
                req_id, status, result = frame
                fut = self._pending.pop(req_id, None)
                self._inflight.pop(req_id, None)
                if fut is not None and not fut.done():
                    if status == "ok":
                        fut.set_result(result)
                    else:
                        fut.set_exception(RuntimeError(f"statebus: {result}"))
        except asyncio.CancelledError:
            raise  # deliberate teardown (close/_dial); no recovery tail
        except Exception:
            # ANY reader failure (OSError ETIMEDOUT, corrupt frame, decode
            # error) must fall into the recovery tail below — otherwise the
            # client wedges with _connected still set and no reconnect
            logx.warn("statebus read loop failed; treating as connection loss")
        # connection lost: keep in-flight calls parked for retransmission
        # (each still bounded by its own call timeout); only non-replayable
        # ops (sub/unsub/ping/role) fail immediately.  Then — unless
        # deliberately closed — start the failover walk.
        self._connected.clear()
        for req_id, (op, _) in list(self._inflight.items()):
            if op in _NO_RETRANSMIT:
                fut = self._pending.pop(req_id, None)
                self._inflight.pop(req_id, None)
                if fut is not None and not fut.done():
                    fut.set_exception(ConnectionError("statebus connection lost"))
        if not self._closed and self._reconnect:
            t = self._reconnect_task
            if t is None or t.done():  # never two concurrent reconnect loops
                logx.warn("statebus connection lost; reconnecting",
                          host=self.host, port=self.port,
                          reason=self._loss_reason)
                self._reconnect_task = asyncio.ensure_future(self._reconnect_loop())
        elif not self._closed:
            # no reconnect: surface the loss to in-flight callers directly
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("statebus connection lost"))
            self._pending.clear()
            self._inflight.clear()

    async def _reconnect_loop(self) -> None:
        backoff = 0.05
        reason = self._loss_reason
        while not self._closed:
            try:
                await self._connect_cycle()
                await self._resubscribe()
                self._retransmit_inflight()
                self.reconnect_count += 1
                if self.metrics is not None:
                    self.metrics.statebus_reconnects.inc(reason=reason)
                self._loss_reason = "connection_lost"
                logx.info("statebus reconnected", host=self.host, port=self.port,
                          subs=len(self._subs), inflight=len(self._inflight),
                          reason=reason)
                self._connected.set()
                return
            except (OSError, ConnectionError):
                # every endpoint refused / not primary / died mid-resubscribe
                # — this same loop retries the whole walk (the dead reader
                # task is cancelled by the next _dial, so no second loop
                # spawns).  Jittered exponential backoff: a fleet of clients
                # failing over together must not dial in lockstep.
                self._connected.clear()
                await asyncio.sleep(backoff * (0.5 + random.random() / 2))
                backoff = min(backoff * 2, self._max_backoff_s)

    async def _resubscribe(self) -> None:
        """Re-issue every registered subscription on the fresh connection."""
        self._handlers.clear()
        # snapshot: _connected is already set, so a concurrent subscribe()
        # may insert into _subs while we await — iterating the live dict
        # would raise and kill the reconnect task
        for entry in list(self._subs.values()):
            sid = await self._call_now("sub", entry["pattern"], entry["queue"] or "")
            entry["sid"] = sid
            entry["epoch"] = self._epoch
            self._handlers[sid] = entry["handler"]

    def _retransmit_inflight(self) -> None:
        """Replay unacked request frames on the fresh connection, in
        original send order.  Version-watched commits that DID apply before
        the failover conflict instead of double-applying; callers' conflict
        paths already handle that (at-least-once, like bus redelivery)."""
        for req_id in sorted(self._inflight):
            _, frame = self._inflight[req_id]
            self._fw.send(frame)

    async def _ping_loop(self) -> None:
        """Liveness probe: a black-holed connection (peer died without
        FIN/RST, or a proxy swallowing traffic) never EOFs the reader —
        a failed ping forces the transport closed so the normal recovery
        tail runs the failover walk."""
        while not self._closed:
            await asyncio.sleep(self._ping_interval_s)
            if self._closed or not self._connected.is_set():
                continue
            try:
                await self._call_now("ping",
                                     timeout_s=max(1.0, self._ping_interval_s))
            except ConnectionError:
                if self._connected.is_set() and self._writer is not None:
                    self._loss_reason = "ping_timeout"
                    logx.warn("statebus ping timed out; forcing failover",
                              host=self.host, port=self.port)
                    self._writer.close()

    # -- subscriptions (registry survives reconnects) -------------------
    async def subscribe(self, pattern: str, queue: str, handler) -> int:
        local = next(self._local_sid)
        # register in _subs only AFTER the server ack: a subscribe that rides
        # a reconnect must not ALSO be issued by _resubscribe (double sid →
        # every message delivered twice)
        sid = await self.call("sub", pattern, queue or "")
        self._subs[local] = {"pattern": pattern, "queue": queue,
                             "handler": handler, "sid": sid, "epoch": self._epoch}
        self._handlers[sid] = handler
        return local

    async def unsubscribe(self, local: int) -> None:
        entry = self._subs.pop(local, None)
        if entry is None:
            return
        sid = entry.get("sid")
        if sid is not None:
            self._handlers.pop(sid, None)
            if entry.get("epoch") != self._epoch or not self._connected.is_set():
                # sid belongs to a dead connection (a restarted server reuses
                # sids, so sending it could kill a live sub), or we're
                # disconnected (server already dropped the sub; the entry is
                # out of _subs so _resubscribe won't revive it)
                return
            try:
                # _call_now (not call): must never ride a reconnect, where the
                # epoch would have moved on under us
                await self._call_now("unsub", sid, timeout_s=2.0)
            except (ConnectionError, RuntimeError):
                pass  # server side cleans up on disconnect anyway

    # -- calls ----------------------------------------------------------
    async def call(self, op: str, *args: Any, timeout_s: float = 15.0) -> Any:
        if self._closed:
            raise ConnectionError("statebus connection closed")
        remaining = timeout_s
        if not self._connected.is_set():
            # disconnected: wait (bounded) for the reconnect loop to win;
            # the wait spends the caller's budget — total latency stays
            # bounded by timeout_s, not 2x
            t0 = time.monotonic()
            try:
                await asyncio.wait_for(self._connected.wait(), timeout_s)
            except asyncio.TimeoutError:
                raise ConnectionError(
                    f"statebus call {op!r}: not connected after {timeout_s}s"
                )
            if self._closed:
                raise ConnectionError("statebus connection closed")
            remaining = max(0.05, timeout_s - (time.monotonic() - t0))
        return await self._call_now(op, *args, timeout_s=remaining)

    async def _call_now(self, op: str, *args: Any, timeout_s: float = 15.0) -> Any:
        req_id = next(self._req_id)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        frame = _encode([req_id, op, *args])
        self._pending[req_id] = fut
        self._inflight[req_id] = (op, frame)
        try:
            # coalesced write: the frame enqueues synchronously and rides the
            # connection's next batched flush — concurrent in-flight calls
            # (engine submit_concurrency) share one socket write per tick
            self._fw.send(frame)
        except (AttributeError, ConnectionError, OSError) as e:
            if (op in _NO_RETRANSMIT or self._closed or not self._reconnect):
                self._pending.pop(req_id, None)
                self._inflight.pop(req_id, None)
                raise ConnectionError(f"statebus call {op!r} failed: {e}")
            # connection mid-teardown: leave the frame parked — the failover
            # walk retransmits it, and the caller's timeout still bounds it
        try:
            # bounded wait: a half-open TCP connection (host died without
            # FIN/RST) must surface as an error, not wedge the service
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            self._inflight.pop(req_id, None)
            raise ConnectionError(f"statebus call {op!r} timed out after {timeout_s}s")


def _maybe_bytes(v: Any) -> Any:
    return v


class StateBusKV(KV):
    """KV interface over a statebus connection."""

    def __init__(self, conn: StateBusConn) -> None:
        self.conn = conn

    def bind_metrics(self, metrics: Any) -> None:
        super().bind_metrics(metrics)
        # the connection emits cordum_statebus_reconnects_total{reason}
        self.conn.metrics = metrics

    async def close(self) -> None:
        await self.conn.close()


def _make_kv_method(op: str) -> Any:
    import inspect

    sig = inspect.signature(getattr(MemoryKV, op))

    async def method(self: "StateBusKV", *args: Any, **kwargs: Any) -> Any:
        if kwargs:  # server applies ops positionally: bind kwargs through
            bound = sig.bind(self, *args, **kwargs)
            bound.apply_defaults()
            args = bound.args[1:]
            if bound.kwargs:
                args = (*args, *bound.kwargs.values())
        self._observe_op(op)
        result = await self.conn.call(op, *args)
        if op == "smembers" and isinstance(result, list):
            return set(result)
        if op == "hgetall" and isinstance(result, dict):
            return {k if isinstance(k, str) else k.decode(): v for k, v in result.items()}
        if op == "watch_read" and isinstance(result, (list, tuple)):
            ver, h = result
            return ver, {k if isinstance(k, str) else k.decode(): v for k, v in (h or {}).items()}
        return result

    method.__name__ = op
    return method


for _op in _KV_OPS:
    if _op != "commit":
        setattr(StateBusKV, _op, _make_kv_method(_op))


async def _commit(self, watches: dict[str, int], ops: list[tuple]) -> bool:
    self._observe_op("commit")
    return await self.conn.call("commit", watches, [list(o) for o in ops])


async def _pipe_execute(
    self, watches: dict[str, int], ops: list[tuple]
) -> tuple[bool, dict[str, int]]:
    """One PIPE wire frame: the whole batch rides a single request and gets
    a single ``[ok, new_versions]`` reply — N ops, one TCP round trip."""
    self._observe_op("pipe", pipeline_size=len(ops))
    ok, versions = await self.conn.call("pipe", watches, [list(o) for o in ops])
    return bool(ok), {
        k if isinstance(k, str) else k.decode(): v for k, v in (versions or {}).items()
    }


async def _server_metrics(self) -> str:
    """Server-side metrics exposition (cordum_statebus_op_seconds etc.)."""
    return str(await self.conn.call("metrics"))


StateBusKV.commit = _commit  # type: ignore[assignment]
StateBusKV.pipe_execute = _pipe_execute  # type: ignore[assignment]
StateBusKV.server_metrics = _server_metrics  # type: ignore[attr-defined]


class StateBusBus(Bus):
    """Bus interface over a statebus connection, with client-side RetryAfter
    redelivery (at-least-once on durable subjects)."""

    def __init__(self, conn: StateBusConn) -> None:
        self.conn = conn

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        await self.conn.call("pub", subject, pkt.to_wire())

    async def subscribe(self, pattern: str, handler, *, queue: Optional[str] = None) -> Subscription:
        from ..protocol import subjects as subj

        async def deliver(subject: str, packet_bytes: bytes) -> None:
            # iterative redelivery (NOT recursive): a hot NAK cycle must not
            # grow the stack across MAX_REDELIVERIES, and the requested delay
            # is capped so one bad handler can't park a delivery task forever
            attempt = 1
            while True:
                try:
                    pkt = BusPacket.from_wire(packet_bytes)
                    # delivery-local: handlers back off exponentially on it
                    # (tenant-concurrency NAKs) instead of a fixed cadence
                    pkt.redelivery_count = attempt - 1
                    await handler(subject, pkt)
                    return
                except RetryAfter as ra:
                    if not subj.is_durable_subject(subject) or attempt >= MAX_REDELIVERIES:
                        logx.warn("dropping message after retries", subject=subject)
                        return
                    attempt += 1
                    await asyncio.sleep(min(max(ra.delay_s, 0.0), MAX_NAK_DELAY_S))
                except Exception:
                    logx.error("bus handler error", subject=subject)
                    return

        local = await self.conn.subscribe(pattern, queue or "", deliver)

        def _unsub() -> None:
            asyncio.ensure_future(self.conn.unsubscribe(local))

        return Subscription(_unsub)

    async def ping(self) -> bool:
        try:
            return bool(await self.conn.call("ping"))
        except Exception:
            return False


#: liveness-ping cadence for replica-set connections (black-hole detection);
#: single-endpoint connections skip the ping loop entirely
FAILOVER_PING_INTERVAL_S = 5.0


async def connect(url: str = "", *,
                  ping_interval_s: Optional[float] = None,
                  ) -> tuple[StateBusKV, StateBusBus, StateBusConn]:
    """Parse one partition's endpoint(s) (env CORDUM_STATEBUS_URL) and connect.

    ``url`` may be a single ``statebus://host:port`` or a ``|``-separated
    replica set (``statebus://h:7420|statebus://h:7520``, primary listed
    first); the connection walks the set on every connection loss until it
    finds the current primary.
    """
    url = url or os.environ.get("CORDUM_STATEBUS_URL", "statebus://127.0.0.1:7420")
    endpoints = parse_replica_set(url)
    if ping_interval_s is None:
        ping_interval_s = FAILOVER_PING_INTERVAL_S if len(endpoints) > 1 else 0.0
    conn = StateBusConn(*endpoints[0], endpoints=endpoints,
                        ping_interval_s=ping_interval_s)
    await conn.connect()
    return StateBusKV(conn), StateBusBus(conn), conn


# ---------------------------------------------------------------------------
# partitioned statebus: N independent servers, clients route by keyspace
# ---------------------------------------------------------------------------

# Keys whose trailing segment is the routing id: every key of one job (or
# trace) lands on ONE partition, which is what keeps pipelined commits —
# always watched on job:meta:<id> — atomic on a single server.
_ID_ROUTED_PREFIXES = (
    "job:meta:", "job:events:", "job:request:", "job:safety:",
    "job:approval:", "lock:job:", "trace:spans:",
)

# Shared index containers whose members are job ids.  They are mutated
# INSIDE job-routed pipes, so each partition holds the slice for the ids it
# owns: standalone writes route by member, reads fan out and merge.
_MEMBER_ROUTED_EXACT = frozenset(("job:recent", "job:deadline"))
_MEMBER_ROUTED_PREFIXES = ("job:index:", "job:tenant_active:", "trace:")


def _route_key(key: str) -> str:
    for p in _ID_ROUTED_PREFIXES:
        if key.startswith(p):
            return key[len(p):] or key
    # workflow state co-locates on ONE partition: WorkflowStore.put_run is a
    # single pipelined commit over the run blob + shared z-indexes
    # (wf:run:index / wf:run:status:* / wf:run:org_active:*), and a pipe
    # executes on one partition — so the index reads (reconciler status
    # scans, run listings) must route to the same partition the pipe wrote.
    # Workflow traffic is control-plane-light relative to job state, so the
    # lost spread is noise.
    if key.startswith("wf:"):
        return "wf:"
    return key


def _member_routed(key: str) -> bool:
    if key in _MEMBER_ROUTED_EXACT:
        return True
    if key.startswith("trace:spans:"):
        return False  # id-routed (collector span ring buffers + their index)
    return key.startswith(_MEMBER_ROUTED_PREFIXES)


class PartitionedKV(KV):
    """KV facade over N statebus partitions (docs/PROTOCOL.md §Partitioning).

    Point ops route by :func:`_route_key` hash; member-routed index
    containers write to ``partition_of(member)`` and merge reads across
    every partition (union / sum; cross-partition ordering of merged
    listings is approximate — they are observability surfaces).  A pipeline
    executes atomically on the partition of its first watched key, which by
    construction is the job's home partition for every control-plane pipe.
    """

    def __new__(cls, parts: list[KV]) -> Any:
        parts = list(parts)
        if len(parts) == 1:
            # identity dispatch chosen at construction: an unsharded store
            # IS its single backend — no routing layer, no per-op branching
            # on the 1×1 hot path (ISSUE 6)
            return parts[0]
        return super().__new__(cls)

    def __init__(self, parts: list[KV]) -> None:
        self.parts = list(parts)
        self.n = len(self.parts)

    def bind_metrics(self, metrics: Any) -> None:
        self.metrics = metrics
        for p in self.parts:
            p.bind_metrics(metrics)

    def _one(self, key: str) -> KV:
        if self._member_is_global(key):
            return self.parts[0]  # deterministic home for member-routed point ops
        return self.parts[partition_of(_route_key(key), self.n)]

    @staticmethod
    def _member_is_global(key: str) -> bool:
        return _member_routed(key)

    def _by_member(self, member: str) -> KV:
        return self.parts[partition_of(member, self.n)]

    # strings -------------------------------------------------------------
    async def get(self, key):
        return await self._one(key).get(key)

    async def set(self, key, value, ttl_s=None):
        return await self._one(key).set(key, value, ttl_s)

    async def setnx(self, key, value, ttl_s=None):
        return await self._one(key).setnx(key, value, ttl_s)

    async def delete(self, *keys):
        grouped: dict[int, list[str]] = {}
        for k in keys:
            if self._member_is_global(k):
                for i in range(self.n):  # slices live on every partition
                    grouped.setdefault(i, []).append(k)
            else:
                grouped.setdefault(partition_of(_route_key(k), self.n), []).append(k)
        counts = await asyncio.gather(
            *(self.parts[i].delete(*ks) for i, ks in grouped.items())
        )
        return sum(counts)

    async def del_eq(self, key, expect):
        return await self._one(key).del_eq(key, expect)

    async def expire(self, key, ttl_s):
        if self._member_is_global(key):
            oks = await asyncio.gather(*(p.expire(key, ttl_s) for p in self.parts))
            return any(oks)
        return await self._one(key).expire(key, ttl_s)

    async def keys(self, prefix=""):
        lists = await asyncio.gather(*(p.keys(prefix) for p in self.parts))
        return sorted({k for ks in lists for k in ks})

    # hashes --------------------------------------------------------------
    async def hset(self, key, mapping):
        return await self._one(key).hset(key, mapping)

    async def hget(self, key, field):
        return await self._one(key).hget(key, field)

    async def hgetall(self, key):
        return await self._one(key).hgetall(key)

    async def hdel(self, key, *fields):
        return await self._one(key).hdel(key, *fields)

    async def hincrby(self, key, field, amount=1):
        return await self._one(key).hincrby(key, field, amount)

    # sorted sets ---------------------------------------------------------
    async def zadd(self, key, member, score):
        if self._member_is_global(key):
            return await self._by_member(member).zadd(key, member, score)
        return await self._one(key).zadd(key, member, score)

    async def zrem(self, key, *members):
        if self._member_is_global(key):
            grouped: dict[int, list[str]] = {}
            for m in members:
                grouped.setdefault(partition_of(m, self.n), []).append(m)
            counts = await asyncio.gather(
                *(self.parts[i].zrem(key, *ms) for i, ms in grouped.items())
            )
            return sum(counts)
        return await self._one(key).zrem(key, *members)

    async def zrange(self, key, start=0, stop=-1, desc=False):
        if not self._member_is_global(key):
            return await self._one(key).zrange(key, start, stop, desc)
        # merged listing: fetch each partition's slice of the requested
        # range and concatenate (per-partition order exact, cross-partition
        # approximate — observability surfaces only)
        per_stop = -1 if stop == -1 else stop
        lists = await asyncio.gather(
            *(p.zrange(key, 0, per_stop, desc) for p in self.parts)
        )
        merged = [m for ms in lists for m in ms]
        if stop == -1:
            return merged[start:]
        return merged[start: stop + 1]

    async def zrangebyscore(self, key, min_score, max_score, limit=0):
        if not self._member_is_global(key):
            return await self._one(key).zrangebyscore(key, min_score, max_score, limit)
        lists = await asyncio.gather(
            *(p.zrangebyscore(key, min_score, max_score, limit) for p in self.parts)
        )
        merged = [m for ms in lists for m in ms]
        return merged[:limit] if limit else merged

    async def zcard(self, key):
        if not self._member_is_global(key):
            return await self._one(key).zcard(key)
        return sum(await asyncio.gather(*(p.zcard(key) for p in self.parts)))

    async def zscore(self, key, member):
        if self._member_is_global(key):
            return await self._by_member(member).zscore(key, member)
        return await self._one(key).zscore(key, member)

    # lists ---------------------------------------------------------------
    async def rpush(self, key, *values):
        return await self._one(key).rpush(key, *values)

    async def lrange(self, key, start=0, stop=-1):
        return await self._one(key).lrange(key, start, stop)

    async def ltrim(self, key, start, stop):
        return await self._one(key).ltrim(key, start, stop)

    async def llen(self, key):
        return await self._one(key).llen(key)

    # sets ----------------------------------------------------------------
    async def sadd(self, key, *members):
        if self._member_is_global(key):
            grouped: dict[int, list[str]] = {}
            for m in members:
                grouped.setdefault(partition_of(m, self.n), []).append(m)
            counts = await asyncio.gather(
                *(self.parts[i].sadd(key, *ms) for i, ms in grouped.items())
            )
            return sum(counts)
        return await self._one(key).sadd(key, *members)

    async def smembers(self, key):
        if not self._member_is_global(key):
            return await self._one(key).smembers(key)
        sets = await asyncio.gather(*(p.smembers(key) for p in self.parts))
        out: set[str] = set()
        for s in sets:
            out |= s
        return out

    # transactions --------------------------------------------------------
    async def version(self, key):
        return await self._one(key).version(key)

    async def watch_read(self, key):
        return await self._one(key).watch_read(key)

    def pipe_group(self, key: str) -> int:
        """Keys on the same partition may share one grouped pipe commit."""
        if self._member_is_global(key):
            return 0
        return partition_of(_route_key(key), self.n)

    def _pipe_part(self, watches: dict[str, int], ops: list[tuple]) -> KV:
        for key in watches:
            return self._one(key)
        for op in ops:
            if len(op) > 1 and isinstance(op[1], str):
                return self._one(op[1])
        return self.parts[0]

    async def commit(self, watches, ops):
        return await self._pipe_part(watches, ops).commit(watches, ops)

    async def pipe_execute(self, watches, ops):
        return await self._pipe_part(watches, ops).pipe_execute(watches, ops)

    async def ping(self):
        oks = await asyncio.gather(*(p.ping() for p in self.parts))
        return all(oks)

    async def close(self):
        await asyncio.gather(*(p.close() for p in self.parts), return_exceptions=True)


class PartitionedBus(Bus):
    """Bus facade over N statebus partitions.

    A concrete subject always lives on ONE partition (hash of the subject
    string), so queue-group and dedupe semantics stay exact per subject;
    wildcard patterns are subscribed on every partition.  Hashing spreads
    the partitioned lifecycle subjects (``sys.job.submit.<p>`` …) across
    brokers so no single event loop serializes the fleet's messaging.
    """

    def __new__(cls, buses: list[Bus]) -> Any:
        buses = list(buses)
        if len(buses) == 1:
            return buses[0]  # identity dispatch: see PartitionedKV.__new__
        return super().__new__(cls)

    def __init__(self, buses: list[Bus]) -> None:
        self.buses = list(buses)
        self.n = len(self.buses)

    def _bus_for(self, subject: str) -> Bus:
        return self.buses[partition_of(subject, self.n)]

    async def publish(self, subject: str, pkt: BusPacket) -> None:
        await self._bus_for(subject).publish(subject, pkt)

    def has_listener(self, subject: str) -> bool:
        return self._bus_for(subject).has_listener(subject)

    async def subscribe(self, pattern: str, handler, *, queue: Optional[str] = None) -> Subscription:
        if "*" in pattern or ">" in pattern:
            subs = await asyncio.gather(
                *(b.subscribe(pattern, handler, queue=queue) for b in self.buses)
            )

            def _unsub_all() -> None:
                for s in subs:
                    s.unsubscribe()

            return Subscription(_unsub_all)
        return await self._bus_for(pattern).subscribe(pattern, handler, queue=queue)

    async def ping(self) -> bool:
        oks = await asyncio.gather(*(b.ping() for b in self.buses))
        return all(oks)


class ConnGroup:
    """Close-handle over the N connections behind a partitioned client."""

    def __init__(self, conns: list[StateBusConn]) -> None:
        self.conns = list(conns)

    async def close(self) -> None:
        await asyncio.gather(*(c.close() for c in self.conns), return_exceptions=True)


async def connect_partitioned(url: str = "") -> tuple[KV, Bus, ConnGroup]:
    """Connect to one or more statebus partitions.

    ``url`` is a comma-separated list of partitions (env
    ``CORDUM_STATEBUS_URL``); each partition is a single
    ``statebus://host:port`` endpoint or a ``|``-separated replica set that
    the connection fails over across (docs/PROTOCOL.md §Replication).  A
    single partition degrades to the plain unpartitioned client, so every
    service binary can use this entry point.
    """
    url = url or os.environ.get("CORDUM_STATEBUS_URL", "statebus://127.0.0.1:7420")
    endpoints = [u.strip() for u in url.split(",") if u.strip()]
    if len(endpoints) <= 1:
        kv, bus, conn = await connect(endpoints[0] if endpoints else "")
        return kv, bus, ConnGroup([conn])
    kvs: list[KV] = []
    buses: list[Bus] = []
    conns: list[StateBusConn] = []
    for ep in endpoints:
        kv, bus, conn = await connect(ep)
        kvs.append(kv)
        buses.append(bus)
        conns.append(conn)
    return PartitionedKV(kvs), PartitionedBus(buses), ConnGroup(conns)
